"""Pallas TPU kernels behind the accelerated-helper seam (ops/helpers.py).

The TPU analog of the reference's cuDNN helper plugin
(deeplearning4j-cuda-7.5/.../nn/layers/convolution/CudnnConvolutionHelper.java:48
plus the subsampling/BN/LRN helpers, loaded reflectively with silent fallback
at ConvolutionLayer.java:64-70). Kernel families behind the seam:

  - ``conv2d_bias_act``: per-(batch-tile, output-row, kernel-row) grid; each
    step runs ONE MXU matmul [bt*ow, kw*c]x[kw*c, oc] with the bias-add +
    activation fused into the last accumulation — the cuDNN "conv+bias+act"
    fused path. Measured 0.66-0.90x of XLA's native conv on v5e (XLA's
    emitter avoids even the kw-fold row expansion), so enable() registers it
    opt-in only; it stands as the seam's working reference kernel.
  - ``attention``: per-shape autotuned choice among XLA einsum attention,
    the TPU flash-attention kernel under several block configs, and splash
    attention — the long-context winner (2.5-3x XLA at L=8192; sole
    survivor past L~16k where dense cannot compile).
  - ``bn_act_pool``: composite BN+activation+2x2-maxpool with a fused
    2-pass Pallas BACKWARD in two layout-matched variants, autotuned.
  - ``paged_decode_attention``: FlashDecoding-style fused paged-KV
    decode (ISSUE 15) — one pass per (batch row, kv-head) walks the
    slot's scalar-prefetched block table and runs QK^T + online softmax
    + V accumulation page by page, int8 dequant fused in-loop; the
    [B, nb*block, Hkv, Dh] gathered cache is never materialized. Per-
    shape autotuned against the XLA gather path; under a tp mesh it
    grids over the LOCAL Hkv shard (shard_map) so the serving
    collective audit is unchanged.
  - ``lstm_sequence``: RETIRED round 4 (XLA's scan won every probed
    regime — see the tombstone note at the section below); the seam and
    the autotune machinery remain.

Training works unchanged: custom kernels are wrapped in ``jax.custom_vjp``
(either with a hand-written fused backward validated against autodiff, or
re-running the XLA default), so numerics match the unfused path.

Selection discipline: decisions are EMPIRICAL per shape (the cuDNN
find-algorithm analog) and measured with scan-timed probes — per-dispatch
timing through the axon tunnel measures the tunnel, not the op.

``enable()`` registers the kernels via ``register_helper``; ``disable()``
restores the XLA defaults — the same silent-fallback seam semantics as the
reference. On non-TPU backends ``enable()`` uses the Pallas interpreter
(slow; for tests only).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import activations
from . import helpers
from . import kvquant

Array = jax.Array

_INTERPRET = False


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


# =============================================================================
# fused conv2d + bias + activation
# =============================================================================

def _conv_geometry(h: int, w: int, kh: int, kw: int, stride, padding):
    sh, sw = stride
    if padding == "SAME":
        oh = -(-h // sh)
        ow = -(-w // sw)
        pad_h = max((oh - 1) * sh + kh - h, 0)
        pad_w = max((ow - 1) * sw + kw - w, 0)
        pads = ((pad_h // 2, pad_h - pad_h // 2),
                (pad_w // 2, pad_w - pad_w // 2))
    elif padding == "VALID":
        pads = ((0, 0), (0, 0))
        oh = (h - kh) // sh + 1
        ow = (w - kw) // sw + 1
    else:
        pads = tuple(tuple(p) for p in padding)
        oh = (h + pads[0][0] + pads[0][1] - kh) // sh + 1
        ow = (w + pads[1][0] + pads[1][1] - kw) // sw + 1
    return oh, ow, pads


def _conv_kernel(xs_ref, w_ref, b_ref, o_ref, *, kh, act_fn):
    """One grid step handles (batch-tile bt, output row oh, kernel row ki):
    the pre-shifted patch row for input row oh*sh+ki sits in VMEM and feeds
    ONE MXU matmul [bt*ow, kw*c]x[kw*c, oc] against kernel row ki's weights,
    accumulated into the VMEM-resident output block; bias+activation fuse
    into the last accumulation step. The full kh*kw*c im2col matrix is never
    materialized in HBM — only a kw-fold row expansion is."""
    ki = pl.program_id(2)
    a = xs_ref[:, 0]  # [bt, ow, kw*c]
    bt, ow, kwc = a.shape
    partial_sum = jnp.dot(a.reshape(bt * ow, kwc), w_ref[0],
                          preferred_element_type=jnp.float32)
    partial_sum = partial_sum.reshape(bt, 1, ow, -1)

    @pl.when(ki == 0)
    def _():
        o_ref[:] = partial_sum

    @pl.when(ki > 0)
    def _():
        o_ref[:] = o_ref[:] + partial_sum

    @pl.when(ki == kh - 1)
    def _():
        o_ref[:] = act_fn(o_ref[:] + b_ref[0, 0].astype(jnp.float32))


def _conv2d_bias_act_forward(x, w, b, stride, padding, dilation, activation):
    act_fn = activations.get(activation)
    kh, kw, _, oc = w.shape
    b_, h, wdt, c = x.shape
    sh, sw = stride
    oh, ow, pads = _conv_geometry(h, wdt, kh, kw, stride, padding)
    hp = (oh - 1) * sh + kh  # rows addressed by oi*sh + ki
    xp = jnp.pad(x, ((0, 0),
                     (pads[0][0], max(hp - h - pads[0][0], 0)),
                     pads[1], (0, 0)))[:, :hp]
    # kj-shifts hoisted to XLA (a kw-fold expansion, cheap vs full im2col);
    # feature order (kj, c) matches w.reshape(kh, kw*c, oc)
    xs = jnp.concatenate(
        [xp[:, :, kj:kj + sw * (ow - 1) + 1:sw, :] for kj in range(kw)],
        axis=-1)  # [B, hp, ow, kw*c]
    wk = w.reshape(kh, kw * c, oc)
    bk = b.reshape(1, 1, oc)
    # batch tile: keep patch-row + out blocks within the VMEM budget
    bt = b_
    while bt > 1 and (2 * bt * ow * kw * c + 2 * bt * ow * oc) * 4 \
            > 8 * 1024 * 1024:
        bt //= 2
    bp = _round_up(b_, bt)
    if bp != b_:
        xs = jnp.pad(xs, ((0, bp - b_), (0, 0), (0, 0), (0, 0)))
    out = pl.pallas_call(
        partial(_conv_kernel, kh=kh, act_fn=act_fn),
        out_shape=jax.ShapeDtypeStruct((bp, oh, ow, oc), jnp.float32),
        grid=(bp // bt, oh, kh),
        in_specs=[
            pl.BlockSpec((bt, 1, ow, kw * c),
                         lambda bi, oi, ki, sh=sh: (bi, oi * sh + ki, 0, 0)),
            pl.BlockSpec((1, kw * c, oc), lambda bi, oi, ki: (ki, 0, 0)),
            pl.BlockSpec((1, 1, oc), lambda bi, oi, ki: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bt, 1, ow, oc),
                               lambda bi, oi, ki: (bi, oi, 0, 0)),
        interpret=_INTERPRET,
    )(xs, wk, bk)
    return out[:b_].astype(x.dtype)


_conv_vjp_cache: Dict = {}


def _get_conv_fn(stride, padding, dilation, activation):
    key = (stride, padding, dilation, activation)
    if key in _conv_vjp_cache:
        return _conv_vjp_cache[key]

    def ref_fn(x, w, b):
        return helpers._conv2d_bias_act_default(
            x, w, b, stride=stride, padding=padding, dilation=dilation,
            activation=activation)

    @jax.custom_vjp
    def fn(x, w, b):
        return _conv2d_bias_act_forward(x, w, b, stride, padding, dilation,
                                        activation)

    def fn_fwd(x, w, b):
        return fn(x, w, b), (x, w, b)

    def fn_bwd(res, g):
        _, vjp = jax.vjp(ref_fn, *res)
        return vjp(g)

    fn.defvjp(fn_fwd, fn_bwd)
    _conv_vjp_cache[key] = fn
    return fn


def conv2d_bias_act_pallas(x, w, b, *, stride, padding, dilation, activation):
    """Measured on v5e (f32, AlexNet shapes): this kernel reaches 0.66-0.90x
    of XLA's native conv — XLA's internal conv emitter wins by avoiding even
    the kw-fold row expansion. Kept as the working reference implementation
    of the helper seam (and the template for fusions XLA can't do); enable()
    therefore registers it only when ``use_conv=True``."""
    # fall back to XLA for dilated convs and for tiny contraction dims
    # (kw*c << MXU lane width starves the systolic array, e.g. 1-channel
    # LeNet conv1 — the same algorithm-applicability choice cuDNN makes)
    if tuple(dilation) != (1, 1) or w.shape[1] * w.shape[2] < 8:
        return helpers._conv2d_bias_act_default(
            x, w, b, stride=stride, padding=padding, dilation=dilation,
            activation=activation)
    pad_key = padding if isinstance(padding, str) \
        else tuple(tuple(p) for p in padding)
    return _get_conv_fn(tuple(stride), pad_key, tuple(dilation), activation)(
        x, w, b)


# =============================================================================
# fused LSTM sequence — RETIRED (round 4)
# =============================================================================
# A full-sequence Pallas LSTM kernel (grid over timesteps, f32 VMEM-resident
# h/c state, one MXU matmul per step) lived here for rounds 2-3 behind a
# per-shape autotune. Round 4's scan-timed measurements (per-dispatch probes
# through the axon tunnel measure the tunnel, not the op — see
# _measure_scan) showed the XLA lax.scan default beating it at EVERY probed
# regime, including the large-state shapes the kernel was built for:
#
#   train (fwd+bwd), bf16, xla/pallas ratio — >1 would mean the kernel wins:
#     T=50  B=128 H=256  -> 0.70      T=50 B=256 H=512 -> 0.69
#     T=50  B=256 H=1024 -> 0.74      T=50 B=512 H=512 -> 0.98
#     T=100 B=256 H=512  -> 0.75
#   forward-only: 0.65-1.00 across the same grid.
#
# XLA pipelines the per-step [B,4H] matmul chain as well as the hand-written
# grid while fusing the gate math; the kernel's only structural edge
# (HBM-resident h/c avoided) does not bind at these sizes. Per the
# win-or-delete rule the kernel is deleted; the `lstm_sequence` HELPER SEAM
# stays (ops/helpers.py, reference LSTMHelpers.java:132 analog) so a future
# kernel can register against the same contract, and the empirical autotune
# machinery lives on in the attention/bn_act_pool seams below.

# =============================================================================
# fused BN+act+pool backward (bn_act_pool composite seam)
# =============================================================================

# activation + derivative pairs the fused backward can recompute in-kernel
_BNAP_ACTS = {
    "relu": (lambda z: jnp.maximum(z, 0.0),
             lambda z: (z > 0).astype(jnp.float32)),
    "identity": (lambda z: z, lambda z: jnp.ones_like(z)),
    "linear": (lambda z: z, lambda z: jnp.ones_like(z)),
    "tanh": (jnp.tanh, lambda z: 1.0 - jnp.tanh(z) ** 2),
    "sigmoid": (jax.nn.sigmoid,
                lambda z: jax.nn.sigmoid(z) * (1.0 - jax.nn.sigmoid(z))),
}


def _bnap_recompute(x_ref, g_ref, p_ref, act_fn, dact_fn, ch_last):
    """Shared recompute for both backward passes. The block is a 5D view
    (2 pool-rows, W/2, 2 pool-cols, D1, D2) where (D1, D2) is (C, bb) for
    the channels-sublane variant or (bb, C) for the channels-lane variant —
    the two physical layouts XLA actually assigns to NHWC activations
    ({0,3,2,1} batch-minor and {3,0,2,1}); feeding the matching transposed
    VIEW makes the transpose a free bitcast instead of a real copy (the
    row-major kernel measured 0.46 ms/step of pure layout copies around the
    pallas calls). From x it rebuilds x_hat, z, the activation, the 2x2
    argmax routing, and the routed gradient g_z — x and g are read from HBM
    exactly once per pass."""
    x = x_ref[...].astype(jnp.float32)        # (2, W2, 2, D1, D2)
    expand = (lambda v: v[None, :]) if ch_last else (lambda v: v[:, None])
    mean = expand(p_ref[0])
    inv = expand(p_ref[1])
    gam = expand(p_ref[2])
    bet = expand(p_ref[3])
    g = g_ref[...].astype(jnp.float32)        # (1, W2, 1, D1, D2)
    xh = (x - mean) * inv
    z = xh * gam + bet
    a = act_fn(z)
    # argmax routing must match the FORWARD's pool, which compared the
    # x.dtype-cast activations (fwd_chain: act(z).astype(x.dtype)) — for
    # bf16, f32 values that tie after rounding would otherwise route the
    # whole gradient to one element instead of splitting it (advisor r4)
    a_c = a.astype(x_ref.dtype).astype(jnp.float32)
    m = jnp.max(a_c, axis=(0, 2), keepdims=True)  # (1, W2, 1, D1, D2)
    eq = (a_c == m).astype(jnp.float32)
    cnt = jnp.sum(eq, axis=(0, 2), keepdims=True)  # ties per 2x2 window
    ga = eq * (g / cnt)  # even split among tied maxima — jnp.max's own
    # gradient convention (select-and-scatter routes to one element; the
    # difference exists only at exact ties, measure-zero for continuous
    # data, and preserves total gradient mass)
    return xh, ga * dact_fn(z)


def _bnap_sums_kernel(x_ref, g_ref, p_ref, dg_ref, db_ref, *, act_fn,
                      dact_fn, ch_last):
    first = jnp.logical_and(pl.program_id(0) == 0, pl.program_id(1) == 0)

    @pl.when(first)
    def _():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    xh, gz = _bnap_recompute(x_ref, g_ref, p_ref, act_fn, dact_fn, ch_last)
    axes = (0, 1, 2, 3) if ch_last else (0, 1, 2, 4)
    db_ref[:] += jnp.sum(gz, axes)
    dg_ref[:] += jnp.sum(gz * xh, axes)


def _bnap_dx_kernel(x_ref, g_ref, p_ref, s_ref, dx_ref, *, act_fn, dact_fn,
                    ch_last, n):
    xh, gz = _bnap_recompute(x_ref, g_ref, p_ref, act_fn, dact_fn, ch_last)
    expand = (lambda v: v[None, :]) if ch_last else (lambda v: v[:, None])
    inv = expand(p_ref[1])
    gam = expand(p_ref[2])
    s_b = expand(s_ref[0]) / n
    s_g = expand(s_ref[1]) / n
    dx_ref[...] = (inv * gam * (gz - s_b - xh * s_g)).astype(dx_ref.dtype)


def _bnap_batch_stats(x):
    # shared dtype-guarded definition (one-pass only for sub-f32 inputs)
    return helpers.bn_batch_stats(x)


_bnap_vjp_cache: Dict = {}


def _get_bnap_fn(eps, activation, variant="hwcb"):
    """variant: which physical layout the backward kernels assume.
    'hwcb' = batch on lanes (matches XLA's batch-minor {0,3,2,1}, the
    layout picked for C < 128 activations); 'hwbc' = channels on lanes
    (matches {3,0,2,1}, picked for C >= 128). The matching transposed view
    turns the layout adaptation into a bitcast instead of a real copy."""
    key = (float(eps), activation, variant)
    if key in _bnap_vjp_cache:
        return _bnap_vjp_cache[key]
    act_fn, dact_fn = _BNAP_ACTS[activation]
    ch_last = variant == "hwbc"

    def fwd_chain(x, gamma, beta):
        mean32, var32 = _bnap_batch_stats(x)
        inv = jax.lax.rsqrt(var32 + eps)
        z = (x.astype(jnp.float32) - mean32) * inv * gamma.astype(
            jnp.float32) + beta.astype(jnp.float32)
        a = act_fn(z).astype(x.dtype)
        B, H, W, C = x.shape
        p = jnp.max(a.reshape(B, H // 2, 2, W // 2, 2, C), axis=(2, 4))
        return p, (mean32, var32)

    @jax.custom_vjp
    def fn(x, gamma, beta):
        p, (mean32, var32) = fwd_chain(x, gamma, beta)
        # the stats outputs are EMA-only by contract: bn_act_pool_pallas
        # stop-gradients them at the seam, so fn_bwd may ignore their
        # cotangents. Returning them here (instead of recomputing outside
        # the opaque custom_vjp call) keeps the production program
        # identical to what the autotune probe measured.
        return p, mean32, var32

    def fn_fwd(x, gamma, beta):
        p, (mean32, var32) = fwd_chain(x, gamma, beta)
        return (p, mean32, var32), (x, gamma, beta, mean32, var32)

    def fn_bwd(res, g):
        x, gamma, beta, mean32, var32 = res
        g = g[0]  # pooled-output cotangent; stat cotangents are zero by
        # the stop-gradient contract at the seam
        B, H, W, C = x.shape
        W2 = W // 2
        n = B * H * W
        inv32 = jax.lax.rsqrt(var32 + eps)
        p = jnp.stack([mean32, inv32, gamma.astype(jnp.float32),
                       beta.astype(jnp.float32)])          # (4, C)
        bb = 64 if ch_last else 128  # lanes need 128; sublane tiles 8x
        Bp = _round_up(B, bb)
        if Bp != B:
            x = jnp.pad(x, ((0, Bp - B), (0, 0), (0, 0), (0, 0)))
            g = jnp.pad(g, ((0, Bp - B), (0, 0), (0, 0), (0, 0)))
        if ch_last:  # [H, W2, 2, B, C]
            xv = x.transpose(1, 2, 0, 3).reshape(H, W2, 2, Bp, C)
            gv = g.transpose(1, 2, 0, 3).reshape(H // 2, W2, 1, Bp, C)
            xspec = pl.BlockSpec((2, W2, 2, bb, C),
                                 lambda hi, bi: (hi, 0, 0, bi, 0))
            gspec = pl.BlockSpec((1, W2, 1, bb, C),
                                 lambda hi, bi: (hi, 0, 0, bi, 0))
        else:        # [H, W2, 2, C, B]
            xv = x.transpose(1, 2, 3, 0).reshape(H, W2, 2, C, Bp)
            gv = g.transpose(1, 2, 3, 0).reshape(H // 2, W2, 1, C, Bp)
            xspec = pl.BlockSpec((2, W2, 2, C, bb),
                                 lambda hi, bi: (hi, 0, 0, 0, bi))
            gspec = pl.BlockSpec((1, W2, 1, C, bb),
                                 lambda hi, bi: (hi, 0, 0, 0, bi))
        grid = (H // 2, Bp // bb)
        common_in = [xspec, gspec,
                     pl.BlockSpec((4, C), lambda hi, bi: (0, 0))]
        dg, db = pl.pallas_call(
            partial(_bnap_sums_kernel, act_fn=act_fn, dact_fn=dact_fn,
                    ch_last=ch_last),
            out_shape=(jax.ShapeDtypeStruct((C,), jnp.float32),
                       jax.ShapeDtypeStruct((C,), jnp.float32)),
            grid=grid,
            in_specs=common_in,
            out_specs=(pl.BlockSpec((C,), lambda hi, bi: (0,)),
                       pl.BlockSpec((C,), lambda hi, bi: (0,))),
            interpret=_INTERPRET,
        )(xv, gv, p)
        s = jnp.stack([db, dg])                             # (2, C)
        dxv = pl.pallas_call(
            partial(_bnap_dx_kernel, act_fn=act_fn, dact_fn=dact_fn,
                    ch_last=ch_last, n=float(n)),
            out_shape=jax.ShapeDtypeStruct(xv.shape, x.dtype),
            grid=grid,
            in_specs=common_in + [pl.BlockSpec((2, C),
                                               lambda hi, bi: (0, 0))],
            out_specs=xspec,
            interpret=_INTERPRET,
        )(xv, gv, p, s)
        if ch_last:
            dx = dxv.reshape(H, W, Bp, C).transpose(2, 0, 1, 3)
        else:
            dx = dxv.reshape(H, W, C, Bp).transpose(3, 0, 1, 2)
        return (dx[:B], dg.astype(gamma.dtype), db.astype(beta.dtype))

    fn.defvjp(fn_fwd, fn_bwd)
    _bnap_vjp_cache[key] = fn
    return fn


_BNAP_AUTOTUNE_CACHE: Dict = {}


def autotune_decisions() -> Dict:
    """Snapshot of ALL per-shape kernel-vs-XLA decisions made so far,
    keyed ("attention", ...shape key...) / ("bn_act_pool", ...) /
    ("paged_decode", ...)."""
    out = {("attention",) + k: v
           for k, v in _ATTN_AUTOTUNE_CACHE.items()}
    out.update({("bn_act_pool",) + k: v
                for k, v in _BNAP_AUTOTUNE_CACHE.items()})
    out.update({("paged_decode",) + k: v
                for k, v in _PAGED_AUTOTUNE_CACHE.items()})
    return out


def clear_autotune_cache() -> None:
    _ATTN_AUTOTUNE_CACHE.clear()
    _BNAP_AUTOTUNE_CACHE.clear()
    _PAGED_AUTOTUNE_CACHE.clear()
    _PAGED_ENGAGED.clear()


def _eagerly(fn):
    """Run an autotune probe OUTSIDE any ambient trace. The helpers are
    normally first called while a train step is being jit-traced; without
    this escape every probe's `float()` fetch hits ConcretizationTypeError
    (inner jit calls inline into the outer trace), the except-clause eats
    it, and the seam silently falls back to XLA forever. jax.core's
    eval_context restores top-level eager semantics for the probe, so the
    measurement is real and the cached decision is shape-true."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.core.eval_context():
            return fn(*args, **kwargs)
    return wrapped


def _measure_scan(step_fn, x0, K=32, repeats=3) -> float:
    """Per-iteration device time of ``step_fn`` measured as ONE jitted
    lax.scan of K carry-chained applications + one host fetch. Sub-ms ops
    CANNOT be timed per-dispatch through the axon tunnel: each dispatch
    costs ~0.5-0.8 ms to enqueue and a dispatch->fetch cycle ~105 ms, so a
    per-call probe measures the tunnel, not the op. The carry feeds back
    into the input so XLA cannot hoist the body out of the loop."""
    import time

    def body(c, _):
        return step_fn(c), None

    run = jax.jit(lambda c: jax.lax.scan(body, c, None, length=K)[0])
    out = run(x0)
    _ = float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
    best = float("inf")
    for _rep in range(repeats):
        t0 = time.perf_counter()
        out = run(x0)
        _ = float(jnp.sum(
            jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)))
        best = min(best, time.perf_counter() - t0)
    return best / K


@_eagerly
def _autotune_bnap(B, H, W, C, dtype, eps, activation) -> bool:
    """Measure the fused-backward composite against the XLA default IN
    CONTEXT: sandwiched between a producer conv (whose input/weight grads
    XLA fuses the BN-backward into) and the train-step chain — the r4
    ISOLATED probe selected the kernel at 8x8x256 where the full model then
    measured a 0.5% LOSS, because the custom-call boundary breaks exactly
    those fusions (VERDICT r4 weak #3 / item 5; docs/ROOFLINE_CNN.md §3).
    Selection rule: the kernel must win the in-context composite by >=5%
    (the find-algorithm discipline of CudnnConvolutionHelper.java:48, with
    the margin covering probe noise), else XLA fallback."""
    import numpy as np
    rng = np.random.default_rng(0)
    # producer conv: same-C 3x3 SAME, the AlexNet-shaped adjacency whose
    # backward XLA fuses the composite's dx into
    xin = jnp.asarray(rng.normal(size=(B, H, W, C)), dtype)
    wc = jnp.asarray(rng.normal(size=(3, 3, C, C)) * 0.05, dtype)
    gamma = jnp.ones((C,), dtype)
    beta = jnp.zeros((C,), dtype)
    dn = ("NHWC", "HWIO", "NHWC")

    def ref(y, gamma, beta):
        return helpers._bn_act_pool_default(
            y, gamma, beta, eps=eps, activation=activation)[0]

    def train_step(comp):
        def loss(xc):
            y = jax.lax.conv_general_dilated(
                xc, wc, (1, 1), "SAME", dimension_numbers=dn)
            return jnp.sum(comp(y, gamma, beta).astype(jnp.float32) ** 2)
        g = jax.grad(loss)
        return lambda xc: xc + 1e-6 * g(xc).astype(xc.dtype)

    best = None  # (time, variant)
    for variant in ("hwcb", "hwbc"):
        fused = _get_bnap_fn(eps, activation, variant)

        def pooled_only(y, g_, b_, fused=fused):
            return fused(y, g_, b_)[0]

        try:
            t = _measure_scan(train_step(pooled_only), xin)
        except Exception:
            continue
        if best is None or t < best[0]:
            best = (t, variant)
    if best is None:
        return False
    try:
        t_r = _measure_scan(train_step(ref), xin)
    except Exception:
        # reference measurement failed transiently: no walkover for a
        # net-negative-prone kernel — fall back to XLA (advisor r4; the
        # attention seam walks over instead because dense XLA genuinely
        # cannot compile at its failing shapes)
        return False
    return best[1] if best[0] * 1.05 < t_r else False


def bn_act_pool_pallas(x, gamma, beta, *, eps=1e-5, activation="relu"):
    """bn_act_pool seam override: identical XLA forward, fused 2-pass Pallas
    BACKWARD (pool-argmax routing + act' + BN stat-grads recomputed
    in-kernel from x — select-and-scatter and the separate reduction passes
    disappear). Per-shape autotuned with silent XLA fallback."""
    B, H, W, C = x.shape
    supported = (activation in _BNAP_ACTS and H % 2 == 0 and W % 2 == 0
                 and C % 8 == 0 and W >= 4)
    if not supported:
        return helpers._bn_act_pool_default(x, gamma, beta, eps=eps,
                                            activation=activation)
    variant = "hwbc"  # interpreter/test default
    if not _INTERPRET:
        key = (B, H, W, C, jnp.dtype(x.dtype).name, float(eps), activation)
        if key not in _BNAP_AUTOTUNE_CACHE:
            _BNAP_AUTOTUNE_CACHE[key] = _autotune_bnap(
                B, H, W, C, x.dtype, float(eps), activation)
        variant = _BNAP_AUTOTUNE_CACHE[key]
        if not variant:
            return helpers._bn_act_pool_default(x, gamma, beta, eps=eps,
                                                activation=activation)
    pooled, mean32, var32 = _get_bnap_fn(float(eps), activation, variant)(
        x, gamma, beta)
    return (pooled, jax.lax.stop_gradient(mean32),
            jax.lax.stop_gradient(var32))


# =============================================================================
# flash attention (library Pallas kernel behind the helper seam)
# =============================================================================

_ATTN_AUTOTUNE_CACHE: Dict = {}


def _flash_block_sizes(block: int):
    """Square BlockSizes config for fwd AND both backward kernels."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes
    b = block
    return BlockSizes(block_q=b, block_k_major=b, block_k=b, block_b=1,
                      block_q_major_dkv=b, block_k_major_dkv=b,
                      block_k_dkv=b, block_q_dkv=b,
                      block_k_major_dq=b, block_k_dq=b, block_q_dq=b)


def _flash_call(q, k, v, causal, scale, block: int = 0):
    """q,k,v: [B, L, H, D] (the framework layout) -> [B, L, H, D] via the
    TPU flash-attention Pallas kernel (jax.experimental.pallas.ops.tpu),
    which ships its own backward pass. block=0 uses the library default
    BlockSizes; nonzero uses a square config (the autotuner probes these —
    measured on v5e the defaults are badly mistuned: L=8192 bf16 runs
    11.4 ms default vs 2.95 ms at block 1024 vs 5.9 ms XLA)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import \
        flash_attention
    D = q.shape[-1]
    sm_scale = float(scale) if scale is not None else float(1.0 / (D ** 0.5))
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, L, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    bs = _flash_block_sizes(block) if block else None
    out = flash_attention(qt, kt, vt, causal=causal, sm_scale=sm_scale,
                          block_sizes=bs)
    return jnp.swapaxes(out, 1, 2)


def _splash_call(q, k, v, causal, scale):
    """q,k,v: [B, L, H, D] -> [B, L, H, D] via the splash-attention Pallas
    kernel (jax.experimental.pallas.ops.tpu.splash_attention) — never
    materializes the [L, L] score matrix, so it trains sequence lengths the
    dense path cannot compile at all (measured v5e, H=8 D=128: dense OOMs at
    L=32k while splash runs 563 ms/step; at 64k splash runs 2.27 s).
    The kernel has no sm_scale parameter, so the scale folds into q."""
    from jax.experimental.pallas.ops.tpu.splash_attention import \
        splash_attention_kernel as sak
    from jax.experimental.pallas.ops.tpu.splash_attention import \
        splash_attention_mask as sam
    B, L, H, D = q.shape
    s = float(scale) if scale is not None else float(1.0 / (D ** 0.5))
    mk = sam.CausalMask((L, L)) if causal else sam.FullMask((L, L))
    kernel = sak.make_splash_mha(mask=sam.MultiHeadMask([mk] * H),
                                 head_shards=1, q_seq_shards=1,
                                 interpret=_INTERPRET)
    qt = jnp.swapaxes(q * jnp.asarray(s, q.dtype), 1, 2)  # [B, H, L, D]
    out = jax.vmap(kernel)(qt, jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2))
    return jnp.swapaxes(out, 1, 2)


@_eagerly
def _autotune_attention(B, L, H, D, dtype, causal):
    """Probe the flash kernel (library-default blocks plus square block
    candidates that divide L) and the splash kernel against the XLA einsum
    attention on this exact shape — forward AND fwd+bwd. Returns the
    winning config: an int flash block (0 = library default), the string
    "splash", or False for the XLA path. When the dense XLA path cannot
    even compile (its [L, L] scores blow HBM at very long L), the best
    kernel wins by walkover."""
    import numpy as np
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, L, H, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, L, H, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, L, H, D)), dtype)

    def train_step(fn):
        # carry-chained fwd+bwd step for _measure_scan (q feeds back so
        # XLA cannot hoist the body); K/V captured
        g = jax.grad(lambda qc: jnp.sum(fn(qc, k, v).astype(jnp.float32)))
        return lambda qc: qc + jnp.asarray(1e-6, qc.dtype) * g(qc).astype(
            qc.dtype)

    def ref(q, k, v):
        return helpers._attention_default(q, k, v, causal=causal, scale=None)

    # per-iteration cost through the tunnel cannot be probed per-dispatch
    # (~105 ms dispatch->fetch RTT, ~0.6 ms enqueue each): time K chained
    # applications inside ONE jitted scan. Probes are TRAIN-only (fwd+bwd
    # through jax.grad — the cost that decides the selection; measured
    # fwd-only rankings track it) and the candidate list shrinks with L so
    # the probe's compile budget stays bounded: every (candidate, K)
    # compile at L=8k+ costs ~20-40 s through the tunnel.
    K = 16 if L <= 2048 else (8 if L <= 8192 else 4)
    if L >= 4096:
        candidates = [b for b in (512, 1024) if L % b == 0] + ["splash"]
    else:
        candidates = [0] + [b for b in (256, 512, 1024) if L % b == 0] \
            + ["splash"]
    best = None  # (train_time, config)
    for block in candidates:
        if block == "splash":
            def fla(q, k, v):
                return _splash_call(q, k, v, causal, None)
        else:
            def fla(q, k, v, block=block):
                return _flash_call(q, k, v, causal, None, block=block)
        try:
            t_t = _measure_scan(train_step(fla), q, K=K, repeats=2)
        except Exception:
            continue  # unsupported config for this shape
        if best is None or t_t < best[0]:
            best = (t_t, block)
    if best is None:
        return False
    try:
        t_r_t = _measure_scan(train_step(ref), q, K=K, repeats=2)
    except Exception:
        # Walkover. The dominant case is a permanent compile failure — the
        # dense [L, L] scores exceed HBM at very long L — but even for a
        # transient error the kernel just measured HEALTHY on this shape
        # while the dense path errored, so the kernel is the safe cached
        # choice.
        return best[1]
    return best[1] if best[0] < t_r_t * 0.95 else False


def attention_pallas(q, k, v, *, causal=False, scale=None):
    """Helper-seam attention: per-shape autotuned choice among the XLA
    einsum path, the flash-attention Pallas kernel under several block
    configurations, and the splash-attention kernel (cuDNN find-algorithm
    semantics).

    Measured on v5e (H=8, D=128, bf16, causal, through the seam inside a
    jitted step): at L=8192 flash with square 1024 blocks trains at
    ~18 ms/step vs ~20 ms XLA; at L=32768 the dense path cannot compile at
    all (34 GB of [L, L] scores vs 15.75 GB HBM) and the kernel wins by
    walkover — 94 ms/step, with splash (563 ms) as the backstop when flash
    blocks don't fit. Short sequences keep the XLA path."""
    if _INTERPRET:  # CPU/test runs: the flash kernel is TPU-only
        return helpers._attention_default(q, k, v, causal=causal,
                                          scale=scale)
    B, L, H, D = q.shape
    key = (B, L, H, D, jnp.dtype(q.dtype).name, bool(causal))
    if key not in _ATTN_AUTOTUNE_CACHE:
        _ATTN_AUTOTUNE_CACHE[key] = _autotune_attention(
            B, L, H, D, q.dtype, bool(causal))
    decision = _ATTN_AUTOTUNE_CACHE[key]
    if decision is False:
        return helpers._attention_default(q, k, v, causal=causal,
                                          scale=scale)
    if decision == "splash":
        return _splash_call(q, k, v, causal, scale)
    return _flash_call(q, k, v, causal, scale, block=int(decision))


# =============================================================================
# fused paged-attention decode (ISSUE 15 tentpole)
# =============================================================================
# The paged decode hot path gathered a slot's ENTIRE logical cache
# [B, nb*block, Hkv, Dh] out of the page arrays every step (attention.py
# `_paged_step`), so decode bandwidth scaled with pool capacity instead of
# live tokens — and the int8 path additionally materialized a full
# dequantized fp copy of that gather. This kernel is the FlashDecoding
# treatment: one grid pass per (batch row, kv-head) walks the row's int32
# block table (scalar-prefetched, so each page's HBM->VMEM stream is
# issued straight off the table entry), computes QK^T + online softmax
# (running max / sum-exp in VMEM scratch) + V accumulation page by page,
# and dequantizes int8 rows in-loop via the shared ops/kvquant.py helpers.
# The gathered cache never exists; HBM traffic is one pass over the rows
# the table actually references.
#
# Seam contract (ops/helpers.py `paged_decode_attention`): the layer's
# gather/einsum body STAYS as the token-identity reference and the
# fallback — prefill chunks (T > 1), shapes the kernel does not support,
# mode "off", and every shape where the per-shape autotune picks XLA all
# return None here and run the reference. K/V WRITES (including the wmask
# scratch-page redirect and int8 quantization) also stay in the XLA
# prologue: the kernel fuses only the read side, so host-side table
# surgery, COW, and masked-lane semantics are untouched.

_PAGED_AUTOTUNE_CACHE: Dict = {}
# every trace-time engagement decision (forced AND autotuned), keyed like
# the autotune cache — the observability feed for the engine's
# `paged_kernel_engaged` gauge and the /debug/engine cost table
_PAGED_ENGAGED: Dict = {}
_PAGED_DEFAULT_VARIANT = "bh"


def paged_decode_decisions() -> Dict:
    """Trace-time kernel-vs-XLA engagements for the paged-decode family
    (includes forced ``mode="on"`` traces, unlike the autotune cache):
    {(B, nb, block, Hkv, H, Dh, dtype, quantized, mode): variant | False}.
    The MODE is part of the key — co-resident engines over the same
    shapes but different ``paged_kernel`` modes (the bench's A/B
    topology) must not overwrite each other's verdicts."""
    return dict(_PAGED_ENGAGED)


def enable_paged_decode(interpret=None) -> None:
    """Register ONLY the paged-decode seam (the serve CLI's arming
    path). Unlike :func:`enable`, this leaves every other helper —
    attention, conv, bn_act_pool — at its XLA default: a serving
    process that opted into ``--paged-kernel`` must not have its
    /predict forwards or GQA contraction silently rerouted through the
    rest of the plugin."""
    global _INTERPRET
    _INTERPRET = (jax.default_backend() != "tpu") if interpret is None \
        else bool(interpret)
    helpers.register_helper("paged_decode_attention",
                            paged_decode_attention_pallas)


def _paged_decode_body(table_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref,
                       vs_ref, o_ref, acc_ref, m_ref, l_ref, *, block,
                       batch_major):
    """One grid step = one page of one (batch row, kv-head) pair.

    Grid (b, h, j) ("bh" variant; "hb" swaps the outer two), j the
    LOGICAL block index — sequential on TPU, so the f32 VMEM scratch
    (acc [G, Dh], running max m and sum-exp l) carries the online
    softmax across the row's pages. The page itself arrives via the
    BlockSpec index map reading the scalar-prefetched table
    (``table_ref[b, j]``), i.e. the gather IS the block fetch. Blocks
    past the row's decode depth are skipped whole; inside a live block,
    positions beyond ``pos`` mask to -inf (same coverage as the
    reference's ``arange(L) <= pos``). int8 pages dequantize per row
    inside the loop (ops/kvquant.py — the exact cast-then-multiply the
    XLA gather uses), so no fp copy of the table ever exists."""
    del table_ref  # consumed by the index maps
    b = pl.program_id(0 if batch_major else 1)
    j = pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[b]

    # skip pages wholly beyond this row's depth: the guard also keeps the
    # running max finite (a processed block always has a valid position,
    # since block j's first position j*block <= pos)
    @pl.when(j * block <= pos)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)          # [G, Dh]
        k = k_ref[0, :, 0]                           # [block, Dh]
        v = v_ref[0, :, 0]
        if ks_ref is not None:
            k = kvquant.dequantize_kv_rows(k, ks_ref[0, :, 0], jnp.float32)
            v = kvquant.dequantize_kv_rows(v, vs_ref[0, :, 0], jnp.float32)
        else:
            k = k.astype(jnp.float32)
            v = v.astype(jnp.float32)
        s = jax.lax.dot_general(                     # [G, block]
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = s / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
        offs = j * block + jax.lax.broadcasted_iota(
            jnp.int32, (1, block), 1)
        s = jnp.where(offs <= pos, s, -jnp.inf)
        m_prev = m_ref[:, 0:1]                       # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_ref[:, 0:1] * alpha \
            + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nb - 1)
    def _():
        o_ref[0, 0] = (acc_ref[...] / l_ref[:, 0:1]).astype(o_ref.dtype)


def _paged_fp_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                     acc_ref, m_ref, l_ref, *, block, batch_major):
    _paged_decode_body(table_ref, pos_ref, q_ref, k_ref, v_ref, None,
                       None, o_ref, acc_ref, m_ref, l_ref, block=block,
                       batch_major=batch_major)


def _paged_int8_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref,
                       vs_ref, o_ref, acc_ref, m_ref, l_ref, *, block,
                       batch_major):
    _paged_decode_body(table_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref,
                       vs_ref, o_ref, acc_ref, m_ref, l_ref, block=block,
                       batch_major=batch_major)


def _paged_decode_call(q, k_pages, v_pages, table, pos, k_scales=None,
                       v_scales=None, *, variant=_PAGED_DEFAULT_VARIANT):
    """The pallas_call over LOCAL (per-shard) shapes. q: [B, 1, H, Dh];
    k/v_pages: [pages, block, Hkv, Dh]; table: [B, nb] int32; pos: [B]
    int32 -> [B, 1, H, Dh]. ``variant``: grid-major-order config probed
    by the autotuner — "bh" walks all of a row's heads back-to-back
    (q block reuse), "hb" streams one head's pages across the batch
    (page-fetch pipeline depth B per head)."""
    B, _, H, Dh = q.shape
    block, Hkv = k_pages.shape[1], k_pages.shape[2]
    G = H // Hkv
    nb = table.shape[1]
    qr = q.reshape(B, Hkv, G, Dh)  # head h*G+g, the _grouped_attention order
    batch_major = variant != "hb"
    if batch_major:
        def bh(i0, i1):
            return i0, i1
        grid = (B, Hkv, nb)
    else:
        def bh(i0, i1):
            return i1, i0
        grid = (Hkv, B, nb)

    def qmap(i0, i1, j, tref, pref):
        b, h = bh(i0, i1)
        return (b, h, 0, 0)

    def kmap(i0, i1, j, tref, pref):
        b, h = bh(i0, i1)
        return (tref[b, j], 0, h, 0)

    def smap(i0, i1, j, tref, pref):
        b, h = bh(i0, i1)
        return (tref[b, j], 0, h)

    in_specs = [pl.BlockSpec((1, 1, G, Dh), qmap),
                pl.BlockSpec((1, block, 1, Dh), kmap),
                pl.BlockSpec((1, block, 1, Dh), kmap)]
    args = [qr, k_pages, v_pages]
    kern = _paged_fp_kernel
    if k_scales is not None:
        in_specs += [pl.BlockSpec((1, block, 1), smap),
                     pl.BlockSpec((1, block, 1), smap)]
        args += [k_scales, v_scales]
        kern = _paged_int8_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, Dh), qmap),
        scratch_shapes=[pltpu.VMEM((G, Dh), jnp.float32),
                        pltpu.VMEM((G, 128), jnp.float32),
                        pltpu.VMEM((G, 128), jnp.float32)])
    out = pl.pallas_call(
        partial(kern, block=block, batch_major=batch_major),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), q.dtype),
        grid_spec=grid_spec,
        interpret=_INTERPRET,
    )(table.astype(jnp.int32), pos.astype(jnp.int32), *args)
    return out.reshape(B, 1, H, Dh)


def _xla_paged_reference(q, k_pages, v_pages, table, pos, k_scales=None,
                         v_scales=None):
    """The current XLA gather path as a standalone function — the
    autotune probe's baseline and the tests' bit-level oracle. Mirrors
    attention.py `_paged_step`'s read side exactly: gather the whole
    logical cache through the table (dequantizing the int8 pool to the
    query dtype first), then the grouped contraction + f32 softmax of
    `_grouped_attention` with per-row causal depths."""
    B, T, H, Dh = q.shape
    block, Hkv = k_pages.shape[1], k_pages.shape[2]
    L = table.shape[1] * block
    dt = q.dtype
    if k_scales is not None:
        kc = kvquant.dequantize_kv_rows(
            k_pages[table], k_scales[table], dt).reshape(B, L, Hkv, Dh)
        vc = kvquant.dequantize_kv_rows(
            v_pages[table], v_scales[table], dt).reshape(B, L, Hkv, Dh)
    else:
        kc = k_pages[table].reshape(B, L, Hkv, Dh)
        vc = v_pages[table].reshape(B, L, Hkv, Dh)
    qg = q.reshape(B, T, Hkv, H // Hkv, Dh)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kc) / jnp.sqrt(
        jnp.asarray(Dh, dt))
    valid = (jnp.arange(L)[None, None, :]
             <= pos[:, None, None] + jnp.arange(T)[None, :, None])
    s = jnp.where(valid[:, None, None], s.astype(jnp.float32),
                  jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dt)
    return jnp.einsum("bhgqk,bkhd->bqhgd", p, vc).reshape(B, T, H, Dh)


@_eagerly
def _autotune_paged_decode(B, nb, block, Hkv, H, Dh, dtype, quantized):
    """Probe the fused decode kernel's grid configs against the XLA
    gather path on this exact LOCAL shape (one decode step, carry-
    chained through _measure_scan). Returns the winning variant string
    or False for XLA. Rows are probed at FULL table depth — the
    regime the bucket was compiled for; shallower rows only shrink the
    kernel's walk. Selection needs a >= 5% win (find-algorithm margin
    over probe noise); a reference that cannot even run while the
    kernel measured healthy is a walkover, like the attention seam."""
    if _INTERPRET:
        # interpreter probes measure the interpreter, not the op: the
        # seam silently keeps XLA (tests force the kernel with "on")
        return False
    import numpy as np
    rng = np.random.default_rng(0)
    pages = B * nb + 1
    kp = jnp.asarray(rng.normal(size=(pages, block, Hkv, Dh)), dtype)
    vp = jnp.asarray(rng.normal(size=(pages, block, Hkv, Dh)), dtype)
    ks = vs = None
    if quantized:
        kp, ks = kvquant.quantize_kv_rows(kp)
        vp, vs = kvquant.quantize_kv_rows(vp)
    table = jnp.asarray(
        1 + np.arange(B * nb, dtype=np.int32).reshape(B, nb))
    pos = jnp.full((B,), nb * block - 1, jnp.int32)
    q0 = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), dtype)

    def step(fn):
        def s(qc):
            out = fn(qc, kp, vp, table, pos, ks, vs)
            return qc + jnp.asarray(1e-6, qc.dtype) * out.astype(qc.dtype)
        return s

    K = 16 if nb * block <= 4096 else 8
    best = None  # (time, variant)
    for variant in ("bh", "hb"):
        def fn(qc, kp, vp, tb, ps, ks, vs, variant=variant):
            return _paged_decode_call(qc, kp, vp, tb, ps, ks, vs,
                                      variant=variant)
        try:
            t = _measure_scan(step(fn), q0, K=K, repeats=2)
        except Exception:
            continue
        if best is None or t < best[0]:
            best = (t, variant)
    if best is None:
        return False
    try:
        t_r = _measure_scan(step(_xla_paged_reference), q0, K=K, repeats=2)
    except Exception:
        # walkover: the gather path blew up (at large pools its
        # materialized [B, nb*block, Hkv, Dh] cache can exceed HBM)
        # while the kernel just measured healthy on this shape
        return best[1]
    return best[1] if best[0] * 1.05 < t_r else False


def paged_decode_attention_pallas(q, k_pages, v_pages, table, pos, *,
                                  k_scales=None, v_scales=None,
                                  mode="auto", mesh=None):
    """Seam override for `ops.helpers.paged_decode_attention`: per-shape
    autotuned choice between the fused page-walk kernel and the XLA
    gather path (returns None = caller runs its reference body — the
    silent-fallback contract). Under a tp mesh the kernel runs inside
    shard_map over the LOCAL Hkv shard (q/pages head-split, table/pos
    replicated — the layout the engine already carries), so the
    compiled program keeps the Megatron all-reduce-only collective
    budget: the kernel itself never communicates."""
    B, T, H, Dh = q.shape
    block, Hkv = k_pages.shape[1], k_pages.shape[2]
    # f32 only: the kernel accumulates QK^T/softmax/PV in f32, which
    # matches the XLA reference's arithmetic for f32 engines but NOT a
    # bf16 engine's (the reference contracts in the model dtype) — a
    # sub-f32 compute dtype falls back so the token-identity contract
    # holds; a dtype-disciplined bf16 variant is future headroom
    if T != 1 or H % Hkv or mode == "off" or q.dtype != jnp.float32:
        return None
    quantized = k_scales is not None
    tp = 1
    axis = "tp"
    if mesh is not None:
        try:
            from ..inference.sharding import TP_AXIS as axis
        except Exception:
            pass
        tp = int(dict(mesh.shape).get(axis, 1))
        if tp > 1 and (Hkv % tp or H % tp):
            return None
    key = (B, int(table.shape[1]), block, Hkv // tp, H // tp, Dh,
           jnp.dtype(q.dtype).name, quantized)
    if mode == "on":
        variant = _PAGED_DEFAULT_VARIANT
    else:
        if key not in _PAGED_AUTOTUNE_CACHE:
            _PAGED_AUTOTUNE_CACHE[key] = _autotune_paged_decode(
                *key[:6], q.dtype, quantized)
        variant = _PAGED_AUTOTUNE_CACHE[key]
    _PAGED_ENGAGED[key + (mode,)] = variant
    if not variant:
        return None
    if tp > 1:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import NamedSharding
        from ..inference.sharding import paged_kernel_shard_specs
        sp = paged_kernel_shard_specs(axis)
        hs4, hs3, rep = sp["rows"], sp["scales"], sp["host"]
        # anchor q's propagated placement to the head split the
        # column-parallel Wq already implies — a no-op when GSPMD
        # agrees, and it keeps the audit at zero resharding when it
        # would otherwise hedge
        q = jax.lax.with_sharding_constraint(
            q, NamedSharding(mesh, hs4))
        if quantized:
            fn = shard_map(
                partial(_paged_decode_call, variant=variant),
                mesh=mesh,
                in_specs=(hs4, hs4, hs4, rep, rep, hs3, hs3),
                out_specs=hs4, check_rep=False)
            return fn(q, k_pages, v_pages, table, pos, k_scales,
                      v_scales)
        fn = shard_map(
            partial(_paged_decode_call, variant=variant),
            mesh=mesh, in_specs=(hs4, hs4, hs4, rep, rep),
            out_specs=hs4, check_rep=False)
        return fn(q, k_pages, v_pages, table, pos)
    return _paged_decode_call(q, k_pages, v_pages, table, pos, k_scales,
                              v_scales, variant=variant)


# =============================================================================
# registration
# =============================================================================

def enable(interpret=None, use_conv=None, use_bn_act_pool=None) -> None:
    """Register the Pallas kernels behind the helper seam.

    interpret=None auto-detects: compiled on TPU, interpreter elsewhere
    (tests). The interpreter is orders of magnitude slower than XLA — only
    enable on CPU to validate numerics.

    use_conv=None registers the conv kernel only in interpreter (test) runs:
    on real TPU it measures slower than XLA's native conv (see
    conv2d_bias_act_pallas).

    use_bn_act_pool=None likewise registers the fused BN+act+pool backward
    only in interpreter (test) runs — PRODUCTION-RETIRED r5 by the same
    win-or-delete rule that retired the LSTM kernel. Measured history on
    the AlexNet-CIFAR10 flagship (v5e, bf16, B=512): the r4 ISOLATED
    scan-probe win (1.10-1.13x at C>=128) was already known not to
    survive in context (full-model 0.995, VERDICT r4 weak #3); the r5
    IN-CONTEXT probe (composite sandwiched in a producer conv, >=5%
    required margin) still selected it, but three independent full-model
    A/Bs measured helper_delta_vs_xla = 1.024 / 0.975 / 0.976 — parity
    within tunnel noise, median slightly NEGATIVE, below the >=1.05
    full-model bar (VERDICT r4 item 5). The custom-call boundary forfeits
    XLA's fusion of BN-dx into the adjacent conv gradients and the 2-pass
    HBM saving does not cover that loss at these shapes. Kernel, VJP,
    autotuner, and interpret-mode numerics tests remain for
    experimentation (pass use_bn_act_pool=True).
    """
    global _INTERPRET
    _INTERPRET = (jax.default_backend() != "tpu") if interpret is None \
        else bool(interpret)
    if use_conv is None:
        use_conv = _INTERPRET
    if use_bn_act_pool is None:
        use_bn_act_pool = _INTERPRET
    if use_conv:
        helpers.register_helper("conv2d_bias_act", conv2d_bias_act_pallas)
    helpers.register_helper("attention", attention_pallas)
    # paged-decode is registered unconditionally like attention: its own
    # per-shape autotune (and the engine's paged_kernel mode knob) keeps
    # XLA wherever the kernel does not win, and in interpreter runs the
    # "auto" decision is always XLA — tests force engagement with "on"
    helpers.register_helper("paged_decode_attention",
                            paged_decode_attention_pallas)
    if use_bn_act_pool:
        helpers.register_helper("bn_act_pool", bn_act_pool_pallas)


def disable() -> None:
    """Restore the XLA default implementations (silent-fallback seam)."""
    helpers.register_helper("conv2d_bias_act", None)
    helpers.register_helper("attention", None)
    helpers.register_helper("paged_decode_attention", None)
    helpers.register_helper("bn_act_pool", None)
