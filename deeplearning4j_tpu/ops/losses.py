"""Loss functions matching the reference's LossFunctions enum semantics.

Reference: ND4J `org.nd4j.linalg.lossfunctions.LossFunctions`/`LossCalculation`
(consumed by deeplearning4j-core/.../nn/layers/BaseOutputLayer.java for scoring).
Each loss takes (labels, preds) with optional per-example mask and returns the
summed-over-outputs, mean-over-examples scalar score (the reference divides the
batch sum by the number of examples at score time; see
MultiLayerNetwork.java score path).

All functions are pure and jit-safe; masks (for variable-length time series,
reference `feedForward(input,fMask,lMask)` MultiLayerNetwork.java:711) are
broadcast [batch, time] -> [batch*time, 1] by the RNN output layer before
calling in here.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

_EPS = 1e-8


def _reduce(per_example: Array, mask: Optional[Array]) -> Array:
    """Sum over output dims already done; average over (masked) examples."""
    if mask is not None:
        m = mask.reshape((per_example.shape[0],) + (1,) * (per_example.ndim - 1))
        per_example = per_example * m.squeeze() if per_example.ndim == 1 else per_example * m
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.sum(per_example) / denom
    return jnp.mean(per_example)


def mse(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    per_ex = jnp.sum((labels - preds) ** 2, axis=-1)
    return _reduce(per_ex, mask)


def squared_loss(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    return mse(labels, preds, mask)


def l1(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    per_ex = jnp.sum(jnp.abs(labels - preds), axis=-1)
    return _reduce(per_ex, mask)


def l2(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    return mse(labels, preds, mask)


def xent(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    """Binary cross entropy (reference XENT)."""
    p = jnp.clip(preds, _EPS, 1.0 - _EPS)
    per_ex = -jnp.sum(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p), axis=-1)
    return _reduce(per_ex, mask)


def mcxent(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    """Multi-class cross entropy against probabilities (reference MCXENT)."""
    p = jnp.clip(preds, _EPS, 1.0)
    per_ex = -jnp.sum(labels * jnp.log(p), axis=-1)
    return _reduce(per_ex, mask)


def negativeloglikelihood(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    return mcxent(labels, preds, mask)


def rmse_xent(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    per_ex = jnp.sqrt(jnp.sum((labels - preds) ** 2, axis=-1) + _EPS)
    return _reduce(per_ex, mask)


def expll(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    """Exponential log likelihood (Poisson-style, reference EXPLL)."""
    p = jnp.clip(preds, _EPS, None)
    per_ex = jnp.sum(p - labels * jnp.log(p), axis=-1)
    return _reduce(per_ex, mask)


def reconstruction_crossentropy(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    return xent(labels, preds, mask)


def hinge(labels: Array, preds: Array, mask: Optional[Array] = None) -> Array:
    """Hinge loss; labels expected in {-1, +1} or one-hot (converted)."""
    lab = jnp.where(labels > 0, 1.0, -1.0)
    per_ex = jnp.sum(jnp.maximum(0.0, 1.0 - lab * preds), axis=-1)
    return _reduce(per_ex, mask)


def softmax_mcxent_from_logits(labels: Array, logits: Array,
                               mask: Optional[Array] = None) -> Array:
    """Fused softmax + multi-class cross entropy computed from PRE-activation
    logits: ``-sum(y * log_softmax(z))`` in f32.

    Why this exists: ``mcxent`` on post-softmax probabilities clips at 1e-8,
    and autodiff through the clip yields exactly ZERO gradient wherever the
    softmax has saturated (p underflows to 0) — a mis-saturated example can
    then never be corrected and training wedges (observed: AlexNet-CIFAR10
    stuck at loss ~6.7 with |grad| ~1e-4 after transient divergence). The
    reference never has this problem because BaseOutputLayer computes the
    output-layer delta analytically as (p - y)
    (LossCalculation / BaseOutputLayer.java getGradientsAndDelta); the
    logits-space log_softmax formulation reproduces exactly that gradient
    (d/dz of -y.log_softmax(z) == softmax(z) - y), bounded and never clipped.
    The facades route (softmax, mcxent/nll) output layers here via
    ``fused_from_logits``."""
    z = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(z, axis=-1)
    per_ex = -jnp.sum(labels.astype(jnp.float32) * logp, axis=-1)
    return _reduce(per_ex, mask)


def sigmoid_xent_from_logits(labels: Array, logits: Array,
                             mask: Optional[Array] = None) -> Array:
    """Fused sigmoid + binary cross entropy from logits (stable softplus
    form); same rationale as softmax_mcxent_from_logits."""
    z = logits.astype(jnp.float32)
    y = labels.astype(jnp.float32)
    per = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return _reduce(jnp.sum(per, axis=-1), mask)


# (activation, loss) pairs with a numerically-stable from-logits form. The
# train/score loss paths consult this and feed PRE-activation outputs.
_FUSED_FROM_LOGITS: dict[tuple, Callable[..., Array]] = {
    ("softmax", "mcxent"): softmax_mcxent_from_logits,
    ("softmax", "negativeloglikelihood"): softmax_mcxent_from_logits,
    ("softmax", "nll"): softmax_mcxent_from_logits,
    ("sigmoid", "xent"): sigmoid_xent_from_logits,
}


def fused_from_logits(activation, loss_name) -> Optional[Callable[..., Array]]:
    if activation is None or loss_name is None:
        return None
    return _FUSED_FROM_LOGITS.get((str(activation).lower(), str(loss_name).lower()))


LOSSES: dict[str, Callable[..., Array]] = {
    "mse": mse,
    "squared_loss": squared_loss,
    "l1": l1,
    "l2": l2,
    "xent": xent,
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "nll": negativeloglikelihood,
    "rmse_xent": rmse_xent,
    "expll": expll,
    "reconstruction_crossentropy": reconstruction_crossentropy,
    "hinge": hinge,
}


def get(name: str) -> Callable[..., Array]:
    try:
        return LOSSES[name.lower()]
    except KeyError:
        raise ValueError(f"Unknown loss '{name}'. Available: {sorted(LOSSES)}") from None
