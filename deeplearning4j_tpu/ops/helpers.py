"""Accelerated-op helper seam.

Parity with the reference's per-layer `*Helper` plugin seam
(nn/layers/convolution/ConvolutionHelper.java:29 + the cuDNN plugin module
deeplearning4j-cuda-7.5, loaded reflectively at ConvolutionLayer.java:64-70
with silent fallback). TPU redesign: the seam lives at the *op* level — a
registry of implementations for conv2d / pool2d / batch_norm / lrn. The
default impls are XLA-lowered lax ops (already MXU-tiled and fused); Pallas
kernels register overrides via `register_helper` (see ops/pallas_kernels.py),
and callers never change. `use_helper(name, None)` restores the default —
the same silent-fallback semantics as the reference.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

_HELPERS: Dict[str, Callable] = {}


def register_helper(name: str, fn: Optional[Callable]) -> None:
    """Override the implementation of an op; None restores the default."""
    if fn is None:
        _HELPERS.pop(name, None)
    else:
        _HELPERS[name] = fn


def get_helper(name: str) -> Optional[Callable]:
    return _HELPERS.get(name)


# -- conv2d --------------------------------------------------------------------

_DIMNUMS = ("NHWC", "HWIO", "NHWC")


def _conv2d_default(x: Array, w: Array, *, stride, padding, dilation=(1, 1)) -> Array:
    # bf16 inputs: the TPU MXU accumulates partial sums in f32 internally;
    # forcing preferred_element_type=f32 here breaks the autodiff transpose
    # (mixed-dtype conv in the backward pass), so dtypes are left as-is.
    return lax.conv_general_dilated(
        x, w,
        window_strides=tuple(stride),
        padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=_DIMNUMS,
    )


def conv2d(x: Array, w: Array, *, stride=(1, 1), padding="SAME", dilation=(1, 1)) -> Array:
    """NHWC x HWIO -> NHWC convolution."""
    impl = _HELPERS.get("conv2d", _conv2d_default)
    return impl(x, w, stride=stride, padding=padding, dilation=dilation)


# -- fused conv2d + bias + activation -----------------------------------------

def _conv2d_bias_act_default(x, w, b, *, stride, padding, dilation, activation):
    from . import activations
    # route through the public conv2d seam so a 'conv2d' override still
    # applies when no fused-op override is registered
    y = conv2d(x, w, stride=stride, padding=padding, dilation=dilation)
    return activations.get(activation)(y + b)


def conv2d_bias_act(x: Array, w: Array, b: Array, *, stride=(1, 1),
                    padding="SAME", dilation=(1, 1),
                    activation="identity") -> Array:
    """Fused NHWC conv + bias + activation — the cuDNN-helper hot path
    (CudnnConvolutionHelper.java:48). Default: XLA fuses the epilogue into
    the conv; Pallas override in ops/pallas_kernels.py."""
    impl = _HELPERS.get("conv2d_bias_act", _conv2d_bias_act_default)
    return impl(x, w, b, stride=stride, padding=padding, dilation=dilation,
                activation=activation)


# -- fused LSTM sequence -------------------------------------------------------

def lstm_cell(z, c_prev, peep, act_fn):
    """One LSTM cell step from pre-activations z = x·W + b + h·RW.
    Gate packing [i, f, o, g]; peep = (pI, pF, pO) peephole weights (zeros/
    scalars for a plain LSTM). THE single definition of the cell math —
    shared by the scan default below and _LSTMCore._gates (masked path /
    rnnTimeStep); the Pallas kernel mirrors it on padded shapes."""
    H = c_prev.shape[-1]
    i = jax.nn.sigmoid(z[..., :H] + c_prev * peep[0])
    f = jax.nn.sigmoid(z[..., H:2 * H] + c_prev * peep[1])
    g = act_fn(z[..., 3 * H:])
    c = f * c_prev + i * g
    o = jax.nn.sigmoid(z[..., 2 * H:3 * H] + c * peep[2])
    h = o * act_fn(c)
    return h, c


def _lstm_sequence_default(xproj_t, rw, peep, h0, c0, *, activation, reverse):
    from . import activations
    act_fn = activations.get(activation)

    def body(state, xp):
        h_prev, c_prev = state
        h, c = lstm_cell(xp + h_prev @ rw, c_prev, peep, act_fn)
        return (h, c), h

    (ht, ct), ys = lax.scan(body, (h0, c0), xproj_t, reverse=reverse)
    return ys, ht, ct


def lstm_sequence(xproj_t: Array, rw: Array, peep: Array, h0: Array, c0: Array,
                  *, activation="tanh", reverse=False):
    """Fused LSTM over a pre-projected sequence (the LSTMHelpers.java:132
    hot loop). xproj_t: [T, B, 4H] = x·W + b for all timesteps; gate packing
    [i, f, o, g]; peep: [3, H] peephole weights (zeros => plain LSTM).
    Returns (ys [T, B, H], h_T, c_T)."""
    impl = _HELPERS.get("lstm_sequence", _lstm_sequence_default)
    return impl(xproj_t, rw, peep, h0, c0, activation=activation,
                reverse=reverse)


# -- pool2d --------------------------------------------------------------------

def _pool2d_default(x: Array, *, kind, kernel, stride, padding, pnorm=2) -> Array:
    # NOTE (r4 device-trace study, tools/trace_alexnet.py): reduce_window is
    # the RIGHT lowering here. Alternatives tried and measured worse on the
    # full AlexNet step: rank-6 reshape+max (its gradient materializes
    # [B,H/2,2,W/2,2,C] broadcasts) and strided-slice pairwise max (layout
    # copies around every strided read). select-and-scatter for the 2x2/s2
    # backward runs at ~memory roofline for the large shapes; the remaining
    # win is cross-op fusion of the BN+act+pool epilogue, not the pool alone.
    kh, kw = kernel
    window = (1, kh, kw, 1)
    strides = (1, stride[0], stride[1], 1)
    if padding == "SAME":
        pad = "SAME"
    else:
        (ph0, ph1), (pw0, pw1) = padding
        pad = ((0, 0), (ph0, ph1), (pw0, pw1), (0, 0))
    kind = kind.lower()
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pad)
    if kind in ("avg", "mean"):
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        ones = jnp.ones_like(x)
        count = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad)
        return s / count
    if kind == "sum":
        return lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
    if kind == "pnorm":
        p = float(pnorm)
        s = lax.reduce_window(jnp.power(jnp.abs(x), p), 0.0, lax.add, window, strides, pad)
        return jnp.power(s, 1.0 / p)
    raise ValueError(f"Unknown pooling kind '{kind}'")


def pool2d(x: Array, *, kind="max", kernel=(2, 2), stride=(2, 2), padding="SAME", pnorm=2) -> Array:
    impl = _HELPERS.get("pool2d", _pool2d_default)
    return impl(x, kind=kind, kernel=kernel, stride=stride, padding=padding, pnorm=pnorm)


# -- batch norm ----------------------------------------------------------------

def _batch_norm_default(x, gamma, beta, mean, var, *, eps) -> Array:
    inv = lax.rsqrt(var + eps)
    return (x - mean) * inv * gamma + beta


def batch_norm(x, gamma, beta, mean, var, *, eps=1e-5) -> Array:
    impl = _HELPERS.get("batch_norm", _batch_norm_default)
    return impl(x, gamma, beta, mean, var, eps=eps)


# -- fused train-mode BatchNorm + activation + 2x2/s2 max-pool ----------------

def bn_batch_stats(x) -> Tuple[Array, Array]:
    """Per-channel batch (mean, var) over all-but-last axes — THE single
    definition of the BN stats math. For sub-f32 inputs: one-pass
    E[x^2]-E[x]^2 with f32 accumulation (one fused multi-output reduction,
    fusable into the producer conv's epilogue; f32 has ~16 guard bits over
    bf16/f16 significands so the cancellation is safe). For f32/f64 the
    cancellation would destroy precision, so two-pass jnp.var is kept.
    Callers: BatchNormalizationImpl.forward, _bn_act_pool_default, and the
    Pallas bn_act_pool override."""
    axes = tuple(range(x.ndim - 1))
    if x.dtype in (jnp.bfloat16, jnp.float16):
        xf = x.astype(jnp.float32)
        mean32 = jnp.mean(xf, axis=axes)
        var32 = jnp.maximum(
            jnp.mean(xf * xf, axis=axes) - mean32 * mean32, 0.0)
    else:
        mean32 = jnp.mean(x, axis=axes)
        var32 = jnp.var(x, axis=axes)
    return mean32, var32


def _bn_act_pool_default(x, gamma, beta, *, eps, activation):
    from . import activations
    mean32, var32 = bn_batch_stats(x)
    y = batch_norm(x, gamma, beta, mean32.astype(x.dtype),
                   var32.astype(x.dtype), eps=eps)
    y = activations.get(activation)(y)
    y = pool2d(y, kind="max", kernel=(2, 2), stride=(2, 2), padding="SAME")
    return y, mean32, var32


def bn_act_pool(x, gamma, beta, *, eps=1e-5, activation="relu"):
    """Train-mode batch norm (batch stats) + activation + 2x2/s2 max-pool as
    ONE composite op, returning (pooled, batch_mean32, batch_var32).

    Why a composite exists at the seam: the device trace of the AlexNet
    train step (tools/trace_alexnet.py) shows XLA's BACKWARD for this
    layer-pair costs ~4 HBM passes over the largest activations
    (select-and-scatter pool grad + act/BN-dx passes + two stat-grad
    reductions); a fused custom-VJP kernel does it in two
    (ops/pallas_kernels.py). Reference analog: the cuDNN BN helper fuses
    normalize+activation the same way (CudnnBatchNormalizationHelper).
    Requires x [B,H,W,C] with even H and W."""
    impl = _HELPERS.get("bn_act_pool", _bn_act_pool_default)
    return impl(x, gamma, beta, eps=eps, activation=activation)


# -- local response normalization ---------------------------------------------

def _lrn_default(x: Array, *, k, n, alpha, beta) -> Array:
    # cross-channel sliding-window sum of squares; NHWC channels-last
    half = int(n) // 2
    sq = x * x
    window = (1, 1, 1, 2 * half + 1)
    s = lax.reduce_window(sq, 0.0, lax.add, window, (1, 1, 1, 1),
                          ((0, 0), (0, 0), (0, 0), (half, half)))
    return x / jnp.power(k + alpha * s, beta)


def lrn(x: Array, *, k=2.0, n=5.0, alpha=1e-4, beta=0.75) -> Array:
    impl = _HELPERS.get("lrn", _lrn_default)
    return impl(x, k=k, n=n, alpha=alpha, beta=beta)


# -- multi-head attention -----------------------------------------------------

def _attention_default(q: Array, k: Array, v: Array, *, causal=False,
                       scale=None) -> Array:
    """Dense attention via XLA einsums (parallel/ring.full_attention)."""
    from ..parallel.ring import full_attention
    return full_attention(q, k, v, causal=causal, scale=scale)


def attention(q: Array, k: Array, v: Array, *, causal: bool = False,
              scale=None) -> Array:
    """Multi-head attention helper seam. q,k,v: [B, L, H, D] -> [B, L, H, D].
    The accelerated plugin may register a flash-attention kernel here
    (ops/pallas_kernels.py), same silent-fallback semantics as the conv/
    LSTM helpers."""
    impl = _HELPERS.get("attention", _attention_default)
    return impl(q, k, v, causal=causal, scale=scale)


# -- fused paged-attention decode ----------------------------------------------

def paged_decode_attention(q: Array, k_pages: Array, v_pages: Array,
                           table: Array, pos: Array, *,
                           k_scales=None, v_scales=None,
                           mode: str = "auto", mesh=None):
    """Fused paged-KV decode attention seam (ISSUE 15).

    ``q``: [B, 1, H, Dh] single-token queries (RoPE already applied);
    ``k_pages``/``v_pages``: [pages, block, Hkv, Dh] pool-wide page
    arrays AFTER this step's write (page 0 = scratch); ``table``:
    [B, nb] int32 block tables (scratch-padded); ``pos``: [B] int32
    decode depths — row b attends causally over absolute positions
    [0, pos[b]]. ``k_scales``/``v_scales``: [pages, block, Hkv] f32
    dequant scales when the pages are int8 (ops/kvquant.py contract).
    ``mode``: "auto" (per-shape autotune vs the XLA gather path) /
    "on" (force the kernel) / "off". ``mesh``: the engine's tp mesh —
    the registered kernel grids over the LOCAL Hkv shard via shard_map
    so head-sharded serving never reshards (inference/sharding.py).

    Returns [B, 1, H, Dh], or **None** — the contract's silent-fallback
    arm: no kernel registered, mode "off", an unsupported shape, or a
    per-shape autotune decision for XLA. The caller (the layer's
    ``_paged_step``) then runs its own gather/einsum body, which stays
    the token-identity reference. The decision is made at TRACE time
    (shapes and mode are static), so a None costs nothing compiled.
    """
    impl = _HELPERS.get("paged_decode_attention")
    if impl is None or mode == "off":
        return None
    return impl(q, k_pages, v_pages, table, pos, k_scales=k_scales,
                v_scales=v_scales, mode=mode, mesh=mesh)
