"""Block-pooled KV store: paged live-decode backing + radix-trie prefix index.

Real serving traffic is dominated by shared prompt prefixes (system
prompts, few-shot templates, chat history) and wildly mixed prompt
lengths, yet the decode scheduler used to hand every slot a contiguous
``max_cache_len`` stripe of K/V — HBM cost ``slots × max_cache_len``
regardless of actual lengths. This module is the block-level KV
management of modern inference engines (vLLM's PagedAttention block
tables, SGLang's RadixAttention prefix tree), in two modes:

**Paged mode** (``paged=True`` — the ISSUE 6 tentpole): the pool IS the
live decode cache. The engine owns one pool-wide page array per layer
(``k_pages``/``v_pages``: ``[capacity+1, block, Hkv, Dh]``) and gives
each slot an int32 *block table* mapping logical block index → page row;
the jitted decode/prefill programs read and write K/V through the table
(`nn/layers/attention.py` paged step). The pool object holds only the
host-side metadata: the free list, the trie, and per-node refcounts.
Consequences that fall out of the layout:

  - slot capacity is bounded by total pool bytes, not
    ``slots × max_cache_len`` — dozens of short sequences share the
    pages one long one would have monopolized;
  - prefix restore is a **block-table remap**: cached blocks are
    *referenced*, never gathered (zero K/V copies), with copy-on-write
    on the first write into a shared block;
  - publish at finish is the same move in reverse: the slot's full
    prompt blocks are *adopted* by the trie (ownership transfer, no
    scatter);
  - under pool pressure the scheduler preempts the latest-submitted slot
    (blocks released, sequence requeued) and resumes it later.

**Contiguous mode** (``paged=False`` — the ISSUE 4 layout, kept as the
token-identity reference and for nets the paged path cannot serve): a
side pool caching completed prompts' K/V, restored into the slot's
contiguous stripe by a jitted block-gather:

  - :class:`KVPool` — per-layer K/V storage carved into fixed-size blocks
    of ``block`` positions, preallocated under a byte budget (index 0 is a
    scratch block that absorbs padded writes and is never handed out).
    Blocks are refcounted through the trie nodes that own them and
    LRU-evicted (unreferenced leaves first) when the free list runs dry.
  - a **radix/trie prefix index**: one node per full block of token ids,
    children keyed by the block's token tuple, so a prefix lookup walks
    the trie in O(prompt/block) dict hops and returns the longest chain
    of cached blocks. Only COMPLETE blocks are indexed — a partial tail
    block is never shared (its K/V would depend on tokens the next
    request may not send).
  - :func:`gather_blocks` / :func:`scatter_blocks` — the pure program
    bodies the engine jits: restore gathers a block chain out of pool
    storage into one slot's contiguous cache rows ``[0, n*block)`` via a
    single fused take + ``dynamic_update_slice`` (bucketed by chain
    length, same pow2 compile discipline as chunked prefill) and advances
    the slot's ``pos`` past the hit; publish slices a finished prompt's
    rows back out of the slot cache into pool blocks.

Soundness: reuse is only valid for **pos-0-anchored prefixes**. Cached
keys are stored pre-rotated at their absolute positions (RoPE commutes
with the cache — nn/layers/attention.py), so a prefix starting at
position 0 is bit-identical across requests and can be copied instead of
recomputed; a mid-sequence match would need re-rotation and is not
attempted. Restored rows are *copies* into the slot's private cache, so a
slot never aliases pool storage — and pool writes go through functional
``.at[idx].set`` updates, so a restore gather issued against the previous
storage array still reads consistent data (structural copy-on-write: a
live reader is never aliased by a writer).

Threading: the pool's host-side metadata (trie, free list, refcounts) is
owned by the engine's scheduler thread — every mutation happens between
engine steps on that single thread, the same single-writer discipline
``DecodeScheduler._slots`` uses — so it needs no lock of its own.
"""
from __future__ import annotations

import heapq
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import failpoints
from .metrics import MetricsRegistry
from .trace import FlightRecorder

# storage index 0 is the scratch block: padded restore lanes gather from
# it and padded publish lanes scatter into it, so bucketed programs never
# need a mask — real blocks are numbered from 1
SCRATCH_BLOCK = 0

# every pool-wide page-array key a paged attention state may carry: K/V
# pages plus (int8 KV mode) their per-row dequantization scales. The
# single source of truth for "this leaf is SHARED pool storage, not a
# per-slot row" across the engine's slice/scatter/zero/freeze/COW paths.
PAGE_KEYS = ("k_pages", "v_pages", "k_scales", "v_scales")


class _Node:
    """One full block of a cached prefix: ``key`` is the block's token
    tuple (the edge label from the parent), ``block_id`` its storage row.
    ``lock`` counts live sequences pinning this node (admission locks the
    deepest matched node; publish pins its extension path while
    allocating) — locked nodes and interior nodes are never evicted."""

    __slots__ = ("key", "block_id", "parent", "children", "last_access",
                 "lock", "hash")

    def __init__(self, key: Tuple[int, ...], block_id: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_access = 0
        self.lock = 0
        #: content-addressed chain hash (kvtier.chain_hash over the
        #: ancestor chain); computed only when a TierManager is armed —
        #: None otherwise, and "" at the root
        self.hash: Optional[str] = None


class KVPool:
    """Refcounted block pool + trie prefix index over per-layer K/V.

    ``attn_states``: the engine's attention state entries
    (``{key: {"k": [n_slots, L, Hkv, Dh], "v": ..., "pos": ...}}``) —
    only shapes/dtypes are read; storage is allocated fresh. The byte
    budget covers EVERYTHING the pool allocates (scratch block included):
    ``capacity_blocks`` usable blocks cost
    ``(capacity_blocks + 1) * bytes_per_block <= budget_bytes``.

    ``paged=True``: the engine owns the page arrays (they live inside
    its jitted state pytree, where the programs scatter/gather them);
    this object allocates NOTHING on device and becomes pure metadata —
    free list, trie, refcounts — plus the ``kv_pool_*`` gauges.

    ``shard_factor``: tensor-parallel device count when the K/V head
    axis is sharded over a mesh (`inference/sharding.py`). Each device
    then holds only ``Hkv / shard_factor`` heads of every block, so
    ``budget_bytes`` is the PER-DEVICE byte budget and
    ``bytes_per_block`` the per-device cost — at fixed per-device HBM a
    ``tp``-wide mesh holds ``tp×`` the blocks. The block/trie/refcount
    metadata is device-count-agnostic (one logical pool).
    """

    def __init__(self, attn_states: Dict, *, block: int, budget_bytes: int,
                 paged: bool = False, shard_factor: int = 1,
                 cache_dtype: Optional[str] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 tracer: Optional[FlightRecorder] = None):
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        if cache_dtype not in (None, "int8"):
            raise ValueError(f"cache_dtype must be None or 'int8', got "
                             f"{cache_dtype!r}")
        if cache_dtype and not paged:
            raise ValueError("cache_dtype='int8' requires paged mode "
                             "(the contiguous side pool stores the "
                             "model's own K/V dtype)")
        self.block = int(block)
        self.paged = bool(paged)
        self.cache_dtype = cache_dtype
        self.shard_factor = max(1, int(shard_factor))
        # flight recorder (trace.py): eviction/publish instants on the
        # `kvpool` track; None (standalone pool) records nothing
        self._tracer = tracer
        self.budget_bytes = int(budget_bytes)
        per_block = 0
        shapes = {}
        for key, st in attn_states.items():
            row_shape = tuple(st["k"].shape[2:])  # (Hkv, Dh)
            dtype = st["k"].dtype
            shapes[key] = (row_shape, dtype)
            if cache_dtype == "int8":
                # int8 KV pages + one f32 dequant scale per (position,
                # head) row: Hkv*Dh bytes of values + Hkv*4 of scales
                # per position per k-or-v — under half the f32 cost for
                # any Dh >= 8, so the same budget holds >= 2x the blocks
                row_bytes = int(math.prod(row_shape)) \
                    + int(row_shape[0]) * 4
            else:
                row_bytes = int(jnp.dtype(dtype).itemsize) \
                    * int(math.prod(row_shape))
            per_block += 2 * self.block * row_bytes
        # per-DEVICE block cost: the head axis splits evenly over the
        # mesh (the engine refuses to shard otherwise), so a block costs
        # each device 1/shard_factor of its total bytes
        per_block = per_block // self.shard_factor
        self.bytes_per_block = per_block
        total = self.budget_bytes // per_block if per_block else 0
        # one block of the budget is the scratch row
        self.capacity_blocks = max(0, int(total) - 1)
        self.storage: Dict = {}
        if self.capacity_blocks > 0 and not self.paged:
            n = self.capacity_blocks + 1
            self.storage = {
                key: {"k": jnp.zeros((n, self.block) + row_shape, dtype),
                      "v": jnp.zeros((n, self.block) + row_shape, dtype)}
                for key, (row_shape, dtype) in shapes.items()}
        self._free: List[int] = list(range(1, self.capacity_blocks + 1))
        self._root = _Node((), SCRATCH_BLOCK, None)
        self._root.hash = ""
        #: optional kvtier.TierManager — armed by the engine before any
        #: traffic. When set, every trie node is chain-hashed, inserts
        #: publish to the prefix directory, and LRU evictions offer the
        #: victim's pages for demotion instead of silently freeing them.
        self.tier = None
        self._clock = 0  # logical LRU clock (monotonic per pool op)
        self._metrics = metrics
        self._g_live = self._g_free = self._g_dev_used = None
        if metrics is not None:
            self._m_evicted = metrics.counter(
                "prefix_cache_evicted_blocks_total")
            if self.paged:
                # unified-pool occupancy: live = every allocated block
                # (slot-owned + trie-cached), free = the free list. The
                # utilization ratio is derived at snapshot time so it can
                # never go stale between scrapes.
                self._g_live = metrics.gauge("kv_pool_blocks_live")
                self._g_free = metrics.gauge("kv_pool_blocks_free")
                cap_g = metrics.gauge("kv_pool_blocks_capacity")
                cap_g.set(self.capacity_blocks)
                metrics.ratio("kv_pool_utilization", self._g_live, cap_g)
                # per-DEVICE pool footprint (scratch included): under a
                # tp mesh each device holds its head slice of every
                # page, so used bytes track utilization per device
                metrics.gauge("kv_pool_device_bytes").set(
                    (self.capacity_blocks + 1) * self.bytes_per_block)
                self._g_dev_used = metrics.gauge(
                    "kv_pool_device_used_bytes")
                self._sync_gauges()
            else:
                self._m_used = metrics.gauge("prefix_cache_used_bytes")
                cap = metrics.gauge("prefix_cache_capacity_bytes")
                cap.set((self.capacity_blocks + 1) * per_block
                        if self.capacity_blocks else 0)

    # -- host-side bookkeeping ---------------------------------------------
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _hash_and_publish(self, node: _Node) -> None:
        """Chain-hash a freshly attached node and publish it to the
        prefix directory — only when a TierManager is armed (the
        tierless pool pays nothing, not even the sha1)."""
        tier = self.tier
        if tier is None:
            return
        parent_hash = node.parent.hash
        if parent_hash is None:
            return  # ancestor predates arming; leave the branch unhashed
        from .kvtier import chain_hash
        node.hash = chain_hash(parent_hash, node.key)
        tier.note_resident(node.hash, parent_hash, node.key)

    def _sync_gauges(self) -> None:
        if self._g_live is not None:
            self._g_live.set(self.used_blocks)
            self._g_free.set(len(self._free))
            self._g_dev_used.set(self.used_blocks * self.bytes_per_block)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.capacity_blocks - len(self._free)

    @property
    def used_bytes(self) -> int:
        """Logical bytes held by indexed blocks (the eviction pressure
        signal; allocation itself is fixed at capacity)."""
        return self.used_blocks * self.bytes_per_block

    def outstanding_refs(self) -> int:
        """Total live sequence references across the trie — zero when no
        admitted sequence holds a prefix pin (the cancel-leak invariant)."""
        return sum(n.lock for n in self._walk())

    def refcounts(self) -> Dict[int, int]:
        """block_id -> live sequence references on its node."""
        return {n.block_id: n.lock for n in self._walk() if n.lock}

    def stats(self) -> dict:
        """One JSON-able occupancy/trie census for `GET /debug/engine`:
        block accounting plus the prefix index's shape (node count =
        indexed blocks, pinned refs, max chain depth). O(trie) — a
        diagnostics read, not a hot-path one."""
        nodes = depth = refs = 0
        stack = [(c, 1) for c in self._root.children.values()]
        while stack:
            n, d = stack.pop()
            nodes += 1
            refs += n.lock
            depth = max(depth, d)
            stack.extend((c, d + 1) for c in n.children.values())
        return {
            "capacity_blocks": self.capacity_blocks,
            "block_positions": self.block,
            "bytes_per_block": self.bytes_per_block,
            "free_blocks": len(self._free),
            "used_blocks": self.used_blocks,
            "utilization": round(
                self.used_blocks / self.capacity_blocks, 4)
            if self.capacity_blocks else 0.0,
            "trie": {"nodes": nodes, "max_depth_blocks": depth,
                     "pinned_refs": refs},
        }

    def _walk(self):
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    # -- prefix lookup ------------------------------------------------------
    def _walk_prefix(self, tokens: Sequence[int], max_blocks: int
                     ) -> Tuple[_Node, List[int]]:
        """Descend the deepest cached prefix of ``tokens`` (full blocks
        only, capped at ``max_blocks``), ticking ``last_access`` on the
        path — the single definition of the trie walk shared by
        :meth:`match` / :meth:`insert` / :meth:`adopt`. Returns the
        deepest node and the block ids along the path."""
        node, ids = self._root, []
        B = self.block
        while len(ids) < max_blocks:
            child = node.children.get(
                tuple(int(t) for t in tokens[len(ids) * B:(len(ids) + 1) * B]))
            if child is None:
                break
            node = child
            node.last_access = self._tick()
            ids.append(node.block_id)
        return node, ids

    def match(self, tokens: Sequence[int], max_blocks: int
              ) -> Tuple[int, List[int], Optional[_Node]]:
        """Longest cached prefix of ``tokens``, capped at ``max_blocks``
        full blocks. Returns ``(n_blocks, block_ids, node)`` and takes one
        reference on the deepest matched node (release with
        :meth:`release` when the sequence leaves its slot); no hit returns
        ``(0, [], None)`` and takes no reference."""
        node, ids = self._walk_prefix(tokens, max_blocks)
        if not ids:
            return 0, [], None
        node.lock += 1
        return len(ids), ids, node

    def release(self, node: _Node) -> None:
        if node.lock <= 0:
            raise AssertionError("release() without a matching reference")
        node.lock -= 1

    # -- paged mode: the pool as the live decode cache ----------------------
    def alloc(self) -> Optional[int]:
        """One free block for a slot's table (lazy allocation as ``pos``
        crosses a block boundary), LRU-evicting unreferenced cached
        blocks under pressure. ``None`` means even eviction could not
        free a block — every block is owned by a live slot or pinned,
        and the scheduler must preempt. The returned block is OWNED by
        the caller: it is in no trie node and no free list, so nothing
        else can touch it until `free_block` or `adopt`."""
        failpoints.fire("pool.alloc")  # chaos seam: injected OOM/crash
        bid = self._alloc()
        self._sync_gauges()
        return bid

    def free_block(self, block_id: int) -> None:
        """Return a slot-owned block (never a trie-owned one — those are
        freed by eviction) to the free list."""
        if block_id == SCRATCH_BLOCK:
            raise AssertionError("the scratch block is never owned")
        self._free.append(block_id)
        self._sync_gauges()

    def adopt(self, tokens: Sequence[int], block_ids: Sequence[int]
              ) -> List[int]:
        """Zero-copy publish: index ``tokens``'s full blocks by
        REFERENCE. ``block_ids[j]`` is the slot-owned page already
        holding block ``j``'s K/V (the slot's table — prefill wrote the
        pages in place, so there is nothing to scatter). Walks the
        existing trie prefix, attaches a node per missing block that
        simply takes over the caller's page, and returns the adopted
        ids — the caller must NOT free those (ownership moved to the
        trie; eviction frees them eventually)."""
        B = self.block
        n_total = len(tokens) // B
        node, matched = self._walk_prefix(tokens, n_total)
        i = len(matched)
        adopted: List[int] = []
        for j in range(i, n_total):
            key = tuple(int(t) for t in tokens[j * B:(j + 1) * B])
            child = _Node(key, int(block_ids[j]), node)
            node.children[key] = child
            self._hash_and_publish(child)
            node = child
            node.last_access = self._tick()
            adopted.append(int(block_ids[j]))
        if adopted and self._tracer is not None:
            self._tracer.instant("pool_publish", track="kvpool",
                                 args={"blocks": len(adopted),
                                       "used_blocks": self.used_blocks,
                                       "zero_copy": True})
        return adopted

    def reclaimable_blocks(self) -> int:
        """Free blocks plus cached blocks eviction could actually free
        (everything not on a pinned trie path) — the scheduler's
        admission gate: admitting a prompt needing more than this would
        immediately preempt a live slot."""
        pinned = set()
        for n in self._walk():
            if n.lock:
                p = n
                while p is not None and id(p) not in pinned:
                    pinned.add(id(p))
                    p = p.parent
        return len(self._free) + sum(
            1 for n in self._walk() if id(n) not in pinned)

    # -- insertion / eviction ----------------------------------------------
    def insert(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Index ``tokens`` (length a multiple of ``block``): walk the
        existing prefix, then allocate blocks for the missing suffix.
        Returns ``(start_block, new_block_ids)`` — the caller must copy
        the slot's cache rows ``[start*block, (start+len(ids))*block)``
        into those storage rows *before* the next admission can match
        them (trivially true on the single scheduler thread). Allocation
        is best-effort: when eviction cannot free a block (everything
        referenced), the suffix is simply not cached."""
        B = self.block
        n_total = len(tokens) // B
        node, matched = self._walk_prefix(tokens, n_total)
        start, new_ids, pinned = len(matched), [], []
        if node is not self._root:
            node.lock += 1  # pin the extension point against eviction
            pinned.append(node)
        try:
            # amortized: free everything this publish needs in ONE trie
            # walk instead of one walk per allocated block
            need = (n_total - start) - len(self._free)
            if need > 0:
                self._evict_lru(need)
            for j in range(start, n_total):
                bid = self._alloc()
                if bid is None:
                    break
                key = tuple(int(t) for t in tokens[j * B:(j + 1) * B])
                child = _Node(key, bid, node)
                node.children[key] = child
                self._hash_and_publish(child)
                node = child
                node.last_access = self._tick()
                node.lock += 1  # keep the fresh chain out of eviction
                pinned.append(node)
                new_ids.append(bid)
        finally:
            for n in pinned:
                n.lock -= 1
        if self._metrics is not None:
            if self.paged:
                self._sync_gauges()
            else:
                self._m_used.set(self.used_bytes)
        if new_ids and self._tracer is not None:
            self._tracer.instant("pool_publish", track="kvpool",
                                 args={"blocks": len(new_ids),
                                       "used_blocks": self.used_blocks})
        return start, new_ids

    def _alloc(self) -> Optional[int]:
        if not self._free:
            self._evict_lru()
        return self._free.pop() if self._free else None

    def _evict_lru(self, want: int = 1) -> None:
        """Free up to ``want`` blocks, least-recently-used unreferenced
        LEAVES first, in one trie walk (a heap over the candidates;
        a parent whose last child goes becomes a candidate itself).
        Interior nodes are never evicted directly — their children would
        become unreachable prefixes."""
        heap = [(n.last_access, id(n), n) for n in self._walk()
                if not n.children and not n.lock]
        heapq.heapify(heap)
        freed = 0
        while heap and freed < want:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            del parent.children[victim.key]
            if self.tier is not None:
                # demotion interception: capture the page row BEFORE the
                # id returns to the free list (the captured device
                # snapshot is immutable under functional updates, so the
                # reused id can be rewritten immediately)
                self.tier.offer_spill(victim.hash, victim.block_id)
            self._free.append(victim.block_id)
            freed += 1
            if parent is not self._root and not parent.children \
                    and not parent.lock:
                heapq.heappush(heap,
                               (parent.last_access, id(parent), parent))
        if freed and self._metrics is not None:
            self._m_evicted.inc(freed)
            if self.paged:
                self._sync_gauges()
            else:
                self._m_used.set(self.used_bytes)
        if freed and self._tracer is not None:
            self._tracer.instant("pool_evict", track="kvpool",
                                 args={"blocks": freed,
                                       "used_blocks": self.used_blocks})


# -- jitted program bodies (the engine jits these once per pow2 bucket) ----
def gather_blocks(states, slot1, idx, nblk1, storage, *, block):
    """Restore a cached prefix into one slot's contiguous cache rows.

    ``idx``: int32 [bucket] pool block ids, padded past ``nblk1[0]`` with
    :data:`SCRATCH_BLOCK` — the padded rows land at ``[nblk*block,
    bucket*block)``, beyond the restored ``pos``, so they are causally
    invisible and overwritten by the cold-suffix prefill exactly like
    chunked-prefill padding. ``slot1``/``nblk1`` are 1-element int32
    arrays (explicit transfers, the engine's transfer-guard contract).
    One XLA program per idx-length bucket; returns the updated states.
    """
    slot = slot1[0]
    nblk = nblk1[0]
    out = dict(states)
    for key, store in storage.items():
        st = states[key]
        nb = idx.shape[0]
        rows_k = store["k"][idx].reshape((1, nb * block) + st["k"].shape[2:])
        rows_v = store["v"][idx].reshape((1, nb * block) + st["v"].shape[2:])
        kc = jax.lax.dynamic_update_slice(st["k"], rows_k, (slot, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(st["v"], rows_v, (slot, 0, 0, 0))
        pos = jax.lax.dynamic_update_slice(
            st["pos"], jnp.reshape(nblk * block, (1,)).astype(st["pos"].dtype),
            (slot,))
        out[key] = {**st, "k": kc, "v": vc, "pos": pos}
    return out


def scatter_blocks(states, slot1, start1, idx, storage, *, block):
    """Publish one slot's prompt rows ``[start*block, (start+nb)*block)``
    into pool storage rows ``idx`` (int32 [nb], exact — no padding: the
    engine covers the new-block suffix with a greedy descending-bucket
    walk, so every id is real). The update is functional ``.at[idx].set``
    (copy-on-write semantics: a reader of the input arrays is never
    aliased by the write); the engine jits this with the storage argument
    DONATED so XLA updates the pool in place instead of re-materializing
    the whole byte budget per call — safe because all restore gathers
    against the old buffers were dispatched earlier on the same thread
    and XLA orders them before the donated write. Returns the updated
    storage pytree."""
    slot = slot1[0]
    start = start1[0]
    new_storage = {}
    for key, store in storage.items():
        st = states[key]
        nb = idx.shape[0]
        tail = st["k"].shape[2:]
        rows_k = jax.lax.dynamic_slice(
            st["k"], (slot, start * block, 0, 0), (1, nb * block) + tail)
        rows_v = jax.lax.dynamic_slice(
            st["v"], (slot, start * block, 0, 0), (1, nb * block) + tail)
        new_storage[key] = {
            "k": store["k"].at[idx].set(rows_k.reshape((nb, block) + tail)),
            "v": store["v"].at[idx].set(rows_v.reshape((nb, block) + tail)),
        }
    return new_storage
