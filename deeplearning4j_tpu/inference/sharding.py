"""Tensor-parallel sharding for the decode engine (ISSUE 9 tentpole).

The training side already shards weights Megatron-style over a mesh axis
(`parallel/tensor_parallel.py`); this module applies the same scheme to
the *serving* hot path so model size and KV-pool capacity scale past one
chip's HBM. Everything is pure annotation: params, variables, and the
engine's carried state pytree are placed with `NamedSharding`s on a 1-D
``tp`` mesh, and GSPMD partitions the existing jitted decode / prefill /
restore / COW program families — no program body changes.

Sharding plan (the weight-update-sharding / array-redistribution papers,
arxiv 2004.13336 / 2112.01075: pick shardings so the steady-state loop
needs no resharding collectives):

  - attention Wq/Wk/Wv column-parallel (head dim over ``tp``), Wo
    row-parallel, bias replicated — one all-reduce per attention block;
  - FFN up-projection column-parallel (hidden dim over ``tp``), its bias
    sharded with it, down-projection row-parallel — one all-reduce per
    FFN;
  - embeddings, LayerNorms, and the OUTPUT head replicated. The training
    scheme column-shards any activated DenseLayer, which would include a
    softmax output head — sharding the vocab axis would put softmax
    reductions and a per-token host gather of the sampled distribution
    on the hot path, so decode keeps heads replicated;
  - the KV cache (contiguous ``k``/``v`` stripes and paged
    ``k_pages``/``v_pages`` alike) sharded on its **Hkv head axis**:
    each device holds only its heads' rows, so at fixed per-device HBM
    the pool holds ``tp×`` the blocks. ``pos``, token ids, the ``live``
    mask, and the host-authoritative block tables are replicated —
    paged attention, prefix restore remaps, COW, and preemption are
    host-side table surgery that never notices the mesh.

Consequence (provable, see :func:`collective_counts`): the per-token
decode program contains ONLY the two all-reduces per transformer block
(attention output + FFN output). Anything else — an all-gather,
all-to-all, reduce-scatter, or collective-permute — means a chosen
sharding disagreed with the dataflow and GSPMD inserted a resharding on
the per-token path; the runtime audit (tests/test_sharded_decode.py)
fails the build when that happens.

CPU verification: ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
gives N host "devices" whose collectives run the real partitioner, so
token-identity and the collective budget are tier-1-testable without
accelerators (tests/conftest.py already forces an 8-device mesh).
"""
from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TP_AXIS = "tp"

# HLO collective ops that may legitimately appear in a tensor-parallel
# decode step (reductions of row-parallel partial sums) vs. the ones
# whose presence means a resharding snuck onto the hot path
REDUCE_COLLECTIVES = ("all-reduce",)
RESHARD_COLLECTIVES = ("all-gather", "all-to-all", "reduce-scatter",
                       "collective-permute", "ragged-all-to-all")
ALL_COLLECTIVES = REDUCE_COLLECTIVES + RESHARD_COLLECTIVES


def decode_mesh(n_devices: int, axis: str = TP_AXIS) -> Mesh:
    """1-D tensor-parallel mesh over the first ``n_devices`` local
    devices. The serving CLI's ``--tp N`` resolves through here."""
    devs = jax.devices()
    if n_devices > len(devs):
        raise ValueError(
            f"tp={n_devices} needs {n_devices} devices, have {len(devs)} "
            "(CPU: set XLA_FLAGS=--xla_force_host_platform_device_count)")
    return Mesh(np.asarray(devs[:n_devices]), (axis,))


def decode_param_specs(conf, axis: str = TP_AXIS) -> Dict[str, Dict[str, P]]:
    """Per-vertex PartitionSpecs for DECODE: the training Megatron scheme
    (`parallel.tensor_parallel._tp_specs_for_graph`) with every output
    vertex forced replicated — a column-parallel softmax head would shard
    the vocab axis and put softmax collectives + a sharded host readback
    on the per-token path."""
    from ..parallel.tensor_parallel import _tp_specs_for_graph
    specs = _tp_specs_for_graph(conf, axis)
    for out in conf.network_outputs:
        specs[out] = {}
    return specs


def shard_decode_params(net, mesh: Mesh, axis: str = TP_AXIS
                        ) -> Tuple[Dict, Dict]:
    """(sharded params, replicated variables) COPIES placed on ``mesh``.

    Unlike the training-side `shard_transformer_tp` this never mutates
    ``net`` — the caller's net keeps its original placement, so a
    1-device reference engine over the same net stays single-device.
    A spec dim the mesh axis does not divide falls back to replication
    with a warning (same contract as training)."""
    specs = decode_param_specs(net.conf, axis)
    repl = NamedSharding(mesh, P())

    def put(arr, spec, pname):
        for d, ax in enumerate(spec):
            if ax is not None and arr.shape[d] % mesh.shape[ax]:
                import warnings
                warnings.warn(
                    f"shard_decode_params: {pname} dim {d} (size "
                    f"{arr.shape[d]}) is not divisible by mesh axis "
                    f"'{ax}' ({mesh.shape[ax]}); replicating this param",
                    stacklevel=4)
                spec = P()
                break
        return jax.device_put(arr, NamedSharding(mesh, spec))

    params = {
        name: {pname: put(arr, specs.get(name, {}).get(pname, P()),
                          f"{name}/{pname}")
               for pname, arr in lp.items()}
        for name, lp in net.params.items()}
    variables = jax.tree_util.tree_map(
        lambda a: jax.device_put(a, repl), net.variables)
    return params, variables


def state_shardings(states, mesh: Mesh, axis: str = TP_AXIS):
    """NamedSharding pytree for the engine's carried state: K/V rows
    (contiguous ``k``/``v``: [n_slots, L, Hkv, Dh]; paged
    ``k_pages``/``v_pages``: [pages, block, Hkv, Dh]) sharded on the
    head axis 2, everything else (``pos``, recurrent h/c) replicated."""
    from .kvpool import PAGE_KEYS
    repl = NamedSharding(mesh, P())
    # axis 2 is Hkv for K/V rows ([.., .., Hkv, Dh]) AND for the int8
    # dequant scale pages ([pages, block, Hkv]) — one spec serves both;
    # PAGE_KEYS is the single source of truth for what counts as
    # shared pool storage (a new page-array key lands here for free)
    head = NamedSharding(mesh, P(None, None, axis))
    out = {}
    for key, st in states.items():
        if isinstance(st, dict) and (
                ("k" in st and "v" in st) or "k_pages" in st):
            out[key] = {k: (head if k in ("k", "v") + PAGE_KEYS
                            else repl) for k in st}
        else:
            out[key] = jax.tree_util.tree_map(lambda _: repl, st)
    return out


def storage_shardings(storage, mesh: Mesh, axis: str = TP_AXIS):
    """Shardings for the contiguous-mode side prefix pool's storage
    (``{layer: {"k"/"v": [n_blocks, block, Hkv, Dh]}}``): same head-axis
    split as the live cache, so restore's block gather never reshards."""
    head = NamedSharding(mesh, P(None, None, axis))
    return jax.tree_util.tree_map(lambda _: head, storage)


def paged_kernel_shard_specs(axis: str = TP_AXIS) -> Dict[str, P]:
    """PartitionSpecs for the fused paged-decode kernel's shard_map
    (ops/pallas_kernels.py, ISSUE 15) — the SAME head-axis split the
    engine already places its state with, so handing the kernel its
    per-shard view costs zero resharding collectives:

      - ``rows``: q [B, 1, H, Dh] / page arrays [pages, block, Hkv, Dh]
        / the kernel output — head axis 2 over ``axis`` (matches
        `state_shardings`' page placement and the column-parallel Wq's
        propagated q split);
      - ``scales``: int8 dequant scale pages [pages, block, Hkv] —
        trailing head axis over ``axis``;
      - ``host``: block tables and ``pos`` — replicated, like every
        other host-authoritative input.

    The kernel grids over the LOCAL Hkv shard inside the shard_map and
    never communicates, so the per-token program keeps the Megatron
    budget: exactly the two all-reduces per transformer block
    (:func:`assert_hot_path_collectives` verifies this with the kernel
    engaged, same audit as the XLA path)."""
    return {"rows": P(None, None, axis, None),
            "scales": P(None, None, axis),
            "host": P()}


def kv_heads_shardable(abstract_states, attn_keys, tp: int) -> bool:
    """True when every attention layer's Hkv head count divides by
    ``tp`` — the hard requirement for head-sharding the KV cache (param
    sharding can fall back per-weight; the cache cannot)."""
    return bool(attn_keys) and all(
        abstract_states[key]["k"].shape[2] % tp == 0 for key in attn_keys)


# -- compiled-program collective audit -------------------------------------
def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Instances of each collective op in compiled HLO text. Ops are
    counted at their definition site (`... = shape all-reduce(...)`,
    async variants included) so operand references and metadata lines
    don't inflate the count."""
    return {op: len(re.findall(
        rf"\s{re.escape(op)}(?:-start)?\(", hlo_text))
        for op in ALL_COLLECTIVES}


def decode_program_hlo(engine) -> str:
    """Compiled HLO of the engine's per-token decode program, lowered
    with the exact arg placements live dispatch uses (same jit cache
    key — auditing a warmed engine compiles nothing new)."""
    from .kvpool import SCRATCH_BLOCK
    ids = engine._dev_array(np.zeros((engine.n_slots,), np.int32))
    live = engine._dev_array(np.zeros((engine.n_slots,), bool))
    if engine.paged:
        nb = engine.table_buckets[0]
        table = engine._dev_array(
            np.full((engine.n_slots, nb), SCRATCH_BLOCK, np.int32))
        lowered = engine._jstep.lower(engine._params, engine._variables,
                                      ids, live, table, engine._states)
    else:
        lowered = engine._jstep.lower(engine._params, engine._variables,
                                      ids, live, engine._states)
    return lowered.compile().as_text()


def prefill_program_hlo(engine, bucket: Optional[int] = None) -> str:
    """Compiled HLO of one prefill-chunk program (smallest bucket by
    default) — the other half of the steady-state program family."""
    from .kvpool import SCRATCH_BLOCK
    b = bucket or engine.prefill_buckets[0]
    slot0 = engine._dev_index(0)
    one = engine._dev_index(1)
    ids = engine._dev_array(np.zeros((b,), np.int32))
    if engine.paged:
        nb = engine.table_buckets[0]
        table = engine._dev_array(
            np.full((engine.n_slots, nb), SCRATCH_BLOCK, np.int32))
        lowered = engine._jprefill.lower(
            engine._params, engine._variables, slot0, ids, one, table,
            engine._states)
    else:
        lowered = engine._jprefill.lower(
            engine._params, engine._variables, slot0, ids, one,
            engine._states)
    return lowered.compile().as_text()


def verify_program_hlo(engine) -> str:
    """Compiled HLO of the engine's speculative multi-token VERIFY
    program (ISSUE 10) with live-dispatch placements — it must obey the
    same zero-resharding discipline as decode: the chain axis is just a
    wider T, so the Megatron all-reduce count per block is unchanged."""
    from .kvpool import SCRATCH_BLOCK
    ids = engine._dev_array(
        np.zeros((engine.n_slots, engine.speculate + 1), np.int32))
    live = engine._dev_array(np.zeros((engine.n_slots,), bool))
    if engine.paged:
        nb = engine.table_buckets[0]
        table = engine._dev_array(
            np.full((engine.n_slots, nb), SCRATCH_BLOCK, np.int32))
        lowered = engine._jverify.lower(
            engine._params, engine._variables, ids, live, table,
            engine._states)
    else:
        lowered = engine._jverify.lower(
            engine._params, engine._variables, ids, live,
            engine._states)
    return lowered.compile().as_text()


def draft_program_hlo(engine) -> str:
    """Compiled HLO of the speculative DRAFT step (the shallow-exit /
    draft-net single-token forward): a prefix of the target's blocks
    under the same param specs, so its per-token program is bounded by
    the same audit — zero resharding, <= 2 all-reduces per draft
    block."""
    ids = engine._dev_array(np.zeros((engine.n_slots,), np.int32))
    live = engine._dev_array(np.zeros((engine.n_slots,), bool))
    lowered = engine._jdraft_step.lower(
        engine._draft_params, engine._draft_variables, ids, live,
        engine._draft_states)
    return lowered.compile().as_text()


def assert_hot_path_collectives(counts: Dict[str, int],
                                n_blocks: int) -> None:
    """The collective-count budget for a per-token program: resharding
    collectives are FORBIDDEN, and reduce ops are bounded by the
    Megatron shape (attention + FFN all-reduce per block, with slack
    for partitioner-introduced mask/select reductions)."""
    bad = {op: n for op in RESHARD_COLLECTIVES
           if (n := counts.get(op, 0))}
    if bad:
        raise AssertionError(
            f"resharding collective(s) on the per-token hot path: {bad} "
            "— a chosen sharding disagrees with the dataflow "
            "(see inference/sharding.py docstring)")
    budget = 4 * n_blocks
    n_reduce = sum(counts.get(op, 0) for op in REDUCE_COLLECTIVES)
    if n_reduce > budget:
        raise AssertionError(
            f"{n_reduce} reduce collectives in the per-token program, "
            f"budget is {budget} (4 per transformer block): the program "
            "is reducing more than the two Megatron partial sums per "
            "block")
