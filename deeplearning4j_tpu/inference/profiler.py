"""Performance-attribution & SLO plane for the decode engine (ISSUE 11).

The flight recorder (`inference/trace.py`) answers *"what happened to
request X"*; the metrics registry (`inference/metrics.py`) answers *"how
is the fleet doing"*. Neither answers *"why is the fleet at 31% MFU"* or
*"is p99 burning the SLO"* — the attribution questions a serving stack
must answer continuously, not in a one-off profiling session (the
DeepSpark discipline, arXiv 1602.08191: commodity-cluster monitoring is
always-on, floor-gated overhead). Three pieces:

**Step-phase profiler** (:class:`StepPhaseProfiler`). The scheduler loop
stamps each iteration's phases — batch assembly (``admit``), prefill
dispatch, draft rounds, pool ops + candidate assembly (``pool``), the
decode dispatch + device wait (``decode``), host-side acceptance
(``accept``), speculative verify (``verify``), and the metric/trace
flush (``flush``) — into per-phase histograms
(``decode_step_phase_seconds{phase=...}``) and a rolling step-time
decomposition, so "decode is slow" resolves into "68% of step time is
the decode dispatch, 19% is host acceptance". Appends are plain
scheduler-thread float arithmetic on preallocated state (the trace
buffer's lock-free single-writer discipline): the armed-vs-disarmed
step-time ratio is floor-gated ≥ 0.95 (`bench.py profiler_overhead`).

**Cost attribution** (:func:`program_costs` + the profiler's rolling
FLOPs window). At warmup, every compiled program family (decode /
prefill / verify / draft, per bucket, at the engine's actual mesh size)
is lowered through ``.lower(...).compile().cost_analysis()`` — the XLA
cost model's FLOPs and bytes-accessed per invocation. Live dispatch
counts (stamped by the scheduler per dispatch) combine with the table
into derived gauges: ``decode_tokens_per_sec``,
``device_flops_per_sec``, ``device_mfu_estimate`` (against a per-device
peak — a documented *estimate*: the peak comes from a device-kind table
or ``DL4J_PEAK_FLOPS``), ``device_hbm_gbps`` and per-family FLOPs
shares — exposed on `/metrics`, `/info`, and `GET /debug/engine`.

**SLO monitor** (:class:`SLOMonitor`). Sliding-window p50/p95/p99 per
HTTP route plus **multi-window burn rates** against a configurable
latency objective (`serve --slo-p99-ms`): with a p99 objective the
error budget is 1% of requests over the objective; the burn rate is the
observed violation fraction divided by that budget, evaluated over a
fast (default 60 s) and a slow (default 600 s) window — the standard
SRE multiwindow alert shape, so a one-request blip cannot page and a
slow leak still does. ``burning()`` feeds the PR 7 degradation ladder a
SECOND escalation input (`supervisor.EngineSupervisor(slo=...)`): the
ladder becomes latency-aware, not just queue-pressure-aware, and
de-escalates only when BOTH inputs are calm (no flapping when one input
oscillates around its watermark). Route histograms record exemplars
carrying the ``request_id``, so a Prometheus histogram bucket links
straight back into the flight recorder.
"""
from __future__ import annotations

import collections
import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry, default_registry

__all__ = ["StepPhaseProfiler", "SLOMonitor", "program_costs",
           "device_peak_flops", "burn_verdict"]


def burn_verdict(fast: float, slow: float, fast_burn: float = 6.0,
                 slow_burn: float = 3.0) -> Tuple[bool, bool]:
    """(burning, calm) from a (fast, slow) burn-rate pair — THE single
    home of the multiwindow thresholds: burning = both windows over
    their burn thresholds (a fast-only spike or a slow-window leftover
    stays quiet); calm = fast window inside budget (< 1.0), the much
    stricter de-escalation gate, so escalate/de-escalate use hysteresis
    instead of one shared edge. Module-level so the fleet federation
    (`serving/telemetry.py`) applies the SAME verdict to fleet-level
    burn rates that each replica's :class:`SLOMonitor` applies locally
    — the router's SLO-aware admission must not disagree with the
    replicas about what "burning" means."""
    return fast >= fast_burn and slow >= slow_burn, fast < 1.0

# iteration phases, in stamp order (engine._step_once lap boundaries)
PHASES = ("admit", "prefill", "draft", "pool", "decode", "accept",
          "verify", "flush")

# nominal per-device peak FLOP/s by device kind — the MFU denominator.
# Deliberately coarse (dense fp32/bf16 marketing peaks): MFU here is an
# ESTIMATE for attribution ("are we at 3% or 30%"), not a benchmark
# claim. Override with DL4J_PEAK_FLOPS or the peak_flops knob.
DEVICE_PEAK_FLOPS = {
    "TPU v2": 22.5e12, "TPU v3": 61.25e12, "TPU v4": 137.5e12,
    "TPU v5 lite": 98.5e12, "TPU v5p": 229.5e12, "TPU v6 lite": 459e12,
}
_CPU_PEAK_FLOPS = 1e11  # ~a few AVX cores; CPU MFU is order-of-magnitude


# net -> {engine-shape tuple -> cost table}; weak on the net so the
# cache dies with the model (see program_costs)
_COST_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _cost_shape_key(engine) -> tuple:
    # paged_kernel is part of the key: the fused and XLA decode
    # programs have different FLOPs/bytes tables, and two engines over
    # one net may run different modes (the bench's A/B does)
    return (engine.tp, engine.paged, engine.speculate, engine.kv_dtype,
            engine.n_slots, tuple(engine.table_buckets),
            tuple(engine.prefill_buckets),
            getattr(engine, "paged_kernel", None))


def cached_program_costs(engine):
    """The cost table for this (net, engine shape) if some earlier
    engine already computed it, else None — the free path a REBUILT
    engine's warmup takes so a post-recovery engine comes up attributed
    without re-tracing the family inside the recovery window."""
    try:
        per_net = _COST_CACHE.get(engine.net)
    except TypeError:
        return None
    if per_net is None:
        return None
    cached = per_net.get(_cost_shape_key(engine))
    return dict(cached) if cached is not None else None


def device_peak_flops(default: float = _CPU_PEAK_FLOPS) -> float:
    """Per-device peak FLOP/s estimate: ``DL4J_PEAK_FLOPS`` env override,
    else the device-kind table, else ``default``."""
    env = os.environ.get("DL4J_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:
        return default
    for key, peak in DEVICE_PEAK_FLOPS.items():
        if key.lower() in str(kind).lower():
            return peak
    return default


def _cost_of(lowered) -> Dict[str, float]:
    """FLOPs / bytes-accessed of one lowered program via the XLA cost
    model. `Lowered.cost_analysis()` runs HLO-level analysis WITHOUT the
    backend compile (milliseconds, so warming a many-bucket paged family
    costs tracing time, not a second full compile pass); older jax falls
    back to ``.compile().cost_analysis()``. The result is a dict (newer
    jax) or a one-per-device list of dicts; missing keys read 0 (some
    backends publish partial models)."""
    try:
        c = lowered.cost_analysis()
    except (AttributeError, NotImplementedError):
        c = lowered.compile().cost_analysis()
    if isinstance(c, (list, tuple)):
        c = c[0] if c else {}
    return {"flops": float(c.get("flops", 0.0) or 0.0),
            "bytes": float(c.get("bytes accessed", 0.0) or 0.0)}


def program_costs(engine) -> Dict[Tuple[str, int], Dict[str, float]]:
    """Per-invocation FLOPs/bytes for every program family the engine
    dispatches, keyed ``(family, bucket)`` — the SAME keys the scheduler
    stamps per live dispatch (:meth:`StepPhaseProfiler.count`), so the
    rolling FLOPs window is a pure table lookup.

    Families and keys:
      - ``decode``: one entry per table bucket (paged) or ``(decode, 0)``
      - ``prefill``: one entry per chunk bucket (paged programs lowered
        at the SMALLEST table bucket — table width is second-order next
        to the chunk's matmuls, and lowering every (chunk × table) pair
        would double warmup for a rounding error)
      - ``verify`` (speculation): per table bucket / ``0``
      - ``draft`` / ``draft_prefill``: the shallow-exit draft's step and
        chunk programs

    Lowering uses the engine's live-dispatch placements (the
    `sharding.decode_program_hlo` contract), so the numbers are for the
    engine's ACTUAL mesh size. The AOT ``.lower()`` path never touches
    the jit call caches — CompileCounter budgets are unaffected.

    Cached per (net, engine shape): the supervisor rebuilds engines
    from a factory over the SAME net on every crash recovery / drain
    swap, and re-tracing the whole family per restart would tax the
    very recovery window warmup exists to protect. The cache is a
    WeakKeyDictionary on the net — it dies with the model.
    """
    import numpy as np

    from .kvpool import SCRATCH_BLOCK

    shape_key = _cost_shape_key(engine)
    cached = cached_program_costs(engine)
    if cached is not None:
        return cached
    try:
        per_net = _COST_CACHE.setdefault(engine.net, {})
    except TypeError:  # unweakrefable stub net (tests): just recompute
        per_net = None

    out: Dict[Tuple[str, int], Dict[str, float]] = {}
    params, variables = engine._params, engine._variables
    ids = engine._dev_array(np.zeros((engine.n_slots,), np.int32))
    live = engine._dev_array(np.zeros((engine.n_slots,), bool))
    slot0 = engine._dev_index(0)
    one = engine._dev_index(1)

    def table(nb):
        return engine._dev_array(
            np.full((engine.n_slots, nb), SCRATCH_BLOCK, np.int32))

    if engine.paged:
        for nb in engine.table_buckets:
            out[("decode", nb)] = _cost_of(engine._jstep.lower(
                params, variables, ids, live, table(nb), engine._states))
        # name which buckets run the fused Pallas kernel vs the XLA
        # gather (ISSUE 15): the .lower() calls above traced every
        # bucket through the paged_decode_attention seam, so the
        # engagement registry has a verdict per bucket — /debug/engine's
        # cost table carries it as a per-invocation "fused" flag
        try:
            fused = engine.paged_kernel_status()["buckets"]
            for nb in engine.table_buckets:
                out[("decode", nb)]["fused"] = (
                    1.0 if fused.get(nb) else 0.0)
        except Exception:
            pass  # a stub engine without the status surface (tests)
        nb0 = engine.table_buckets[0]
        for b in engine.prefill_buckets:
            cids = engine._dev_array(np.zeros((b,), np.int32))
            out[("prefill", b)] = _cost_of(engine._jprefill.lower(
                params, variables, slot0, cids, one, table(nb0),
                engine._states))
    else:
        out[("decode", 0)] = _cost_of(engine._jstep.lower(
            params, variables, ids, live, engine._states))
        for b in engine.prefill_buckets:
            cids = engine._dev_array(np.zeros((b,), np.int32))
            out[("prefill", b)] = _cost_of(engine._jprefill.lower(
                params, variables, slot0, cids, one, engine._states))
    if engine.speculate:
        ids2 = engine._dev_array(
            np.zeros((engine.n_slots, engine.speculate + 1), np.int32))
        if engine.paged:
            for nb in engine.table_buckets:
                out[("verify", nb)] = _cost_of(engine._jverify.lower(
                    params, variables, ids2, live, table(nb),
                    engine._states))
        else:
            out[("verify", 0)] = _cost_of(engine._jverify.lower(
                params, variables, ids2, live, engine._states))
        dp, dv = engine._draft_params, engine._draft_variables
        out[("draft", 0)] = _cost_of(engine._jdraft_step.lower(
            dp, dv, ids, live, engine._draft_states))
        for b in engine.prefill_buckets:
            cids = engine._dev_array(np.zeros((b,), np.int32))
            out[("draft_prefill", b)] = _cost_of(
                engine._jdraft_prefill.lower(dp, dv, slot0, cids, one,
                                             engine._draft_states))
    if per_net is not None:
        per_net[shape_key] = dict(out)
    return out


class StepPhaseProfiler:
    """Per-iteration phase decomposition + rolling cost attribution.

    Hot-path discipline (the flight recorder's): every method the
    scheduler loop calls is plain float/dict arithmetic on preallocated
    SINGLE-WRITER state — no locks, no allocation beyond one small ring
    entry per iteration, no device work. Cross-thread readers
    (`GET /debug/engine`, the gauges) see GIL-atomic snapshots one
    iteration stale at worst. ``enabled=False`` reduces every call to
    one attribute test (`bench.py profiler_overhead` gates the armed
    cost at ≥ 0.95 step-time ratio).
    """

    def __init__(self, metrics: Optional[MetricsRegistry] = None, *,
                 enabled: bool = True, window: int = 256,
                 gauge_every: int = 16,
                 peak_flops: Optional[float] = None,
                 peak_hbm_gbps: float = 100.0):
        self.enabled = bool(enabled)
        self.metrics = metrics if metrics is not None else default_registry()
        self.peak_flops = (float(peak_flops) if peak_flops
                           else device_peak_flops())
        self.peak_hbm_gbps = float(peak_hbm_gbps)
        self._window = max(8, int(window))
        self._gauge_every = max(1, int(gauge_every))
        # cumulative per-phase seconds (scheduler-thread-only writes;
        # dict preallocated so the hot path never inserts keys)
        self.phase_seconds: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._hists = {
            p: self.metrics.histogram(
                "decode_step_phase_seconds",
                help="scheduler iteration wall time by phase "
                     "(admit=batch assembly, pool=pool ops + candidate "
                     "assembly, accept=host-side token acceptance)",
                labels={"phase": p})
            for p in PHASES} if self.enabled else {}
        # rolling ring of per-iteration (ts_end, flops, bytes, tokens):
        # preallocated, single-writer, index = iterations % window — the
        # trace ring's overwrite semantics
        self._ring: List[Optional[tuple]] = [None] * self._window
        self.iterations = 0
        # per-invocation cost table from program_costs(); {} until the
        # engine's warmup ingests it (dispatch counts still accumulate)
        self.costs: Dict[Tuple[str, int], Dict[str, float]] = {}
        self.flops_total = 0.0
        self.bytes_total = 0.0
        self.tokens_total = 0
        # per-family cumulative dispatch/flops tallies (debug snapshot +
        # flops-share gauges)
        self.family_dispatches: Dict[str, int] = {}
        self.family_flops: Dict[str, float] = {}
        # per-iteration scratch, reset by iter_begin
        self._iter_counts: List[Tuple[str, int, int]] = []
        self._t_iter = 0.0
        self._t_lap = 0.0
        self._t_gauges = 0.0  # last _refresh_gauges wall time
        if self.enabled:
            m = self.metrics
            self._g_tps = m.gauge(
                "decode_tokens_per_sec",
                help="rolling emitted-token rate over the last "
                     f"{self._window} scheduler iterations")
            self._g_flops = m.gauge(
                "device_flops_per_sec",
                help="rolling attributed device FLOP rate (XLA "
                     "cost_analysis per program family x live dispatch "
                     "counts)")
            self._g_mfu = m.gauge(
                "device_mfu_estimate",
                help="model-FLOPs-utilization estimate: attributed "
                     "FLOP/s over the per-device peak (device-kind "
                     "table or DL4J_PEAK_FLOPS) x mesh size")
            self._g_hbm = m.gauge(
                "device_hbm_gbps",
                help="rolling attributed memory traffic (cost_analysis "
                     "bytes accessed), GB/s")
            self._g_share: Dict[str, object] = {}

    # -- hot path (scheduler thread only) ----------------------------------
    def iter_begin(self) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        self._t_iter = now
        self._t_lap = now
        if self._iter_counts:
            self._iter_counts.clear()

    def lap(self, phase: str) -> None:
        """Close the current phase: everything since the previous lap
        (or iter_begin) is attributed to ``phase``. Skipped phases cost
        one monotonic read and land only in the decomposition (sub-µs
        laps stay out of the histograms, which would otherwise drown in
        zeros from phases that did not run this iteration)."""
        if not self.enabled:
            return
        now = time.monotonic()
        dt = now - self._t_lap
        self._t_lap = now
        self.phase_seconds[phase] += dt
        if dt >= 1e-6:
            self._hists[phase].record(dt)

    def count(self, family: str, bucket: int, n: int = 1) -> None:
        """Stamp ``n`` dispatches of ``(family, bucket)`` this iteration
        (one list append; costs resolve at iter_end)."""
        if self.enabled:
            self._iter_counts.append((family, bucket, n))

    def iter_end(self, tokens: int = 0) -> None:
        """Close the iteration: resolve this iteration's dispatches
        against the cost table, push one ring entry, and refresh the
        derived gauges every ``gauge_every`` iterations."""
        if not self.enabled:
            return
        self.lap("flush")
        flops = bytes_ = 0.0
        for family, bucket, n in self._iter_counts:
            c = self.costs.get((family, bucket))
            self.family_dispatches[family] = \
                self.family_dispatches.get(family, 0) + n
            if c is not None:
                f = c["flops"] * n
                flops += f
                bytes_ += c["bytes"] * n
                self.family_flops[family] = \
                    self.family_flops.get(family, 0.0) + f
        self.flops_total += flops
        self.bytes_total += bytes_
        self.tokens_total += tokens
        now = time.monotonic()
        idx = self.iterations % self._window
        # increment BEFORE the store: a concurrent rates() reader
        # indexes ring[iterations % window] as the oldest entry — with
        # store-then-increment it could grab the entry written
        # microseconds ago (dt ~ 0, rates report ~0 on a busy engine);
        # this order makes its view at worst one entry shorter
        self.iterations += 1
        self._ring[idx] = (
            now, self.flops_total, self.bytes_total, self.tokens_total)
        if self.iterations % self._gauge_every == 0:
            self._refresh_gauges(now)

    def idle_tick(self) -> None:
        """Called from the scheduler's IDLE wait (10 Hz wakeups):
        iter_end never runs on idle passes, so without this the rate
        gauges would freeze at the last busy burst's values forever —
        a Prometheus scrape of an hour-idle engine reporting 2000
        tokens/s. Recomputing against the fixed oldest ring entry
        decays the rates as the window stretches. Throttled to ~1 Hz;
        the idle-path cost is one monotonic read and a compare."""
        if not self.enabled or not self.iterations:
            return
        now = time.monotonic()
        if now - self._t_gauges >= 1.0:
            self._refresh_gauges(now)

    def _refresh_gauges(self, now: float) -> None:
        self._t_gauges = now
        oldest = self._ring[self.iterations % self._window] \
            if self.iterations >= self._window else self._ring[0]
        if oldest is None:
            return
        t0, f0, b0, k0 = oldest
        dt = now - t0
        if dt <= 0:
            return
        self._g_tps.set((self.tokens_total - k0) / dt)
        fps = (self.flops_total - f0) / dt
        self._g_flops.set(fps)
        if self.peak_flops > 0:
            self._g_mfu.set(fps / self.peak_flops)
        self._g_hbm.set((self.bytes_total - b0) / dt / 1e9)
        total_f = sum(self.family_flops.values())
        if total_f > 0:
            for fam, f in self.family_flops.items():
                g = self._g_share.get(fam)
                if g is None:
                    g = self._g_share[fam] = self.metrics.gauge(
                        "program_family_flops_share",
                        help="fraction of attributed device FLOPs by "
                             "program family (cumulative)",
                        labels={"family": fam})
                g.set(f / total_f)

    # -- ingestion / read side ---------------------------------------------
    def ingest_costs(self, costs: Dict[Tuple[str, int],
                                       Dict[str, float]]) -> None:
        """Install the per-invocation cost table (engine.warmup calls
        this with :func:`program_costs`' output). One dict rebind —
        GIL-atomic against the scheduler thread's lookups."""
        self.costs = dict(costs)

    def rates(self) -> Dict[str, float]:
        """Rolling-window rates (the gauges' values, computed fresh)."""
        if not self.iterations:
            return {"tokens_per_sec": 0.0, "flops_per_sec": 0.0,
                    "mfu_estimate": 0.0, "hbm_gbps": 0.0}
        now = time.monotonic()
        oldest = self._ring[self.iterations % self._window] \
            if self.iterations >= self._window else self._ring[0]
        if oldest is None:
            return {"tokens_per_sec": 0.0, "flops_per_sec": 0.0,
                    "mfu_estimate": 0.0, "hbm_gbps": 0.0}
        t0, f0, b0, k0 = oldest
        dt = max(1e-9, now - t0)
        fps = (self.flops_total - f0) / dt
        return {
            "tokens_per_sec": round((self.tokens_total - k0) / dt, 3),
            "flops_per_sec": round(fps, 1),
            "mfu_estimate": round(fps / self.peak_flops, 6)
            if self.peak_flops > 0 else 0.0,
            "hbm_gbps": round((self.bytes_total - b0) / dt / 1e9, 6),
        }

    def decomposition(self) -> Dict[str, dict]:
        """Cumulative per-phase seconds and shares — where every second
        of scheduler wall time went since construction."""
        totals = dict(self.phase_seconds)  # one-pass copy, atomic items
        whole = sum(totals.values()) or 1.0
        return {p: {"seconds": round(s, 6),
                    "share": round(s / whole, 4)}
                for p, s in totals.items()}

    def cost_snapshot(self) -> dict:
        """The `/debug/engine` ``costs`` block: per-family per-bucket
        invocation costs, cumulative dispatch counts, FLOPs shares, and
        the live rolling rates."""
        costs = dict(self.costs)
        fams = sorted({f for f, _ in costs})
        total_f = sum(self.family_flops.values())
        return {
            "per_invocation": {
                f: {str(b): costs[(f2, b)]
                    for f2, b in sorted(costs) if f2 == f}
                for f in fams},
            "dispatches": dict(self.family_dispatches),
            "family_flops_share": {
                f: round(v / total_f, 4)
                for f, v in sorted(self.family_flops.items())}
            if total_f > 0 else {},
            "peak_flops_per_device": self.peak_flops,
            **self.rates(),
        }


class SLOMonitor:
    """Sliding-window latency percentiles + multiwindow burn rate per
    HTTP route, against one p99 latency objective.

    ``objective_p99_s``: the target — None tracks percentiles but never
    burns (``burning()`` is False, the ladder input stays cold).
    ``error_budget``: allowed violation fraction (0.01 for a p99
    objective). ``burning()`` requires the burn rate over BOTH windows
    to exceed its threshold — fast-window-only spikes and slow-window
    leftovers both stay quiet, the standard multiwindow page condition.
    ``min_samples``: a window holding fewer samples reads burn 0 — on a
    2-requests-a-minute server one slow request is a 100% violation
    fraction, and without the floor that single blip would walk the
    ladder to full admission rejection.
    ``calm()`` is a stricter de-escalation gate (fast burn under 1.0 =
    currently spending within budget) so escalate/de-escalate use
    hysteresis instead of one shared edge.

    Thread-safe: observations arrive from every HTTP handler thread;
    one small lock guards the per-route deques (same discipline as the
    metrics instruments). ``clock`` is injectable so the burn-rate
    algebra is frozen-clock-testable like the supervisor's watchdog.
    """

    def __init__(self, objective_p99_s: Optional[float] = None, *,
                 error_budget: float = 0.01,
                 fast_window_s: float = 60.0, slow_window_s: float = 600.0,
                 fast_burn: float = 6.0, slow_burn: float = 3.0,
                 min_samples: int = 20, max_samples: int = 4096,
                 metrics: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.objective_p99_s = (float(objective_p99_s)
                                if objective_p99_s else None)
        self.error_budget = float(error_budget)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.min_samples = int(min_samples)
        self.max_samples = int(max_samples)
        self.metrics = metrics if metrics is not None else default_registry()
        self._clock = clock
        self._lock = threading.Lock()
        # per-route (ts, latency) deques: maxlen bounds memory, expired
        # heads popleft in O(expired) per observe — a list rebuild here
        # would be an O(max_samples) copy under the lock on EVERY
        # request once traffic outlives the slow window
        self._samples: Dict[str, collections.deque] = {}
        self._hists: Dict[str, object] = {}
        self._observed = 0
        m = self.metrics
        self._g_fast = m.gauge(
            "slo_burn_rate_fast",
            help="latency-SLO burn rate over the fast window "
                 "(violation fraction / error budget; 1.0 = spending "
                 "exactly the budget)")
        self._g_slow = m.gauge(
            "slo_burn_rate_slow",
            help="latency-SLO burn rate over the slow window")
        if self.objective_p99_s is not None:
            m.gauge("slo_objective_p99_ms",
                    help="configured p99 latency objective"
                    ).set(self.objective_p99_s * 1e3)
        self._g_p99: Dict[str, object] = {}

    def observe(self, route: str, latency_s: float,
                request_id: Optional[str] = None) -> None:
        """Record one request's end-to-end latency for ``route``. The
        labeled histogram keeps an exemplar carrying ``request_id``, so
        a Prometheus bucket links back into `GET /trace`."""
        now = self._clock()
        latency_s = float(latency_s)
        with self._lock:
            hist = self._hists.get(route)
            if hist is None:
                hist = self._hists[route] = self.metrics.histogram(
                    "http_route_latency_seconds",
                    help="end-to-end HTTP request latency by route "
                         "(exemplars carry the request_id)",
                    labels={"route": route})
            buf = self._samples.get(route)
            if buf is None:
                buf = self._samples[route] = collections.deque(
                    maxlen=self.max_samples)
            buf.append((now, latency_s))
            horizon = now - self.slow_window_s
            while buf and buf[0][0] < horizon:
                buf.popleft()
            self._observed += 1
            n = self._observed
        hist.record(latency_s, exemplar=request_id)
        if n % 16 == 0 or n <= 4:
            self._refresh_gauges(now)

    def _window_samples(self, window_s: float, now: float,
                        route: Optional[str] = None) -> List[float]:
        t0 = now - window_s
        with self._lock:
            bufs = ([self._samples.get(route) or ()]
                    if route is not None
                    else list(self._samples.values()))
            return [lat for buf in bufs for ts, lat in buf if ts >= t0]

    def percentiles(self, route: str,
                    window_s: Optional[float] = None) -> dict:
        """Sliding-window p50/p95/p99 (seconds) for one route."""
        now = self._clock()
        vals = sorted(self._window_samples(
            window_s if window_s is not None else self.slow_window_s,
            now, route))
        if not vals:
            return {"n": 0}

        def q(f):
            return vals[min(len(vals) - 1, int(f * len(vals)))]
        return {"n": len(vals), "p50": round(q(0.50), 6),
                "p95": round(q(0.95), 6), "p99": round(q(0.99), 6)}

    def burn_rates(self, now: Optional[float] = None
                   ) -> Tuple[float, float]:
        """(fast, slow) burn rates across all routes: the fraction of
        windowed requests over the objective, divided by the error
        budget. 0.0 when no objective is set or a window holds fewer
        than ``min_samples`` — a near-empty window's violation fraction
        is statistically meaningless and (at 1-2 samples) would let one
        slow request escalate the ladder to admission rejection."""
        if self.objective_p99_s is None:
            return 0.0, 0.0
        now = self._clock() if now is None else now
        out = []
        for w in (self.fast_window_s, self.slow_window_s):
            vals = self._window_samples(w, now)
            if len(vals) < max(1, self.min_samples):
                out.append(0.0)
                continue
            frac = sum(1 for v in vals if v > self.objective_p99_s) \
                / len(vals)
            out.append(frac / self.error_budget)
        return out[0], out[1]

    def _verdict(self, fast: float, slow: float) -> Tuple[bool, bool]:
        """(burning, calm) from an already-computed burn-rate pair —
        delegates to the module-level :func:`burn_verdict` (shared with
        the fleet federation) at this monitor's thresholds."""
        return burn_verdict(fast, slow, self.fast_burn, self.slow_burn)

    def pressure(self, now: Optional[float] = None) -> Tuple[bool, bool]:
        """(burning, calm) from ONE burn-rate computation — the ladder
        evaluates both every watchdog tick, and each burn_rates() call
        scans every route's sample window under the lock, so the paired
        form halves the per-tick cost versus burning()+calm()."""
        fast, slow = self.burn_rates(now)
        return self._verdict(fast, slow)

    def burning(self, now: Optional[float] = None) -> bool:
        """True when the SLO is burning hot enough to escalate."""
        return self.pressure(now)[0]

    def calm(self, now: Optional[float] = None) -> bool:
        """True when latency is inside budget on the fast window."""
        return self.pressure(now)[1]

    def _refresh_gauges(self, now: float) -> None:
        fast, slow = self.burn_rates(now)
        self._g_fast.set(fast)
        self._g_slow.set(slow)
        with self._lock:
            routes = list(self._samples)
        for route in routes:
            p = self.percentiles(route, self.fast_window_s)
            if not p.get("n"):
                continue
            g = self._g_p99.get(route)
            if g is None:
                g = self._g_p99[route] = self.metrics.gauge(
                    "slo_route_p99_ms",
                    help="fast-window p99 latency by route",
                    labels={"route": route})
            g.set(p["p99"] * 1e3)

    def brief(self) -> dict:
        """The burn-rate headline WITHOUT per-route percentiles — what
        `supervisor.status()` embeds in every `/readyz` body. One
        burn_rates() window scan, no sorting: percentiles sort each
        route's full slow-window buffer, and paying that per liveness
        probe (orchestrators poll readiness constantly) would contend
        the SLO lock against every handler's observe(). The full
        per-route picture stays on `/info` and `/debug/engine`."""
        fast, slow = self.burn_rates()
        return {
            "objective_p99_ms": (round(self.objective_p99_s * 1e3, 3)
                                 if self.objective_p99_s else None),
            "burn_rate_fast": round(fast, 4),
            "burn_rate_slow": round(slow, 4),
            "burning": self._verdict(fast, slow)[0],
        }

    def snapshot(self) -> dict:
        """The `/debug/engine` / `/info` SLO block."""
        now = self._clock()
        fast, slow = self.burn_rates(now)
        with self._lock:
            routes = list(self._samples)
        return {
            "objective_p99_ms": (round(self.objective_p99_s * 1e3, 3)
                                 if self.objective_p99_s else None),
            "burn_rate_fast": round(fast, 4),
            "burn_rate_slow": round(slow, 4),
            # reuse the pair computed above rather than re-scanning
            "burning": self._verdict(fast, slow)[0],
            "routes": {
                r: {k: (round(v * 1e3, 3) if k != "n" else v)
                    for k, v in self.percentiles(r).items()}
                for r in routes},
        }
