"""Serving-side SLO metrics: counters, gauges, streaming latency histograms.

No reference counterpart (the 0.4-era serving route had zero telemetry);
modeled on the Prometheus client-library data model — monotonic counters,
point-in-time gauges, and fixed-bucket histograms whose percentiles are
estimated by linear interpolation inside the owning bucket (the same
estimate `histogram_quantile()` computes server-side).

Lock discipline: one small lock per instrument, held only for a couple of
scalar updates (`record` does no allocation on the hot path). Python's GIL
already serializes the increments; the locks exist so `snapshot()` never
reads a torn (count, sum) pair and so the module stays correct on GIL-free
builds.

Everything is wired through a :class:`MetricsRegistry` so the serving stack
(`serving/server.py` `GET /metrics`), the UI snapshot poster
(`ui/listeners.post_serving_metrics`) and the bench harness all read ONE
source of truth.

Robustness instruments (`inference/supervisor.py`, `inference/
failpoints.py`): ``engine_restarts_total`` / ``requests_recovered_total``
/ ``requests_abandoned_total`` / ``requests_shed_total`` counters,
``serving_ready`` (the /readyz verdict as a scrapeable 0/1 — its
high-water ``_max`` being 1 with value 0 is the "was ready, went
unready" alert) and ``degradation_level`` gauges, and
``failpoint_triggers_total`` counting injected chaos faults.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional


class Counter:
    """Monotonic event counter (requests served, tokens emitted, ...)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        # single int, GIL-atomic read: a scrape racing inc() sees the
        # count from one instant earlier — a correct counter value. The
        # lock exists for the read-modify-write in inc(), not for this.
        return self._value  # graftlint: disable=CC005


class Gauge:
    """Point-in-time value (queue depth, active slots, ...). Also tracks the
    high-water mark — saturation shows up even between scrapes."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0
        self._max = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            if v > self._max:
                self._max = float(v)

    @property
    def value(self) -> float:
        # GIL-atomic single-float read (see Counter.value): any value
        # this returns was the gauge's value at some instant
        return self._value  # graftlint: disable=CC005

    @property
    def max(self) -> float:
        # GIL-atomic; _max is monotonic within a process lifetime, so a
        # stale read only ever under-reports by the in-flight sample
        return self._max  # graftlint: disable=CC005


def _log_buckets(lo: float, hi: float, per_decade: int) -> List[float]:
    """Log-spaced bucket upper bounds covering [lo, hi]."""
    n = int(math.ceil(math.log10(hi / lo) * per_decade))
    return [lo * 10 ** (i / per_decade) for i in range(n + 1)]


class Histogram:
    """Streaming histogram over fixed log-spaced buckets.

    O(1) per `record` (binary search over ~40 static bounds), O(buckets)
    per percentile query — no reservoir, no per-sample storage, so a
    million-request day costs the same memory as an idle server. Default
    bounds cover 10 microseconds .. 100 seconds, the full range a serving
    latency can plausibly land in.
    """

    def __init__(self, name: str, lo: float = 1e-5, hi: float = 100.0,
                 per_decade: int = 6):
        self.name = name
        self._bounds = _log_buckets(lo, hi, per_decade)
        self._counts = [0] * (len(self._bounds) + 1)  # + overflow bucket
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def record(self, v: float) -> None:
        v = float(v)
        lo, hi = 0, len(self._bounds)
        while lo < hi:  # first bound >= v (bisect_left on static bounds)
            mid = (lo + hi) // 2
            if self._bounds[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        with self._lock:
            self._counts[lo] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        # GIL-atomic single-int read; consistent multi-field snapshots
        # go through _state() under the lock (the CC004 fix)
        return self._count  # graftlint: disable=CC005

    @property
    def mean(self) -> float:
        # derived from one locked copy: a lock-free (_sum, _count) pair
        # read racing record() could pair a new sum with an old count
        _, count, total, _, _ = self._state()
        return total / count if count else 0.0

    def _state(self) -> tuple:
        """ONE consistent copy of the mutable state, under ONE lock
        acquisition. Every read path (percentile, snapshot) derives from
        a single copy — graftlint CC004 caught the original version
        reading `_min`/`_max` lock-free and re-locking per percentile, so
        a `/metrics` scrape racing `record()` could report a (count, sum)
        pair from one instant and quantiles/extremes from another (e.g.
        a count-1 histogram whose p99 was not its only sample)."""
        with self._lock:
            return (list(self._counts), self._count, self._sum,
                    self._min, self._max)

    def _estimate(self, counts: List[int], total: int, vmin: float,
                  vmax: float, q: float) -> float:
        """Quantile over a consistent state copy: walk to the owning
        bucket, interpolate linearly inside it, clamp to min/max."""
        if not total:
            return 0.0
        target = q * total
        seen = 0
        for i, c in enumerate(counts):
            if seen + c >= target and c:
                lo = self._bounds[i - 1] if i else 0.0
                hi = self._bounds[i] if i < len(self._bounds) else vmax
                frac = (target - seen) / c
                est = lo + (hi - lo) * frac
                return min(max(est, vmin), vmax)
            seen += c
        return vmax

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1])."""
        counts, count, _, vmin, vmax = self._state()
        return self._estimate(counts, count, vmin, vmax, q)

    def snapshot(self) -> dict:
        counts, count, total, vmin, vmax = self._state()
        if not count:
            return {"count": 0}
        return {
            "count": count,
            "sum": round(total, 6),
            "mean": round(total / count, 6),
            "min": round(vmin, 6),
            "max": round(vmax, 6),
            "p50": round(self._estimate(counts, count, vmin, vmax, 0.50), 6),
            "p95": round(self._estimate(counts, count, vmin, vmax, 0.95), 6),
            "p99": round(self._estimate(counts, count, vmin, vmax, 0.99), 6),
        }


class MetricsRegistry:
    """Named instrument registry; `get_or_create` semantics so call sites
    never race on registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        # derived name -> (numerator, denominator) counters, computed at
        # snapshot time (a stored value would go stale between scrapes)
        self._ratios: Dict[str, tuple] = {}
        self._t0 = time.monotonic()

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name)
            return self._gauges[name]

    def histogram(self, name: str, **kw) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(name, **kw)
            return self._histograms[name]

    def ratio(self, name: str, numerator, denominator) -> None:
        """Register a derived numerator/denominator instrument — any two
        objects with a ``.value`` (Counter OR Gauge): the prefix-cache
        hit rate is hit-token / looked-up-token counters, the paged-KV
        ``kv_pool_utilization`` is live-blocks / capacity gauges.
        Evaluated fresh at every snapshot so it can never go stale
        between scrapes; an empty denominator reads as 0.0."""
        with self._lock:
            self._ratios[name] = (numerator, denominator)

    def snapshot(self) -> dict:
        """One JSON-able view of everything — the `GET /metrics` body and
        the UI snapshot payload."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            ratios = dict(self._ratios)
        return {
            "uptime_sec": round(time.monotonic() - self._t0, 3),
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: {"value": g.value, "max": g.max}
                       for n, g in sorted(gauges.items())},
            "histograms": {n: h.snapshot()
                           for n, h in sorted(histograms.items())},
            "ratios": {n: round(num.value / den.value, 6)
                       if den.value else 0.0
                       for n, (num, den) in sorted(ratios.items())},
        }

    def render_text(self) -> str:
        """Prometheus-flavored text exposition (`/metrics?format=text`).

        Parity with the JSON snapshot: the text form used to drop the
        saturation signals the JSON carries — gauge high-water marks,
        histogram extremes, process uptime — so a Prometheus-only
        consumer could not see that a queue ever peaked between scrapes.
        Now every gauge also exposes ``{name}_max``, every non-empty
        histogram ``{name}_min``/``{name}_max``, and the process its
        ``uptime_sec``."""
        snap = self.snapshot()
        lines = ["# TYPE uptime_sec gauge",
                 f"uptime_sec {snap['uptime_sec']}"]
        for n, v in snap["counters"].items():
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {v}")
        for n, g in snap["gauges"].items():
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {g['value']}")
            lines.append(f"# TYPE {n}_max gauge")
            lines.append(f"{n}_max {g['max']}")
        for n, v in snap.get("ratios", {}).items():
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {v}")
        for n, h in snap["histograms"].items():
            lines.append(f"# TYPE {n} summary")
            if h.get("count"):
                # Prometheus summary convention: fractional quantile
                # labels ({quantile="0.5"}), not percentile numbers
                for q, frac in (("p50", "0.5"), ("p95", "0.95"),
                                ("p99", "0.99")):
                    lines.append(f'{n}{{quantile="{frac}"}} {h[q]}')
                lines.append(f"{n}_sum {h['sum']}")
                lines.append(f"{n}_min {h['min']}")
                lines.append(f"{n}_max {h['max']}")
            lines.append(f"{n}_count {h.get('count', 0)}")
        return "\n".join(lines) + "\n"


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """Process-wide registry for components not handed an explicit one."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default
