"""Serving-side SLO metrics: counters, gauges, streaming latency histograms.

No reference counterpart (the 0.4-era serving route had zero telemetry);
modeled on the Prometheus client-library data model — monotonic counters,
point-in-time gauges, and fixed-bucket histograms whose percentiles are
estimated by linear interpolation inside the owning bucket (the same
estimate `histogram_quantile()` computes server-side).

Lock discipline: one small lock per instrument, held only for a couple of
scalar updates (`record` does no allocation on the hot path). Python's GIL
already serializes the increments; the locks exist so `snapshot()` never
reads a torn (count, sum) pair and so the module stays correct on GIL-free
builds.

Everything is wired through a :class:`MetricsRegistry` so the serving stack
(`serving/server.py` `GET /metrics`), the UI snapshot poster
(`ui/listeners.post_serving_metrics`) and the bench harness all read ONE
source of truth.

Instruments carry **HELP text** (registered at creation —
``registry.counter(name, help=...)``; first non-empty help wins) and
optional **labels** (``labels={"phase": "decode"}``): labeled series of
one family share a base name and differ by label set, the Prometheus
data model. The registry key — and the JSON-snapshot / text-exposition
key — is the canonical series string (``name{phase="decode"}``), so
unlabeled instruments are bit-compatible with the pre-label format.

Three expositions, kept in name/value parity (test-asserted):
  - ``snapshot()``       -> JSON (`GET /metrics`; carries a ``help`` map)
  - ``render_text()``    -> the legacy Prometheus-FLAVORED summary text
                            (`?format=text`: quantile labels, _min/_max)
  - ``render_prometheus()`` -> real Prometheus/OpenMetrics exposition
                            (`?format=prometheus`): ``# HELP``/``# TYPE``
                            per family, cumulative ``_bucket{le=...}``
                            histogram series, and OpenMetrics exemplars
                            (``# {request_id="r000042"} v ts``) linking
                            a bucket back into `GET /trace`.

Robustness instruments (`inference/supervisor.py`, `inference/
failpoints.py`): ``engine_restarts_total`` / ``requests_recovered_total``
/ ``requests_abandoned_total`` / ``requests_shed_total`` counters,
``serving_ready`` (the /readyz verdict as a scrapeable 0/1 — its
high-water ``_max`` being 1 with value 0 is the "was ready, went
unready" alert) and ``degradation_level`` gauges, and
``failpoint_triggers_total`` counting injected chaos faults.
Attribution instruments (`inference/profiler.py`):
``decode_step_phase_seconds{phase=...}`` histograms, the
``device_mfu_estimate`` / ``device_flops_per_sec`` /
``decode_tokens_per_sec`` gauges, and the
``http_route_latency_seconds{route=...}`` SLO histograms with
request-id exemplars.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Tuple


def _escape_label(v) -> str:
    """Prometheus/OpenMetrics label-value escaping (backslash, quote,
    newline). Internal label values are constants, but exemplar labels
    carry the CLIENT-controlled request id — one unescaped quote there
    would corrupt the whole exposition for every consumer."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def series_key(name: str, labels: Optional[dict]) -> str:
    """Canonical series string: ``name`` or ``name{k="v",...}`` (sorted
    label keys, the Prometheus exposition form — so a registry key IS a
    valid text-exposition series name)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def _suffixed(key: str, name: str, suffix: str) -> str:
    """``key`` with ``suffix`` appended to the BASE name (labels keep
    their place: ``lat{route="/x"}`` + ``_max`` ->
    ``lat_max{route="/x"}``)."""
    return name + suffix + key[len(name):]


def _with_label(key: str, name: str, extra: str, suffix: str = "") -> str:
    """``key`` with ``suffix`` on the base name and one more
    ``k="v"`` label spliced in: ``lat{route="/x"}`` + ``_bucket`` +
    ``le="0.1"`` -> ``lat_bucket{route="/x",le="0.1"}``."""
    rest = key[len(name):]  # "" or "{...}"
    inner = rest[1:-1] + "," + extra if rest.startswith("{") else extra
    return f"{name}{suffix}{{{inner}}}"


class Counter:
    """Monotonic event counter (requests served, tokens emitted, ...)."""

    def __init__(self, name: str, help: str = "",
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.key = series_key(name, labels)
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        # single int, GIL-atomic read: a scrape racing inc() sees the
        # count from one instant earlier — a correct counter value. The
        # lock exists for the read-modify-write in inc(), not for this.
        return self._value  # graftlint: disable=CC005


class Gauge:
    """Point-in-time value (queue depth, active slots, ...). Also tracks the
    high-water mark — saturation shows up even between scrapes."""

    def __init__(self, name: str, help: str = "",
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.key = series_key(name, labels)
        self._lock = threading.Lock()
        self._value = 0.0
        self._max = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            if v > self._max:
                self._max = float(v)

    @property
    def value(self) -> float:
        # GIL-atomic single-float read (see Counter.value): any value
        # this returns was the gauge's value at some instant
        return self._value  # graftlint: disable=CC005

    @property
    def max(self) -> float:
        # GIL-atomic; _max is monotonic within a process lifetime, so a
        # stale read only ever under-reports by the in-flight sample
        return self._max  # graftlint: disable=CC005


def _log_buckets(lo: float, hi: float, per_decade: int) -> List[float]:
    """Log-spaced bucket upper bounds covering [lo, hi]."""
    n = int(math.ceil(math.log10(hi / lo) * per_decade))
    return [lo * 10 ** (i / per_decade) for i in range(n + 1)]


def estimate_quantile(bounds: List[float], counts: List[int], total: int,
                      vmin: float, vmax: float, q: float) -> float:
    """Quantile estimate over fixed-bucket counts (``counts`` has one
    overflow slot beyond ``bounds``): walk to the owning bucket,
    interpolate linearly inside it, clamp to [vmin, vmax] — the same
    estimate ``histogram_quantile()`` computes server-side. Module-level
    so the fleet federation path (`serving/telemetry.py`) can recompute
    p50/p95/p99 from MERGED bucket counts with the exact algorithm the
    per-replica `Histogram` uses (pass ``vmin=0, vmax=math.inf`` when
    the extremes are unknown, e.g. parsed from a Prometheus scrape)."""
    if not total:
        return 0.0
    target = q * total
    seen = 0
    for i, c in enumerate(counts):
        if seen + c >= target and c:
            lo = bounds[i - 1] if i else 0.0
            hi = bounds[i] if i < len(bounds) else \
                (vmax if math.isfinite(vmax) else bounds[-1])
            frac = (target - seen) / c
            est = lo + (hi - lo) * frac
            return min(max(est, vmin), vmax)
        seen += c
    return vmax if math.isfinite(vmax) else bounds[-1]


def merge_histograms(snapshots: List[dict]) -> dict:
    """Merge N :meth:`Histogram.bucket_snapshot` dicts into one — the
    fleet-federation primitive (ISSUE 12): per-bucket counts sum, count
    and sum add, min/max recombine as min-of-mins / max-of-maxes, and
    p50/p95/p99 are re-estimated over the merged buckets. Merging two
    snapshots is EXACTLY equivalent to one histogram having observed
    the union stream (property-tested in tests/test_telemetry.py),
    because fixed canonical bucket boundaries make the bucket counts a
    sufficient statistic.

    Mismatched bucket boundaries raise ``ValueError`` — silently
    summing bucket i of two different layouts would fabricate a
    latency distribution, which is strictly worse than failing the
    scrape."""
    snaps = [s for s in snapshots if s is not None]
    if not snaps:
        return {"count": 0}
    bounds = list(snaps[0]["bounds"])
    for s in snaps[1:]:
        b = s["bounds"]
        if len(b) != len(bounds) or any(
                not math.isclose(x, y, rel_tol=1e-9)
                for x, y in zip(b, bounds)):
            raise ValueError(
                "cannot merge histograms with mismatched bucket "
                f"boundaries ({len(bounds)} bounds starting "
                f"{bounds[:2]} vs {len(b)} starting {list(b)[:2]}): "
                "summing unlike buckets would silently fabricate the "
                "distribution")
        if len(s["counts"]) != len(bounds) + 1:
            raise ValueError(
                f"histogram counts length {len(s['counts'])} != "
                f"bounds+overflow {len(bounds) + 1}")
    counts = [0] * (len(bounds) + 1)
    count, total = 0, 0.0
    vmin, vmax = math.inf, -math.inf
    for s in snaps:
        for i, c in enumerate(s["counts"]):
            counts[i] += int(c)
        count += int(s.get("count", sum(s["counts"])))
        total += float(s.get("sum", 0.0))
        vmin = min(vmin, s.get("min", math.inf))
        vmax = max(vmax, s.get("max", -math.inf))
    if not count:
        return {"bounds": bounds, "counts": counts, "count": 0,
                "sum": 0.0}
    if not math.isfinite(vmin):
        vmin = 0.0  # extremes unknown (e.g. parsed from a Prometheus
        # scrape, which carries no _min/_max): estimate clamps fall
        # back to the bucket edges
    if vmax == -math.inf:
        vmax = math.inf
    return {
        "bounds": bounds, "counts": counts, "count": count,
        "sum": round(total, 9), "min": vmin, "max": vmax,
        "p50": estimate_quantile(bounds, counts, count, vmin, vmax, .50),
        "p95": estimate_quantile(bounds, counts, count, vmin, vmax, .95),
        "p99": estimate_quantile(bounds, counts, count, vmin, vmax, .99),
    }


class Histogram:
    """Streaming histogram over fixed log-spaced buckets.

    O(1) per `record` (binary search over ~40 static bounds), O(buckets)
    per percentile query — no reservoir, no per-sample storage, so a
    million-request day costs the same memory as an idle server. Default
    bounds cover 10 microseconds .. 100 seconds, the full range a serving
    latency can plausibly land in.

    ``record(v, exemplar="r000042")`` keeps the newest exemplar per
    bucket (value, label, wall time) — the OpenMetrics bucket→trace
    link `render_prometheus` emits.
    """

    def __init__(self, name: str, lo: float = 1e-5, hi: float = 100.0,
                 per_decade: int = 6, help: str = "",
                 labels: Optional[dict] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.key = series_key(name, labels)
        self._bounds = _log_buckets(lo, hi, per_decade)
        self._counts = [0] * (len(self._bounds) + 1)  # + overflow bucket
        self._exemplars: List[Optional[tuple]] = \
            [None] * (len(self._bounds) + 1)
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def record(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        lo, hi = 0, len(self._bounds)
        while lo < hi:  # first bound >= v (bisect_left on static bounds)
            mid = (lo + hi) // 2
            if self._bounds[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        with self._lock:
            self._counts[lo] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if exemplar is not None:
                self._exemplars[lo] = (v, exemplar, time.time())

    @property
    def count(self) -> int:
        # GIL-atomic single-int read; consistent multi-field snapshots
        # go through _state() under the lock (the CC004 fix)
        return self._count  # graftlint: disable=CC005

    @property
    def mean(self) -> float:
        # derived from one locked copy: a lock-free (_sum, _count) pair
        # read racing record() could pair a new sum with an old count
        _, count, total, _, _ = self._state()
        return total / count if count else 0.0

    def _state(self) -> tuple:
        """ONE consistent copy of the mutable state, under ONE lock
        acquisition. Every read path (percentile, snapshot) derives from
        a single copy — graftlint CC004 caught the original version
        reading `_min`/`_max` lock-free and re-locking per percentile, so
        a `/metrics` scrape racing `record()` could report a (count, sum)
        pair from one instant and quantiles/extremes from another (e.g.
        a count-1 histogram whose p99 was not its only sample)."""
        with self._lock:
            return (list(self._counts), self._count, self._sum,
                    self._min, self._max)

    def buckets(self) -> tuple:
        """(upper bounds, per-bucket counts incl. overflow, exemplars,
        count, sum) — ONE consistent locked copy, the Prometheus
        renderer's input: count/sum taken under a separate acquisition
        could disagree with the ``+Inf`` cumulative when a record()
        lands between the two, and OpenMetrics validators reject a
        scrape whose ``_count`` != last bucket."""
        with self._lock:
            return (list(self._bounds), list(self._counts),
                    list(self._exemplars), self._count, self._sum)

    def _estimate(self, counts: List[int], total: int, vmin: float,
                  vmax: float, q: float) -> float:
        """Quantile over a consistent state copy: walk to the owning
        bucket, interpolate linearly inside it, clamp to min/max (the
        shared :func:`estimate_quantile`, so per-replica and merged
        fleet estimates use one algorithm)."""
        return estimate_quantile(self._bounds, counts, total, vmin,
                                 vmax, q)

    def bucket_snapshot(self) -> dict:
        """Merge-ready state (:func:`merge_histograms` input): bounds,
        NON-cumulative per-bucket counts (incl. the overflow slot),
        count/sum/min/max — one consistent locked copy."""
        counts, count, total, vmin, vmax = self._state()
        return {"bounds": list(self._bounds), "counts": counts,
                "count": count, "sum": total,
                "min": vmin if count else math.inf,
                "max": vmax if count else -math.inf}

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1])."""
        counts, count, _, vmin, vmax = self._state()
        return self._estimate(counts, count, vmin, vmax, q)

    def snapshot(self) -> dict:
        counts, count, total, vmin, vmax = self._state()
        if not count:
            return {"count": 0}
        return {
            "count": count,
            "sum": round(total, 6),
            "mean": round(total / count, 6),
            "min": round(vmin, 6),
            "max": round(vmax, 6),
            "p50": round(self._estimate(counts, count, vmin, vmax, 0.50), 6),
            "p95": round(self._estimate(counts, count, vmin, vmax, 0.95), 6),
            "p99": round(self._estimate(counts, count, vmin, vmax, 0.99), 6),
        }


class MetricsRegistry:
    """Named instrument registry; `get_or_create` semantics so call sites
    never race on registration. Instruments are keyed by their canonical
    series string (base name + sorted labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        # derived name -> (numerator, denominator) counters, computed at
        # snapshot time (a stored value would go stale between scrapes)
        self._ratios: Dict[str, tuple] = {}
        self._help: Dict[str, str] = {}
        self._t0 = time.monotonic()

    def _register_help(self, name: str, help: str) -> None:
        # caller holds self._lock; first non-empty help wins so every
        # series of a family documents itself once
        if help and not self._help.get(name):
            self._help[name] = help

    def counter(self, name: str, help: str = "",
                labels: Optional[dict] = None) -> Counter:
        key = series_key(name, labels)
        with self._lock:
            if key not in self._counters:
                self._counters[key] = Counter(name, help, labels)
            self._register_help(name, help)
            return self._counters[key]

    def gauge(self, name: str, help: str = "",
              labels: Optional[dict] = None) -> Gauge:
        key = series_key(name, labels)
        with self._lock:
            if key not in self._gauges:
                self._gauges[key] = Gauge(name, help, labels)
            self._register_help(name, help)
            return self._gauges[key]

    def histogram(self, name: str, help: str = "",
                  labels: Optional[dict] = None, **kw) -> Histogram:
        key = series_key(name, labels)
        with self._lock:
            if key not in self._histograms:
                self._histograms[key] = Histogram(name, help=help,
                                                  labels=labels, **kw)
            self._register_help(name, help)
            return self._histograms[key]

    def ratio(self, name: str, numerator, denominator,
              help: str = "") -> None:
        """Register a derived numerator/denominator instrument — any two
        objects with a ``.value`` (Counter OR Gauge): the prefix-cache
        hit rate is hit-token / looked-up-token counters, the paged-KV
        ``kv_pool_utilization`` is live-blocks / capacity gauges.
        Evaluated fresh at every snapshot so it can never go stale
        between scrapes; an empty denominator reads as 0.0."""
        with self._lock:
            self._ratios[name] = (numerator, denominator)
            self._register_help(name, help)

    def help_text(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._help)

    def snapshot(self) -> dict:
        """One JSON-able view of everything — the `GET /metrics` body and
        the UI snapshot payload. Keys are canonical series strings
        (identical to the bare name for unlabeled instruments); the
        ``help`` map documents each base name once."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            ratios = dict(self._ratios)
            help_map = {n: h for n, h in self._help.items() if h}
        return {
            "uptime_sec": round(time.monotonic() - self._t0, 3),
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: {"value": g.value, "max": g.max}
                       for k, g in sorted(gauges.items())},
            "histograms": {k: h.snapshot()
                           for k, h in sorted(histograms.items())},
            "ratios": {n: round(num.value / den.value, 6)
                       if den.value else 0.0
                       for n, (num, den) in sorted(ratios.items())},
            "help": help_map,
        }

    def render_text(self) -> str:
        """Prometheus-FLAVORED text exposition (`/metrics?format=text`,
        the legacy summary form: quantile labels, ``_min``/``_max``).

        Parity with the JSON snapshot: the text form used to drop the
        saturation signals the JSON carries — gauge high-water marks,
        histogram extremes, process uptime — so a Prometheus-only
        consumer could not see that a queue ever peaked between scrapes.
        Now every gauge also exposes ``{name}_max``, every non-empty
        histogram ``{name}_min``/``{name}_max``, the process its
        ``uptime_sec`` — and every documented family its ``# HELP``
        line (once per base name, like ``# TYPE``)."""
        snap = self.snapshot()
        with self._lock:
            metas = ([(c.key, c.name, "counter")
                      for c in self._counters.values()]
                     + [(g.key, g.name, "gauge")
                        for g in self._gauges.values()]
                     + [(h.key, h.name, "summary")
                        for h in self._histograms.values()]
                     + [(n, n, "gauge") for n in self._ratios])
        base_of = {key: name for key, name, _ in metas}
        help_map = snap.get("help", {})
        lines = ["# TYPE uptime_sec gauge",
                 f"uptime_sec {snap['uptime_sec']}"]
        typed = set()

        def head(key: str, kind: str) -> None:
            name = base_of.get(key, key)
            if name not in typed:
                typed.add(name)
                if help_map.get(name):
                    lines.append(f"# HELP {name} {help_map[name]}")
                lines.append(f"# TYPE {name} {kind}")

        for k, v in snap["counters"].items():
            head(k, "counter")
            lines.append(f"{k} {v}")
        for k, g in snap["gauges"].items():
            head(k, "gauge")
            lines.append(f"{k} {g['value']}")
            name = base_of.get(k, k)
            if name + "_max" not in typed:
                typed.add(name + "_max")
                lines.append(f"# TYPE {name}_max gauge")
            lines.append(f"{_suffixed(k, name, '_max')} {g['max']}")
        for k, v in snap.get("ratios", {}).items():
            head(k, "gauge")
            lines.append(f"{k} {v}")
        for k, h in snap["histograms"].items():
            head(k, "summary")
            name = base_of.get(k, k)
            if h.get("count"):
                # Prometheus summary convention: fractional quantile
                # labels ({quantile="0.5"}), not percentile numbers
                for q, frac in (("p50", "0.5"), ("p95", "0.95"),
                                ("p99", "0.99")):
                    series = _with_label(k, name, f'quantile="{frac}"')
                    lines.append(f"{series} {h[q]}")
                lines.append(f"{_suffixed(k, name, '_sum')} {h['sum']}")
                lines.append(f"{_suffixed(k, name, '_min')} {h['min']}")
                lines.append(f"{_suffixed(k, name, '_max')} {h['max']}")
            lines.append(f"{_suffixed(k, name, '_count')} "
                         f"{h.get('count', 0)}")
        return "\n".join(lines) + "\n"

    def render_prometheus(self, openmetrics: bool = True) -> str:
        """Real Prometheus/OpenMetrics exposition
        (`/metrics?format=prometheus`, also served on Accept
        negotiation): ``# HELP``/``# TYPE`` once per family, label
        support throughout, cumulative ``_bucket{le="..."}`` histogram
        series ending in ``le="+Inf"``, and ``_sum``/``_count``.

        ``openmetrics=True`` (the default, and what
        ``?format=prometheus`` / an openmetrics Accept header serve)
        additionally emits exemplars (``# {request_id="..."} value
        ts``) on buckets whose newest sample carried one — the
        bucket→flight-recorder link — and the required ``# EOF``
        terminator; the content type must then be
        ``application/openmetrics-text``. ``openmetrics=False`` is the
        plain Prometheus 0.0.4 text form (a legacy ``text/plain``
        scraper's parser rejects the ``#`` exemplar marker after a
        value, so exemplars are omitted there)."""
        with self._lock:
            counters = sorted(self._counters.values(), key=lambda i: i.key)
            gauges = sorted(self._gauges.values(), key=lambda i: i.key)
            histograms = sorted(self._histograms.values(),
                                key=lambda i: i.key)
            ratios = sorted(self._ratios.items())
            help_map = {n: h for n, h in self._help.items() if h}
        lines = ["# TYPE uptime_sec gauge",
                 f"uptime_sec {round(time.monotonic() - self._t0, 3)}"]
        typed = set()

        def head(name: str, kind: str) -> None:
            if name not in typed:
                typed.add(name)
                if help_map.get(name):
                    lines.append(f"# HELP {name} {help_map[name]}")
                lines.append(f"# TYPE {name} {kind}")

        for c in counters:
            # strict OpenMetrics: a counter FAMILY 'foo' exposes
            # samples 'foo_total' — families here are literally named
            # *_total, so the HELP/TYPE lines carry the stripped
            # family name (what prometheus_client's OM encoder does);
            # sample lines keep the full name. The 0.0.4 form keeps
            # the full name in TYPE too (the legacy convention).
            fam = (c.name[:-6] if openmetrics
                   and c.name.endswith("_total") else c.name)
            if fam is not c.name and help_map.get(c.name) \
                    and fam not in help_map:
                help_map[fam] = help_map[c.name]
            head(fam, "counter")
            lines.append(f"{c.key} {c.value}")
        for g in gauges:
            head(g.name, "gauge")
            lines.append(f"{g.key} {g.value}")
        for g in gauges:
            head(g.name + "_max", "gauge")
            lines.append(f"{_suffixed(g.key, g.name, '_max')} {g.max}")
        for n, (num, den) in ratios:
            head(n, "gauge")
            lines.append(f"{n} {round(num.value / den.value, 6) if den.value else 0.0}")
        for h in histograms:
            head(h.name, "histogram")
            bounds, counts, exemplars, count, total = h.buckets()
            cum = 0
            for i, (bound, c) in enumerate(
                    zip(list(bounds) + ["+Inf"], counts)):
                cum += c
                le = bound if bound == "+Inf" else f"{bound:.9g}"
                line = _with_label(h.key, h.name, f'le="{le}"',
                                   "_bucket") + f" {cum}"
                ex = exemplars[i]
                if ex is not None and openmetrics:
                    v, label, ts = ex
                    line += (f' # {{request_id="{_escape_label(label)}"'
                             f"}} {round(v, 9)} {round(ts, 3)}")
                lines.append(line)
            lines.append(f"{_suffixed(h.key, h.name, '_sum')} "
                         f"{round(total, 9)}")
            lines.append(f"{_suffixed(h.key, h.name, '_count')} {count}")
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """Process-wide registry for components not handed an explicit one."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default
