"""Speculative decoding support: shallow-exit draft nets, the
token-identical acceptance rule, and best-of-n COW fork groups.

Speculative decoding (Leviathan et al.; Medusa/EAGLE-style self-drafting)
attacks the per-token decode cost from ROADMAP item 3: every output token
of a solo decode pays one full forward pass. A cheap *draft* model
proposes gamma tokens, and the target model *verifies* all of them in ONE
multi-token forward (the same per-position-logits machinery chunked
prefill already built) — accepted tokens cost gamma-plus-one-for-one
instead of one-for-one.

Three pieces live here because they are engine-independent and unit-
testable in isolation:

  - :func:`shallow_draft_conf` / :func:`build_shallow_draft` — the
    SELF-speculative draft: a derived ComputationGraph that runs only the
    first K transformer blocks of the target and jumps straight to the
    target's own output head (early exit). Its params are the target's
    params BY REFERENCE (no copies, no training): the draft is literally
    the target truncated at depth K, so it costs ~K/N of a forward and
    needs no separate checkpoint. Requires the pre-LN residual-trunk
    graph shape `models/zoo.transformer_lm` builds (attention blocks
    combined through ElementWise residual adds, single-input head
    chain); anything else must pass an explicit ``draft_net``.
  - :func:`accept_tokens` — THE acceptance rule. Verification samples
    from the TARGET distribution at each position with the sequence's
    own RNG, in order, stopping at the first position whose sampled
    token diverges from the draft. Because every emitted token is drawn
    from exactly the distribution (and exactly the RNG state) solo
    decoding would have used, speculative output is token-identical to
    non-speculative output BY CONSTRUCTION — greedy and seeded-sampled
    alike. Draft quality affects only the acceptance rate (speed),
    never the output.
  - :class:`ForkGroup` — best-of-n bookkeeping: n candidates over one
    prompt share the prompt's paged KV blocks through copy-on-write
    forks (`inference/kvpool.py` block tables + the engine's `_cow_fn`).
    The first-submitted candidate is the *primary*; followers wait in
    the queue until the primary's prefill publishes the prompt blocks,
    then restore them as a zero-copy block-table remap.
"""
from __future__ import annotations

import copy
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.runtime import ledger_note
from ..models.sampling import sample_logits


class ForkGroup:
    """Shared bookkeeping for one best-of-n candidate set.

    Threading: constructed by the submitting thread; ``primary_handle``
    is bound by the FIRST ``engine.submit(..., fork=group)`` (the server
    submits candidates sequentially, so there is no bind race), and
    ``published`` is written only by the scheduler thread. Cross-thread
    readers see GIL-atomic stores; a one-iteration-stale view only
    delays a follower's restore by one admission pass, never corrupts.
    The group survives engine crash recovery by riding the supervisor's
    resubmission kwargs — after a swap, ``published`` may refer to a
    pool the new engine no longer has, which degrades to a cold prefill
    (a trie miss), not a deadlock.
    """

    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"fork group size must be >= 1, got {n}")
        self.n = int(n)
        self.published = False
        self.primary_handle = None

    def bind_primary(self, handle) -> None:
        """First submitted candidate becomes the primary."""
        if self.primary_handle is None:
            self.primary_handle = handle

    def waiting(self, handle) -> bool:
        """True while ``handle`` (a follower) should stay queued: the
        primary is still alive and has not yet published the prompt's
        blocks. A dead/finished primary opens the gate uncondition-
        ally — followers then prefill cold rather than wait forever."""
        p = self.primary_handle
        return (not self.published and p is not None and handle is not p
                and not p.done())


def submit_fork_group(submit: Callable, prompt_ids: Sequence[int], n: int,
                      max_new_tokens: int, *, seed: int = 0,
                      request_id: Optional[str] = None, **kw) -> List:
    """Fan one prompt out into ``n`` fork-group candidates through
    ``submit`` (the engine's or the supervisor's — THE single home of
    the best-of-n submission protocol). Candidate i samples with
    ``seed + i`` and, when a base ``request_id`` is given, carries
    ``<id>.cI`` so every candidate correlates back to the HTTP
    request's header id. If a later submit fails (queue full, ladder,
    engine recovering), every ALREADY-submitted candidate is cancelled
    before the error propagates — a partial group must not keep
    decoding into handles nobody holds."""
    group = ForkGroup(n)
    handles: List = []
    key = None
    try:
        for i in range(n):
            handles.append(submit(
                prompt_ids, max_new_tokens, seed=seed + i, fork=group,
                request_id=f"{request_id}.c{i}" if request_id else None,
                **kw))
            # fork-group membership ref (graftleak's runtime ledger):
            # one per submitted candidate, keyed by the group's primary
            # so the engine's per-candidate request-end checks skip it
            if key is None:
                key = f"fork:{handles[0].request_id}"
            ledger_note("fork_ref", key, +1)
    except BaseException:
        for h in handles:
            h.cancel()
            ledger_note("fork_ref", key, -1)
        raise
    return handles


def await_fork_group(handles: Sequence, timeout: Optional[float],
                     clock: Callable[[], float] = time.monotonic) -> None:
    """Block for every candidate against ONE shared deadline; a timeout
    cancels all unfinished candidates before propagating (the other
    half of the submission protocol shared by engine and supervisor)."""
    deadline = (clock() + timeout) if timeout is not None else None
    key = (f"fork:{handles[0].request_id}" if len(handles) else None)
    released = 0
    try:
        for h in handles:
            h.result(None if deadline is None
                     else max(0.0, deadline - clock()))
            released += 1
            ledger_note("fork_ref", key, -1)
    except TimeoutError:
        for h in handles:
            if not h.done():
                h.cancel()
        raise
    finally:
        # the awaiter's refs drop with the await on EVERY exit —
        # settled, timed out + cancelled, or failed (engine crash mid-
        # await). The cancelled candidates' slot/pool debt is the
        # ENGINE's ledger entry under their own request ids, not this.
        for h in handles[released:]:
            ledger_note("fork_ref", key, -1)


def accept_tokens(rows: np.ndarray, proposals: Sequence[int],
                  temperature: float, top_k: Optional[int],
                  top_p: Optional[float], rng: np.random.Generator,
                  max_tokens: int, eos_id: Optional[int],
                  proc=None) -> Tuple[List[int], int]:
    """Token-identical acceptance over one verified chain.

    ``rows``: the target's per-position next-token distributions for the
    chain ``[last_token, d_1, ..., d_g]`` (``rows[j]`` is the
    distribution AFTER feeding chain position ``j``; only rows
    ``0..len(proposals)`` are read). ``proposals``: the g draft tokens.

    Walks the chain sampling from the TARGET distribution with the
    sequence's own ``rng`` — identical distribution, identical RNG
    state, identical token to what solo decode would emit at that
    position. Stops at the first sampled token that diverges from the
    draft (later rows are conditioned on rejected context), at EOS, or
    at ``max_tokens``; the final row (all drafts matched) yields one
    bonus token for free. RNG is never consumed past the stop, so the
    sequence's sampling stream stays in lockstep with solo decode.

    ``proc`` (`logitproc.LogitState`, or None): the request's
    logit-processor pipeline. Each position's TARGET row is penalty-
    adjusted and grammar-masked exactly as solo decode's `_consume`
    would have (same host-side ``allow`` row, same RNG draw), and the
    pipeline OBSERVES each emitted token here — walking the chain IS
    the emission order, so grammar state and penalty counts at position
    j+1 reflect token j, identical to token-by-token decode. A grammar
    that exhausts mid-chain stops acceptance early (the engine then
    finishes the request); masks therefore compose with speculation
    without touching the acceptance rule.

    Returns ``(emitted, matched)``: the 1..g+1 accepted tokens and how
    many draft proposals they confirmed (the acceptance-rate metric).
    """
    g = len(proposals)
    emitted: List[int] = []
    matched = 0
    for j in range(g + 1):
        if len(emitted) >= max_tokens:
            break
        if proc is not None and proc.exhausted():
            break  # grammar complete: later rows must not consume RNG
        row = rows[j]
        allow = None
        if proc is not None:
            row = proc.adjust(row)
            allow = proc.allow_row()
        tok = sample_logits(row, temperature, top_k, rng, top_p,
                            allow=allow)
        emitted.append(tok)
        if proc is not None:
            proc.advance(tok)
        if eos_id is not None and tok == eos_id:
            if j < g and tok == proposals[j]:
                matched += 1
            break
        if j < g:
            if tok != proposals[j]:
                break  # rows[j+1:] are conditioned on the rejected draft
            matched += 1
    return emitted, matched


def shallow_draft_conf(conf, draft_blocks: int):
    """Derive the early-exit draft configuration: the first
    ``draft_blocks`` transformer blocks of ``conf`` rewired straight
    into the target's head chain (final LayerNorm + output layer).

    Structural contract (the `models/zoo.transformer_lm` shape, pre-LN
    residual stack): attention layers sit behind a single-input
    normalization vertex whose input is the block's residual-trunk
    entry, blocks combine through ElementWise vertices, and the output
    head is a chain of single-input non-ElementWise vertices. Graphs
    that don't match raise ValueError — the engine then demands an
    explicit ``draft_net`` or disables speculation with a warning.
    """
    from ..nn.conf.graph import ElementWiseVertex, LayerVertex

    order = conf.topological_order()
    attns = [name for name in order
             if isinstance(conf.vertices[name], LayerVertex)
             and type(conf.vertices[name].layer).__name__
             == "SelfAttentionLayer"]
    if len(attns) < 2:
        raise ValueError(
            f"self-speculative draft needs >= 2 attention blocks to cut "
            f"between, found {len(attns)}")
    K = int(draft_blocks)
    if not 1 <= K < len(attns):
        raise ValueError(
            f"draft_blocks={K} must be in [1, {len(attns) - 1}] "
            f"(the model has {len(attns)} attention blocks)")
    # block K's trunk entry: the input of the pre-LN feeding attention K
    ln_k = conf.vertex_inputs[attns[K]][0]
    entry = conf.vertex_inputs[ln_k][0]
    if entry not in conf.vertices:
        raise ValueError(
            f"block {K}'s trunk entry '{entry}' is a network input — "
            "nothing to cut")
    # head chain: back-walk from the output through single-input,
    # non-residual vertices; stops at the last block's residual combine
    head: List[str] = []
    v = conf.network_outputs[0]
    while (v in conf.vertices
           and not isinstance(conf.vertices[v], ElementWiseVertex)
           and len(conf.vertex_inputs.get(v, [])) == 1):
        head.append(v)
        v = conf.vertex_inputs[v][0]
    if not head or not isinstance(conf.vertices.get(v), ElementWiseVertex):
        raise ValueError(
            "could not identify the output head chain (expected a "
            "single-input chain ending at a residual ElementWise vertex)")
    # keep = everything feeding block K's entry, plus the head chain
    keep = set(head)
    stack = [entry]
    while stack:
        n = stack.pop()
        if n in keep or n not in conf.vertices:
            continue
        keep.add(n)
        stack.extend(conf.vertex_inputs.get(n, []))
    draft = copy.deepcopy(conf)
    draft.vertices = {n: vx for n, vx in draft.vertices.items() if n in keep}
    draft.vertex_inputs = {n: list(draft.vertex_inputs[n])
                           for n in draft.vertices}
    # the deepest head vertex (e.g. the final LayerNorm) early-exits
    # from block K's trunk output instead of block N's
    draft.vertex_inputs[head[-1]] = [entry]
    for n, ins in draft.vertex_inputs.items():
        for src in ins:
            if src not in draft.vertices and src not in draft.network_inputs:
                raise ValueError(
                    f"draft surgery left vertex '{n}' referencing removed "
                    f"vertex '{src}' — graph shape not supported")
    return draft


def build_shallow_draft(net, draft_blocks: int,
                        max_cache_len: Optional[int] = None):
    """Materialize the early-exit draft as a ComputationGraph whose
    params/variables are the TARGET's arrays by reference (zero extra
    weight bytes; the draft tracks net.params rebinding only at build
    time — the engine re-reads per dispatch for the unsharded case).

    ``max_cache_len``: override the draft's attention cache capacity
    (paged engines decode past the target conf's ``max_cache_len``; the
    draft's private contiguous cache must cover the same depth)."""
    from ..nn.graph import ComputationGraph

    dconf = shallow_draft_conf(net.conf, draft_blocks)
    if max_cache_len is not None:
        for vx in dconf.vertices.values():
            layer = getattr(vx, "layer", None)
            if layer is not None and hasattr(layer, "max_cache_len"):
                layer.max_cache_len = int(max_cache_len)
    draft = ComputationGraph(dconf).init()
    draft.params = {name: net.params[name] for name in draft.params}
    draft.variables = {name: net.variables[name] for name in draft.variables}
    return draft
