"""Deterministic fault injection for the serving stack (chaos seams).

The fault-tolerance argument in `inference/supervisor.py` is only worth
anything if it is *exercised*: "the watchdog restarts a crashed engine
and no request is lost" is a claim about code paths that never run in a
healthy process. This module plants named **failpoint seams** on the hot
paths (the FreeBSD `fail(9)` / etcd `gofail` shape) so chaos tests — and
operators reproducing an incident — can make precisely one dispatch
crash, one allocation report OOM, or one scheduler iteration hang, and
replay the exact same fault sequence from a seed.

Seams (each is one `fire(name)` call at the code site):

  ``scheduler.iteration``  top of every DecodeScheduler iteration
  ``dispatch.decode``      before the all-slots decode XLA dispatch
  ``dispatch.prefill``     before a prefill-chunk XLA dispatch
  ``pool.alloc``           KVPool block allocation (paged engines)
  ``batcher.flush``        before a MicroBatcher batch dispatch
  ``http.handler``         top of every serving-server POST handler
  ``router.journal``       before the fleet router appends a request to
                           its durable journal (serving/router.py)
  ``router.dispatch``      after the journal append, before the router
                           forwards the request to a replica

Cross-process arming (``DL4J_FAILPOINTS``): seams only fire in the
process that armed them, so fleet chaos runs arm seams INSIDE replica
(or router) subprocesses by exporting
``DL4J_FAILPOINTS="name=spec;name2=spec"`` into the child environment —
`serving/replica.py`'s entry point (and the router's, and `dl4j-tpu
serve`) calls :func:`arm_from_env` at startup, and
``ReplicaProcess(failpoints=...)`` sets the variable for one child. The
specs are deterministic (seeded p-triggers, exact n-triggers), so a
fleet chaos replay is the same fault sequence every run.

Arming: ``arm("dispatch.decode", "crash@n:3")`` — the spec grammar is
``action[@trigger]``:

  action   ``crash`` (raise InjectedCrash) | ``oom`` (raise InjectedOOM,
           a MemoryError) | ``hang:<ms>`` (sleep ms, then raise
           InjectedHang — the sleep is the fault the watchdog must
           detect by heartbeat staleness; the raise on wake lets the
           abandoned scheduler thread exit through the ordinary crash
           path instead of racing its replacement engine)
  trigger  ``once`` (first hit only — the default) | ``always`` (every
           hit) | ``n:<K>`` (the Kth hit only) | ``p:<prob>[:<seed>]``
           (each hit fires with probability prob, drawn from a PRIVATE
           seeded RNG — the same seed replays the same trigger
           sequence, which is what makes chaos runs debuggable)

Control planes: programmatic (`arm`/`disarm`), CLI (`dl4j-tpu serve
--failpoint name=spec`, repeatable), environment
(``DL4J_FAILPOINTS="name=spec;name2=spec"`` via :func:`arm_from_env`),
and a test-only HTTP endpoint (`POST /admin/failpoints`, opt-in —
`serving/server.py`).

Disarmed cost is ZERO beyond one module-level dict emptiness test:
``fire()`` returns immediately while nothing is armed, so the seams are
safe to leave in the production hot loop (same discipline as the
tracer's ``enabled`` fast path). Trigger bookkeeping (hit counts, RNG
draws) only runs while a seam is armed, under a small per-arm lock.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["InjectedFault", "InjectedCrash", "InjectedOOM", "InjectedHang",
           "SEAMS", "arm", "disarm", "fire", "snapshot", "arm_from_env",
           "bind_metrics", "parse_spec"]

# the seams the serving stack actually plants (arming anything else is a
# spec error — a typo'd seam name must not silently never fire)
SEAMS = ("scheduler.iteration", "dispatch.decode", "dispatch.prefill",
         "dispatch.verify", "pool.alloc", "batcher.flush", "http.handler",
         "router.journal", "router.dispatch", "tier.spill", "tier.restore",
         "directory.publish")


class InjectedFault(RuntimeError):
    """Base class for injected faults: every fault carries the seam that
    raised it, so recovery paths and chaos asserts can tell injected
    failures from organic ones."""

    def __init__(self, seam: str, detail: str = ""):
        self.seam = seam
        super().__init__(f"injected fault at seam '{seam}'"
                         + (f": {detail}" if detail else ""))


class InjectedCrash(InjectedFault):
    """An uncaught-exception crash of the component owning the seam."""


class InjectedOOM(InjectedFault, MemoryError):
    """An allocation failure (MemoryError subclass, so code that guards
    `except MemoryError` treats it exactly like the real thing)."""


class InjectedHang(InjectedFault):
    """A stalled iteration: the seam slept ``ms`` before raising this.
    The *sleep* is the observable fault (heartbeat goes stale); the
    raise is the stalled thread's exit ramp."""

    def __init__(self, seam: str, ms: float):
        self.ms = float(ms)
        super().__init__(seam, f"hung {ms:g}ms")


class _Arm:
    """One armed seam: parsed spec + trigger state."""

    __slots__ = ("seam", "spec", "action", "ms", "trigger", "nth", "prob",
                 "seed", "rng", "hits", "triggers", "lock")

    def __init__(self, seam: str, spec: str):
        self.seam = seam
        self.spec = spec
        (self.action, self.ms, self.trigger,
         self.nth, self.prob, self.seed) = parse_spec(spec)
        # private PRNG: a p-trigger must replay identically from its
        # seed no matter what else in the process consumes randomness
        self.rng = np.random.default_rng(self.seed)
        self.hits = 0
        self.triggers = 0
        self.lock = threading.Lock()

    def should_fire(self) -> bool:
        with self.lock:
            self.hits += 1
            if self.trigger == "once":
                hit = self.hits == 1
            elif self.trigger == "always":
                hit = True
            elif self.trigger == "n":
                hit = self.hits == self.nth
            else:  # "p"
                hit = float(self.rng.random()) < self.prob
            if hit:
                self.triggers += 1
            return hit

    def state(self) -> dict:
        with self.lock:
            return {"spec": self.spec, "action": self.action,
                    "trigger": self.trigger, "hits": self.hits,
                    "triggers": self.triggers}


def parse_spec(spec: str):
    """``action[@trigger]`` -> (action, hang_ms, trigger, nth, prob, seed).
    Raises ValueError with the offending fragment on any malformed spec
    (an operator typo must fail arming, not arm a no-op)."""
    action_s, _, trigger_s = spec.partition("@")
    action_s = action_s.strip()
    ms = 0.0
    if action_s.startswith("hang"):
        action, _, ms_s = action_s.partition(":")
        if action != "hang" or not ms_s:
            raise ValueError(f"bad hang action {action_s!r} "
                             "(expected 'hang:<ms>')")
        ms = float(ms_s)
        if ms < 0:
            raise ValueError(f"hang ms must be >= 0, got {ms}")
        action_s = "hang"
    if action_s not in ("crash", "oom", "hang"):
        raise ValueError(f"unknown failpoint action {action_s!r} "
                         "(crash | oom | hang:<ms>)")
    trigger_s = trigger_s.strip() or "once"
    nth, prob, seed = 0, 0.0, 0
    if trigger_s in ("once", "always"):
        trigger = trigger_s
    elif trigger_s.startswith("n:"):
        trigger = "n"
        nth = int(trigger_s[2:])
        if nth < 1:
            raise ValueError(f"nth-hit trigger must be >= 1, got {nth}")
    elif trigger_s.startswith("p:"):
        trigger = "p"
        parts = trigger_s.split(":")
        prob = float(parts[1])
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"probability must be in [0, 1], got {prob}")
        seed = int(parts[2]) if len(parts) > 2 else 0
    else:
        raise ValueError(f"unknown failpoint trigger {trigger_s!r} "
                         "(once | always | n:<K> | p:<prob>[:<seed>])")
    return action_s, ms, trigger, nth, prob, seed


# -- module state ------------------------------------------------------------
# `_armed` emptiness IS the fast path: fire() in a disarmed process is
# one lock-free dict bool test (GIL-atomic; a fire racing an arm either
# sees it or misses one hit — both fine, and the suppressed CC005 at the
# fast path documents it). Every OTHER access — arming, disarming, the
# armed path's lookup, and the bound metrics registry — goes through
# _arm_lock.
_armed: Dict[str, _Arm] = {}
_arm_lock = threading.Lock()
_metrics = None  # bound MetricsRegistry (failpoint_triggers_total)

# hang sleeps poll in small slices so a disarm (or test teardown) can
# cut a long hang short instead of holding the thread hostage
_HANG_SLICE_S = 0.05


def bind_metrics(registry) -> None:
    """Point ``failpoint_triggers_total`` at a server's MetricsRegistry
    (the registry is process-global; servers each own their metrics).
    Written under the arm lock so ``fire()``'s armed path (which reads
    it under the same lock) can never observe a half-published registry
    — graftlint CC005 caught the original lock-free publish."""
    global _metrics
    with _arm_lock:
        _metrics = registry


def arm(name: str, spec: str) -> None:
    """Arm one seam. Re-arming replaces the previous spec (trigger state
    resets — that is what makes seed replays exact)."""
    if name not in SEAMS:
        raise ValueError(f"unknown failpoint seam {name!r}; "
                         f"known seams: {', '.join(SEAMS)}")
    new = _Arm(name, spec)  # parse (and fail) before touching state
    with _arm_lock:
        _armed[name] = new


def disarm(name: Optional[str] = None) -> None:
    """Disarm one seam, or every seam when ``name`` is None."""
    with _arm_lock:
        if name is None:
            _armed.clear()
        else:
            _armed.pop(name, None)


def snapshot() -> Dict[str, dict]:
    """Armed seams with hit/trigger counts (the GET /admin/failpoints
    body and the chaos tests' determinism probe)."""
    with _arm_lock:
        arms = list(_armed.items())
    return {name: arm_.state() for name, arm_ in arms}


def arm_from_env(environ=None) -> List[str]:
    """Arm seams from ``DL4J_FAILPOINTS="name=spec;name2=spec"``.
    Returns the armed seam names (empty when the variable is unset)."""
    import os
    env = environ if environ is not None else os.environ
    raw = env.get("DL4J_FAILPOINTS", "")
    out = []
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, spec = entry.partition("=")
        if not sep:
            raise ValueError(
                f"bad DL4J_FAILPOINTS entry {entry!r} (want name=spec)")
        arm(name.strip(), spec.strip())
        out.append(name.strip())
    return out


def fire(name: str) -> None:
    """The seam call. Disarmed: one dict emptiness test, nothing else.
    Armed and triggered: raises the configured typed fault (after the
    configured sleep, for hangs)."""
    # lock-free FAST PATH by design: the disarmed production hot loop
    # must not take a lock per seam. A dict emptiness read is one
    # GIL-atomic bytecode; racing an arm() either sees the arm (fires)
    # or misses this one hit (the next hit fires) — both correct.
    if not _armed:  # graftlint: disable=CC005
        return
    # armed (slow) path: the arm and the bound metrics registry are
    # fetched under the same lock arm()/disarm()/bind_metrics() publish
    # them under, so a fire racing a re-arm can never observe a
    # half-constructed _Arm or half-published registry
    with _arm_lock:
        arm_ = _armed.get(name)
        metrics = _metrics
    if arm_ is None or not arm_.should_fire():
        return
    if metrics is not None:
        metrics.counter("failpoint_triggers_total").inc()
    if arm_.action == "crash":
        raise InjectedCrash(name, arm_.spec)
    if arm_.action == "oom":
        raise InjectedOOM(name, arm_.spec)
    # hang: sleep in slices (a disarm cuts the stall short), then raise
    deadline = time.monotonic() + arm_.ms / 1e3
    while time.monotonic() < deadline:
        with _arm_lock:
            current = _armed.get(name)
        if current is not arm_:
            break  # disarmed / re-armed mid-hang: release the thread
        time.sleep(min(_HANG_SLICE_S,
                       max(0.0, deadline - time.monotonic())))
    raise InjectedHang(name, arm_.ms)
