"""Production inference engine: continuous micro-batching, slot-based
generative decode scheduling, and SLO metrics.

The three pieces compose into the serving stack (`serving/server.py`):
`MicroBatcher` aggregates concurrent `/predict` requests into bucketed
padded batches; `DecodeScheduler` continuously batches generative decode
over the attention KV cache; `MetricsRegistry` records queue depth, batch
occupancy, and latency percentiles, exported at `GET /metrics`.
"""
from .batcher import (InferenceFuture, MicroBatcher, QueueFullError,
                      RequestTimeoutError, pow2_buckets)
from .engine import DecodeHandle, DecodeScheduler
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      default_registry)

__all__ = ["Counter", "DecodeHandle", "DecodeScheduler", "Gauge",
           "Histogram", "InferenceFuture", "MetricsRegistry", "MicroBatcher",
           "QueueFullError", "RequestTimeoutError", "default_registry",
           "pow2_buckets"]
