"""Production inference engine: continuous micro-batching, slot-based
generative decode scheduling, prefix KV reuse, and SLO metrics.

The pieces compose into the serving stack (`serving/server.py`):
`MicroBatcher` aggregates concurrent `/predict` requests into bucketed
padded batches; `DecodeScheduler` continuously batches generative decode
over the attention KV cache — paged (`kv_pool_mb`: all slots share one
`KVPool` block pool through per-slot block tables, with zero-copy prefix
restore/publish and preempt-and-swap under pool pressure) or contiguous
per-slot stripes with a `KVPool` side prefix cache; `MetricsRegistry`
records queue depth, batch occupancy, hit rates, pool occupancy, and
latency percentiles, exported at
`GET /metrics`; the `FlightRecorder` span flight recorder (`trace.py`)
records every request's lifecycle — queued/restore/prefill/decode span
trees plus scheduler instants — exported at `GET /trace` (JSON or
Perfetto-loadable Chrome trace-event format). With ``mesh=N``
(`sharding.py`) the whole decode stack runs tensor-parallel over a
``tp`` device mesh: heads/FFN sharded, KV pool head-sharded (per-device
byte budgets — ``tp×`` the blocks at fixed per-device HBM), block
tables replicated, and the per-token program audited to carry only the
Megatron all-reduces (no resharding collectives on the hot path).
"""
from .batcher import (InferenceFuture, MicroBatcher, QueueFullError,
                      RequestTimeoutError, bucket_for, pow2_buckets)
from .engine import (DecodeHandle, DecodeScheduler, EngineCrashedError,
                     LoadSheddedError, PromptTooLongError)
from .failpoints import (InjectedCrash, InjectedFault, InjectedHang,
                         InjectedOOM)
from .kvpool import KVPool
from .logitproc import (CompiledGrammar, GrammarError, LogitState,
                        StopMatcher, TokenStream, admit_all,
                        compile_json_schema, compile_trie)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      default_registry)
from .profiler import SLOMonitor, StepPhaseProfiler, program_costs
from .sharding import (TP_AXIS, collective_counts, decode_mesh,
                       decode_program_hlo, draft_program_hlo,
                       prefill_program_hlo, verify_program_hlo)
from .speculative import ForkGroup, build_shallow_draft
from .supervisor import (AdmissionRejectedError, EngineSupervisor,
                         RetryBudgetExceededError, ShuttingDownError)
from .trace import FlightRecorder, default_recorder, new_request_id

__all__ = ["AdmissionRejectedError", "CompiledGrammar", "Counter",
           "DecodeHandle",
           "DecodeScheduler", "EngineCrashedError", "EngineSupervisor",
           "FlightRecorder", "ForkGroup", "Gauge", "GrammarError",
           "Histogram", "InferenceFuture",
           "InjectedCrash", "InjectedFault", "InjectedHang", "InjectedOOM",
           "KVPool", "LoadSheddedError", "LogitState", "MetricsRegistry",
           "MicroBatcher",
           "PromptTooLongError", "QueueFullError", "RequestTimeoutError",
           "RetryBudgetExceededError", "SLOMonitor", "ShuttingDownError",
           "StepPhaseProfiler", "StopMatcher", "TP_AXIS", "TokenStream",
           "admit_all",
           "bucket_for", "build_shallow_draft", "collective_counts",
           "compile_json_schema", "compile_trie",
           "decode_mesh", "decode_program_hlo", "default_recorder",
           "default_registry", "draft_program_hlo",
           "new_request_id", "pow2_buckets", "prefill_program_hlo",
           "program_costs", "verify_program_hlo"]
