"""Hierarchical KV cache tiering: HBM → pinned host RAM → durable disk.

A replica's radix trie (`kvpool.py`) caps the prefix-cache hit rate at
what fits in the HBM pool — but serving traffic shares far more prefix
than one device holds. This module adds the next two rungs of the
ladder (ROADMAP item 2): when the pool's LRU evicts an unreferenced
prefix leaf, the :class:`TierManager` captures the block's pages
(int8-quantized pages are already half the bytes) into a host-RAM ring
under a ``--host-cache-mb`` budget, demotes host overflow to a
CRC-framed block store (`serving/durable.py` framing: a SIGKILL
mid-spill leaves a torn file that reads as a *miss*, never as wrong
bytes) under ``--disk-cache-mb``, and promotes blocks back into the
pool on trie hit through the existing zero-copy adopt/table-remap path.

Two disciplines keep the decode hot path untouched:

  - **pacing** (the chunked-transfer discipline of arxiv 1905.04035):
    every device↔host byte moves on the background worker thread under
    a credit budget the scheduler grants per iteration
    (:meth:`TierManager.pace`), so a spill burst can never stall a
    decode step — at worst the spill queue overflows and the block is
    dropped (cold recompute later, counted, never wrong);
  - **tier-portable layout** (arxiv 2112.01075): what moves between
    tiers is the page row exactly as the paged kernels index it
    (``[block, Hkv, Dh]`` per layer, plus int8 scale rows), so
    promotion is one jitted ``dynamic_update_slice`` per tier restore
    and never reshards.

The same metadata doubles as the **fleet prefix directory**: every
insert/spill/evict appends a sequence-numbered event the router polls
(``GET /prefix/directory``), mapping content-addressed block-hash
chains → tier, so ``pick_replica`` can route a prompt to the replica
already holding its prefix in *any* tier — or tell a replica to fetch
the chain from a peer's host/disk tier over HTTP before admission
(``POST /prefix/fetch`` → ``GET /prefix/block``).

Threading: all dynamic state lives under one condition's lock; the
scheduler thread calls the notify/offer/drain seams, the worker thread
moves bytes, HTTP threads read payloads and insert fetched blocks.
The ownership ledger (`analysis/runtime.py`) tracks every host page,
disk block, and directory entry by chain hash so tests prove the
balance sheet zeroes through spill → restore → free.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.runtime import host_read, ledger_check_zero, ledger_note
from ..serving.durable import read_block_file, write_block_file
from . import failpoints
from .metrics import MetricsRegistry
from .trace import FlightRecorder

#: ledger kinds this subsystem owns (mirrored in analysis/lifecycle.py)
TIER_LEDGER_KINDS = ("host_page", "disk_block", "directory_entry")

#: disk store file suffix (one CRC-framed file per chain hash)
BLOCK_SUFFIX = ".kvb"


def chain_hash(parent: str, key: Sequence[int]) -> str:
    """Content address of one trie block: sha1 over the parent block's
    hash and this block's tokens. Identical prompts hash to identical
    chains on every replica — the fleet directory's join key."""
    h = hashlib.sha1()
    h.update(parent.encode("ascii"))
    h.update(b"|")
    h.update(np.asarray(list(key), np.int64).tobytes())
    return h.hexdigest()


def prompt_chain(tokens: Sequence[int], block: int,
                 max_blocks: Optional[int] = None) -> List[str]:
    """Hash chain for every *full* block of ``tokens`` (the router's
    view of a prompt — no trie needed)."""
    n = len(tokens) // block
    if max_blocks is not None:
        n = min(n, max_blocks)
    out: List[str] = []
    parent = ""
    for j in range(n):
        parent = chain_hash(parent, tokens[j * block:(j + 1) * block])
        out.append(parent)
    return out


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bundled with jax; covers bfloat16 etc.
        return np.dtype(getattr(ml_dtypes, name))


def encode_block(entry: "TierEntry",
                 pages: Dict[str, Dict[str, np.ndarray]]) -> bytes:
    """Serialize one tiered block (entry metadata + page rows) to the
    payload the disk store frames and /prefix/block serves."""
    doc = {
        "v": 1,
        "hash": entry.hash,
        "parent": entry.parent,
        "depth": entry.depth,
        "prefix": list(entry.prefix),
        "pages": {
            lk: {pk: {"dtype": a.dtype.name, "shape": list(a.shape),
                      "data": base64.b64encode(
                          np.ascontiguousarray(a).tobytes()).decode("ascii")}
                 for pk, a in pks.items()}
            for lk, pks in pages.items()},
    }
    return json.dumps(doc).encode("utf-8")


def decode_block(payload: bytes):
    """Inverse of :func:`encode_block`. Returns ``(meta, pages)`` or
    ``None`` on any structural defect — a corrupt payload is a miss."""
    try:
        doc = json.loads(payload.decode("utf-8"))
        if doc.get("v") != 1:
            return None
        prefix = [int(t) for t in doc["prefix"]]
        raw_depth = doc["depth"]  # parsed-JSON host scalar
        depth = int(raw_depth)
        pages: Dict[str, Dict[str, np.ndarray]] = {}
        for lk, pks in doc["pages"].items():
            pages[lk] = {}
            for pk, spec in pks.items():
                arr = np.frombuffer(
                    base64.b64decode(spec["data"]),
                    dtype=_np_dtype(spec["dtype"]))
                pages[lk][pk] = arr.reshape(spec["shape"])
        meta = {"hash": str(doc["hash"]), "parent": str(doc["parent"]),
                "depth": depth, "prefix": prefix}
        return meta, pages
    except (KeyError, ValueError, TypeError, json.JSONDecodeError):
        return None


@dataclass
class TierEntry:
    """Directory row for one trie block, keyed by its chain hash."""

    hash: str
    parent: str                 # parent chain hash, "" at the root
    key: Tuple[int, ...]        # this block's tokens
    depth: int                  # blocks from the root (1-based)
    prefix: Tuple[int, ...]     # full token prefix through this block
    tier: str                   # "hbm" | "spilling" | "host" | "disk"


class TierManager:
    """Owns the host-RAM ring, the disk block store, the directory
    event log, and the background transfer worker.

    The engine arms it with :meth:`attach_engine` (a capture callable
    that snapshots one pool page row as device arrays, plus sizing);
    `kvpool.KVPool` calls :meth:`note_resident` on trie insert/adopt
    and :meth:`offer_spill` from ``_evict_lru``; the scheduler loop
    calls :meth:`pace` + :meth:`drain_ready` every iteration; HTTP
    handlers call :meth:`directory_feed` / :meth:`get_block_payload` /
    :meth:`insert_fetched`.
    """

    def __init__(self, *, host_bytes: int, disk_bytes: int = 0,
                 disk_dir: Optional[str] = None,
                 chunk_bytes: int = 512 * 1024,
                 queue_blocks: int = 32, ready_blocks: int = 64,
                 event_log: int = 4096,
                 metrics: Optional[MetricsRegistry] = None,
                 tracer: Optional[FlightRecorder] = None):
        if host_bytes <= 0:
            raise ValueError("host_bytes must be > 0 to arm tiering")
        if disk_bytes > 0 and not disk_dir:
            raise ValueError("disk tier needs disk_dir")
        self.host_budget = int(host_bytes)
        self.disk_budget = int(disk_bytes)
        self.disk_dir = disk_dir
        self.chunk_bytes = int(chunk_bytes)
        self.queue_blocks = int(queue_blocks)
        self.ready_blocks = int(ready_blocks)
        #: process epoch — a restarted replica publishes a fresh epoch so
        #: directory consumers drop stale cursors and resync from zero
        self.epoch = os.urandom(8).hex()
        if self.disk_budget > 0:
            os.makedirs(disk_dir, exist_ok=True)

        # engine attachment (written once before traffic, then read-only)
        self._capture: Optional[Callable[[int], dict]] = None
        self._block_bytes = 0
        self.block = 0

        # -- all dynamic state below lives under _cond's lock ---------------
        self._cond = threading.Condition()
        self._index: Dict[str, TierEntry] = {}
        self._children: Dict[str, Dict[Tuple[int, ...], str]] = {}
        self._host: "OrderedDict[str, Tuple[dict, int]]" = OrderedDict()
        self._host_bytes = 0
        self._disk: "OrderedDict[str, int]" = OrderedDict()
        self._disk_bytes = 0
        self._events: deque = deque(maxlen=int(event_log))
        self._seq = 0
        self._spillq: deque = deque()     # (hash, device pytree, is_copy)
        self._restoreq: deque = deque()   # hashes awaiting promotion
        self._restore_pending: set = set()
        self._readyq: deque = deque()     # (entry snapshot, host pages)
        self._copyq: deque = deque()      # hashes needing HBM copydown
        self._credits = int(chunk_bytes)
        self._credit_cap = 4 * int(chunk_bytes)
        self._stopped = False
        self.last_error: Optional[str] = None

        m = metrics
        self.metrics = m
        if m is not None:
            self._c_spilled = m.counter(
                "kv_tier_spilled_blocks_total",
                "prefix blocks demoted from HBM into the host ring")
            self._c_spilled_bytes = m.counter(
                "kv_tier_spilled_bytes_total",
                "bytes moved device->host by spills")
            self._c_spill_dropped = m.counter(
                "kv_tier_spill_dropped_total",
                "evicted blocks dropped instead of spilled (queue full, "
                "no capture, or injected fault) — cold recompute later")
            self._c_restored = m.counter(
                "kv_tier_restored_blocks_total",
                "tiered blocks staged host-side for promotion")
            self._c_restored_bytes = m.counter(
                "kv_tier_restored_bytes_total",
                "bytes staged for promotion (host+disk reads)")
            self._c_restore_failed = m.counter(
                "kv_tier_restore_failed_total",
                "restore requests dropped (fault/corrupt payload) — the "
                "slot degrades to cold prefill")
            self._c_lookups = m.counter(
                "kv_tier_lookups_total",
                "admission-time tier directory lookups")
            self._c_hits_host = m.counter(
                "kv_tier_hits_host_total",
                "lookup blocks found in the host ring")
            self._c_hits_disk = m.counter(
                "kv_tier_hits_disk_total",
                "lookup blocks found in the disk store")
            self._c_demoted = m.counter(
                "kv_tier_demoted_disk_blocks_total",
                "host-ring overflow blocks demoted to disk")
            self._c_dropped = m.counter(
                "kv_tier_evicted_blocks_total",
                "blocks that fell off the bottom tier (directory del)")
            self._c_fetched = m.counter(
                "kv_tier_fetched_blocks_total",
                "blocks inserted from a peer replica's tier")
            self._c_copydowns = m.counter(
                "kv_tier_copydowns_total",
                "HBM->host copydowns serving peer fetches")
            self._c_publish_dropped = m.counter(
                "kv_tier_publish_dropped_total",
                "directory events lost to injected publish faults")
            self._g_host_blocks = m.gauge(
                "kv_tier_host_blocks", "blocks resident in the host ring")
            self._g_host_bytes = m.gauge(
                "kv_tier_host_bytes", "bytes resident in the host ring")
            self._g_disk_blocks = m.gauge(
                "kv_tier_disk_blocks", "blocks resident in the disk store")
            self._g_disk_bytes = m.gauge(
                "kv_tier_disk_bytes", "bytes resident in the disk store")
            self._g_dir_entries = m.gauge(
                "kv_tier_directory_entries",
                "chain hashes tracked in the prefix directory")
            m.ratio("kv_tier_host_hit_rate",
                    self._c_hits_host, self._c_lookups,
                    "fraction of tier lookups served by the host ring")
            m.ratio("kv_tier_disk_hit_rate",
                    self._c_hits_disk, self._c_lookups,
                    "fraction of tier lookups served by the disk store")
        self.tracer = tracer

        self._worker = threading.Thread(
            target=self._worker_loop, name="kvtier-worker", daemon=True)
        self._worker.start()

    # -- engine attachment (setup-time, single-threaded) --------------------

    def attach_engine(self, capture: Callable[[int], dict],
                      block_bytes: int, block: int) -> None:
        """Arm the device side: ``capture(block_id)`` dispatches the
        jitted page-row slice and returns the device pytree; sizing
        feeds the pacing credit cap so one full block can always earn
        enough credits to move."""
        with self._cond:
            self._capture = capture
            self._block_bytes = int(block_bytes)
            self.block = int(block)
            self._credit_cap = max(4 * self.chunk_bytes, 2 * block_bytes)
            self._credits = min(self._credits, self._credit_cap)

    # -- directory bookkeeping (scheduler thread via kvpool) ----------------

    def note_resident(self, h: str, parent: str,
                      key: Sequence[int]) -> None:
        """Trie insert/adopt hook: record (or re-tier) a resident block.
        A host/disk payload for the same hash is kept — it serves peer
        fetches, and a later eviction flips the tier without recopying."""
        key = tuple(int(t) for t in key)
        with self._cond:
            e = self._index.get(h)
            if e is None:
                if parent:
                    pe = self._index.get(parent)
                    if pe is None:
                        return  # broken chain (ancestor dropped) — skip
                    prefix = pe.prefix + key
                    depth = pe.depth + 1
                else:
                    prefix = key
                    depth = 1
                e = TierEntry(h, parent, key, depth, prefix, "hbm")
                self._index[h] = e
                self._children.setdefault(parent, {})[key] = h
                ledger_note("directory_entry", h, +1)
            else:
                e.tier = "hbm"
            self._restore_pending.discard(h)
            self._publish_locked("put", e)
            self._sync_gauges_locked()

    def offer_spill(self, h: Optional[str], block_id: int) -> None:
        """`_evict_lru` hook, called BEFORE the block id returns to the
        free list. Captures the page row as an immutable device
        snapshot (functional update semantics make the freed id safe to
        reuse immediately) and queues it for the worker; on any
        degradation — queue full, no capture, injected fault — the
        block is dropped from the directory and recomputed cold later."""
        if h is None:
            return
        with self._cond:
            e = self._index.get(h)
            if e is None:
                return
            if h in self._host or h in self._disk:
                # payload already tiered (write-back cache): flip only
                e.tier = "host" if h in self._host else "disk"
                self._publish_locked("put", e)
                return
            cap = self._capture
            if cap is None or len(self._spillq) >= self.queue_blocks:
                self._drop_entry_locked(e)
                if self.metrics is not None:
                    self._c_spill_dropped.inc()
                self._sync_gauges_locked()
                return
            e.tier = "spilling"
        try:
            failpoints.fire("tier.spill")
            dev = cap(int(block_id))
        except failpoints.InjectedFault as exc:
            with self._cond:
                ent = self._index.get(h)
                if ent is not None:
                    self._drop_entry_locked(ent)
                if self.metrics is not None:
                    self._c_spill_dropped.inc()
                self.last_error = f"tier.spill: {exc}"
                self._sync_gauges_locked()
            return
        with self._cond:
            self._spillq.append((h, dev, False))
            self._cond.notify_all()

    def evicted_everywhere(self, h: str) -> None:
        """Drop a chain hash from every tier (test/maintenance seam)."""
        with self._cond:
            e = self._index.get(h)
            if e is not None:
                self._drop_entry_locked(e)
                self._sync_gauges_locked()

    # -- admission-side lookup / promotion (scheduler thread) ---------------

    def lookup_extension(self, frontier: str, prompt: Sequence[int],
                         from_block: int, max_blocks: int) -> List[str]:
        """Walk the directory past the trie's resident frontier: the
        chain of host/disk blocks that extend ``prompt``'s resident
        prefix. One lookup is counted per call; each returned block
        counts as a per-tier hit."""
        out: List[str] = []
        with self._cond:
            B = self.block
            if B <= 0:
                return []
            if self.metrics is not None:
                self._c_lookups.inc()
            h = frontier
            j = from_block
            while j < max_blocks:
                key = tuple(int(t) for t in prompt[j * B:(j + 1) * B])
                ch = self._children.get(h, {}).get(key)
                if ch is None:
                    break
                e = self._index.get(ch)
                if e is None or e.tier not in ("host", "disk"):
                    break
                if self.metrics is not None:
                    (self._c_hits_host if e.tier == "host"
                     else self._c_hits_disk).inc()
                out.append(ch)
                h = ch
                j += 1
        return out

    def request_restore(self, hashes: Sequence[str]) -> int:
        """Queue tiered blocks for promotion (idempotent per hash)."""
        n = 0
        with self._cond:
            for h in hashes:
                if h in self._restore_pending:
                    continue
                e = self._index.get(h)
                if e is None or e.tier not in ("host", "disk"):
                    continue
                self._restore_pending.add(h)
                self._restoreq.append(h)
                n += 1
            if n:
                self._cond.notify_all()
        return n

    def drain_ready(self, max_bytes: int,
                    max_blocks: int = 8) -> List[Tuple[TierEntry, dict]]:
        """Pop promotion payloads staged by the worker, chain-ordered
        (parents first), bounded by the per-iteration upload budget."""
        out: List[Tuple[TierEntry, dict]] = []
        budget = int(max_bytes)
        with self._cond:
            while self._readyq and len(out) < max_blocks:
                entry, pages, nbytes = self._readyq[0]
                if out and nbytes > budget:
                    break
                self._readyq.popleft()
                budget -= nbytes
                out.append((entry, pages))
        return out

    def entry_info(self, h: str) -> Optional[Tuple[Tuple[int, ...], int]]:
        """(prefix tokens, depth) for a tracked chain hash, or None."""
        with self._cond:
            e = self._index.get(h)
            return None if e is None else (e.prefix, e.depth)

    def holds(self, h: str) -> bool:
        """True when this process already has the block in ANY tier
        (HBM-resident, host ring, or disk) — used by the peer-fetch path
        to skip blocks that need no network pull."""
        with self._cond:
            e = self._index.get(h)
            if e is None:
                return False
            return (e.tier in ("hbm", "spilling") or h in self._host
                    or h in self._disk)

    def promotion_done(self, h: str, ok: bool) -> None:
        """Engine resolution for one drained payload. ``ok`` means the
        block was adopted back into the trie (note_resident already
        re-tiered it); failure just clears the pending mark so a later
        hit can retry."""
        with self._cond:
            self._restore_pending.discard(h)
            if not ok and self.metrics is not None:
                self._c_restore_failed.inc()

    # -- pacing (scheduler thread) ------------------------------------------

    def pace(self, nbytes: int) -> None:
        """Grant the worker a transfer budget for this iteration."""
        with self._cond:
            self._credits = min(self._credits + int(nbytes),
                                self._credit_cap)
            self._cond.notify_all()

    # -- copydown (HTTP thread requests, scheduler thread serves) -----------

    def pending_copydowns(self, max_n: int = 4) -> List[str]:
        out: List[str] = []
        with self._cond:
            while self._copyq and len(out) < max_n:
                out.append(self._copyq.popleft())
        return out

    def complete_copydown(self, h: str, dev: dict) -> None:
        """Scheduler hands over a captured HBM-resident page row; the
        worker lands it in the host ring (tier stays ``hbm`` — the
        copy exists to serve peer fetches, not to free HBM)."""
        with self._cond:
            if len(self._spillq) >= self.queue_blocks:
                return  # waiter times out; peer degrades to recompute
            self._spillq.append((h, dev, True))
            if self.metrics is not None:
                self._c_copydowns.inc()
            self._cond.notify_all()

    # -- HTTP-facing payload plane ------------------------------------------

    def get_block_payload(self, h: str,
                          timeout: float = 0.0) -> Optional[bytes]:
        """Encoded payload for one chain hash, from host or disk. An
        HBM-resident entry triggers a copydown request and (with a
        timeout) waits bounded for the scheduler to serve it."""
        deadline = time.monotonic() + max(0.0, timeout)
        requested = False
        with self._cond:
            while True:
                e = self._index.get(h)
                if e is None or self._stopped:
                    return None
                hit = self._host.get(h)
                if hit is not None:
                    self._host.move_to_end(h)
                    return encode_block(e, hit[0])
                if h in self._disk:
                    payload = read_block_file(self._disk_path(h))
                    if payload is not None:
                        return payload
                    self._disk_forget_locked(h)  # torn/corrupt = miss
                    self._drop_entry_locked(e)
                    self._sync_gauges_locked()
                    return None
                if e.tier == "hbm" and not requested:
                    self._copyq.append(h)
                    requested = True
                    self._cond.notify_all()
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return None
                self._cond.wait(min(0.05, remain))

    def insert_fetched(self, payload: bytes) -> Optional[str]:
        """Land a peer-fetched block payload in the host ring (chain
        order matters: parents must arrive before children or the
        chain stays unreachable). Returns the chain hash, or None on a
        corrupt payload / duplicate / broken chain."""
        dec = decode_block(payload)
        if dec is None:
            return None
        meta, pages = dec
        h = meta["hash"]
        nbytes = sum(int(a.nbytes) for pks in pages.values()
                     for a in pks.values())
        with self._cond:
            e = self._index.get(h)
            if e is not None and (e.tier == "hbm" or h in self._host
                                  or h in self._disk):
                return h  # already held locally in some tier
            if e is None:
                parent = meta["parent"]
                if parent and parent not in self._index:
                    return None
                prefix = tuple(meta["prefix"])
                key = prefix[-self.block:] if self.block else prefix
                if parent:
                    key = prefix[len(self._index[parent].prefix):]
                e = TierEntry(h, parent, tuple(key), meta["depth"],
                              prefix, "host")
                self._index[h] = e
                self._children.setdefault(parent, {})[tuple(key)] = h
                ledger_note("directory_entry", h, +1)
            e.tier = "host"
            self._host_put_locked(h, pages, nbytes)
            if self.metrics is not None:
                self._c_fetched.inc()
            self._publish_locked("put", e)
            self._sync_gauges_locked()
            self._cond.notify_all()
        return h

    def directory_feed(self, since: int = 0) -> dict:
        """Event feed for the router: events with seq > ``since``, or a
        full ``reset`` snapshot when the cursor predates the ring (or
        is zero). ``epoch`` changes on process restart."""
        with self._cond:
            oldest = self._events[0]["seq"] if self._events else self._seq + 1
            if since <= 0 or since + 1 < oldest:
                snap = [{"seq": self._seq, "op": "put", "hash": e.hash,
                         "parent": e.parent, "depth": e.depth,
                         "tier": e.tier}
                        for e in self._index.values()
                        if e.tier in ("hbm", "host", "disk")]
                return {"epoch": self.epoch, "next": self._seq,
                        "reset": True, "events": snap}
            evs = [dict(ev) for ev in self._events if ev["seq"] > since]
            return {"epoch": self.epoch, "next": self._seq,
                    "reset": False, "events": evs}

    # -- census / teardown ---------------------------------------------------

    def stats(self) -> dict:
        with self._cond:
            return {
                "epoch": self.epoch,
                "host": {"blocks": len(self._host),
                         "bytes": self._host_bytes,
                         "budget_bytes": self.host_budget},
                "disk": {"blocks": len(self._disk),
                         "bytes": self._disk_bytes,
                         "budget_bytes": self.disk_budget},
                "directory_entries": len(self._index),
                "events": self._seq,
                "queues": {"spill": len(self._spillq),
                           "restore": len(self._restoreq),
                           "ready": len(self._readyq),
                           "copydown": len(self._copyq)},
                "credits_bytes": self._credits,
                "last_error": self.last_error,
            }

    def stop(self, check: bool = True) -> None:
        """Join the worker, release every held resource in the ledger,
        and (by default) assert the tier balance sheet zeroes."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._worker.join(timeout=10.0)
        with self._cond:
            for h in list(self._host):
                self._host.pop(h)
                ledger_note("host_page", h, -1)
            self._host_bytes = 0
            for h in list(self._disk):
                # files stay on disk (it is the durable tier); the
                # ledger releases in-process ownership only
                self._disk.pop(h)
                ledger_note("disk_block", h, -1)
            self._disk_bytes = 0
            for h in list(self._index):
                del self._index[h]
                ledger_note("directory_entry", h, -1)
            self._children.clear()
            self._spillq.clear()
            self._restoreq.clear()
            self._restore_pending.clear()
            self._readyq.clear()
            self._copyq.clear()
            self._sync_gauges_locked()
        if check:
            ledger_check_zero("kvtier.stop", TIER_LEDGER_KINDS)

    # -- internals (lock held unless noted) ----------------------------------

    def _disk_path(self, h: str) -> str:
        return os.path.join(self.disk_dir, h + BLOCK_SUFFIX)

    def _publish_locked(self, op: str, e: TierEntry) -> None:
        try:
            failpoints.fire("directory.publish")
        except failpoints.InjectedFault as exc:
            if self.metrics is not None:
                self._c_publish_dropped.inc()
            self.last_error = f"directory.publish: {exc}"
            return
        self._seq += 1
        self._events.append({"seq": self._seq, "op": op, "hash": e.hash,
                             "parent": e.parent, "depth": e.depth,
                             "tier": e.tier})

    def _sync_gauges_locked(self) -> None:
        if self.metrics is None:
            return
        self._g_host_blocks.set(len(self._host))
        self._g_host_bytes.set(self._host_bytes)
        self._g_disk_blocks.set(len(self._disk))
        self._g_disk_bytes.set(self._disk_bytes)
        self._g_dir_entries.set(len(self._index))

    def _drop_entry_locked(self, e: TierEntry) -> None:
        """Remove one entry from the directory and free its payloads.
        Descendant entries stay indexed (unreachable until an ancestor
        is recomputed, then the chain reconnects)."""
        h = e.hash
        if h in self._host:
            _, nbytes = self._host.pop(h)
            self._host_bytes -= nbytes
            ledger_note("host_page", h, -1)
        if h in self._disk:
            try:
                os.remove(self._disk_path(h))
            except OSError:
                pass
            self._disk_forget_locked(h)
        kids = self._children.get(e.parent)
        if kids is not None and kids.get(e.key) == h:
            del kids[e.key]
            if not kids:
                del self._children[e.parent]
        self._index.pop(h, None)
        self._restore_pending.discard(h)
        ledger_note("directory_entry", h, -1)
        if self.metrics is not None:
            self._c_dropped.inc()
        self._publish_locked("del", e)

    def _disk_forget_locked(self, h: str) -> None:
        nbytes = self._disk.pop(h, None)
        if nbytes is not None:
            self._disk_bytes -= nbytes
            ledger_note("disk_block", h, -1)

    def _host_put_locked(self, h: str, pages: dict, nbytes: int) -> None:
        """Insert into the host ring; overflow demotes the LRU block to
        disk (or drops it when no disk tier / disk is over budget)."""
        if h in self._host:
            _, old = self._host.pop(h)
            self._host_bytes -= old
            ledger_note("host_page", h, -1)
        self._host[h] = (pages, nbytes)
        self._host_bytes += nbytes
        ledger_note("host_page", h, +1)
        while self._host_bytes > self.host_budget and len(self._host) > 1:
            old_h, (old_pages, old_nb) = self._host.popitem(last=False)
            self._host_bytes -= old_nb
            ledger_note("host_page", old_h, -1)
            oe = self._index.get(old_h)
            if oe is None:
                continue
            if self.disk_budget > 0 and self._demote_disk_locked(
                    oe, old_pages):
                if oe.tier == "host":
                    oe.tier = "disk"
                    self._publish_locked("put", oe)
            elif oe.tier == "host":
                self._drop_entry_locked(oe)
        self._cond.notify_all()

    def _demote_disk_locked(self, e: TierEntry, pages: dict) -> bool:
        payload = encode_block(e, pages)
        try:
            write_block_file(self._disk_path(e.hash), payload)
        except (OSError, ValueError) as exc:
            self.last_error = f"disk write: {exc}"
            return False
        self._disk[e.hash] = len(payload)
        self._disk_bytes += len(payload)
        ledger_note("disk_block", e.hash, +1)
        if self.metrics is not None:
            self._c_demoted.inc()
        while self._disk_bytes > self.disk_budget and len(self._disk) > 1:
            old_h = next(iter(self._disk))
            oe = self._index.get(old_h)
            try:
                os.remove(self._disk_path(old_h))
            except OSError:
                pass
            self._disk_forget_locked(old_h)
            if oe is not None and oe.tier == "disk":
                self._drop_entry_locked(oe)
        return True

    # -- worker thread --------------------------------------------------------

    def _take_credits_locked(self, nbytes: int) -> bool:
        """Block (bounded waits, re-checked predicate) until the pacing
        budget covers ``nbytes`` or the manager stops."""
        need = min(int(nbytes), self._credit_cap)
        while self._credits < need and not self._stopped:
            self._cond.wait(0.1)
        if self._stopped:
            return False
        self._credits -= need
        return True

    def _worker_loop(self) -> None:
        while True:
            item = None
            restore_h = None
            with self._cond:
                while (not self._stopped and not self._spillq
                       and not self._restoreq):
                    self._cond.wait(0.2)
                if self._stopped:
                    return
                if self._spillq:
                    item = self._spillq.popleft()
                elif self._restoreq:
                    restore_h = self._restoreq.popleft()
            try:
                if item is not None:
                    self._process_spill(*item)
                elif restore_h is not None:
                    self._process_restore(restore_h)
            except failpoints.InjectedFault as exc:
                with self._cond:
                    self.last_error = f"worker: {exc}"
                    if restore_h is not None:
                        self._restore_pending.discard(restore_h)
                        if self.metrics is not None:
                            self._c_restore_failed.inc()
            except Exception as exc:  # degrade, never kill the worker
                with self._cond:
                    self.last_error = f"worker: {exc!r}"
                    if restore_h is not None:
                        self._restore_pending.discard(restore_h)
                        if self.metrics is not None:
                            self._c_restore_failed.inc()

    def _process_spill(self, h: str, dev: dict, is_copy: bool) -> None:
        nbytes = sum(int(a.nbytes) for pks in dev.values()
                     for a in pks.values())
        with self._cond:
            if not self._take_credits_locked(nbytes):
                return
        # the one device->host transfer, off the scheduler thread and
        # paced: host_read blocks until the bytes land
        pages = {lk: {pk: host_read(a) for pk, a in pks.items()}
                 for lk, pks in dev.items()}
        with self._cond:
            e = self._index.get(h)
            if e is None:
                return  # dropped while in flight
            self._host_put_locked(h, pages, nbytes)
            if not is_copy and e.tier == "spilling":
                e.tier = "host"
                self._publish_locked("put", e)
            if self.metrics is not None:
                self._c_spilled.inc()
                self._c_spilled_bytes.inc(nbytes)
            self._sync_gauges_locked()
        if self.tracer is not None:
            self.tracer.instant(
                "tier_spill", track="kvtier",
                args={"hash": h[:12], "bytes": nbytes,
                      "copydown": bool(is_copy)})

    def _process_restore(self, h: str) -> None:
        failpoints.fire("tier.restore")
        with self._cond:
            e = self._index.get(h)
            if e is None or h not in self._restore_pending:
                self._restore_pending.discard(h)
                return
            pages = None
            nbytes = 0
            hit = self._host.get(h)
            if hit is not None:
                pages, nbytes = hit[0], hit[1]
                self._host.move_to_end(h)
        if pages is None:
            payload = read_block_file(self._disk_path(h))
            dec = decode_block(payload) if payload is not None else None
            with self._cond:
                if dec is None:
                    # torn/corrupt disk block: a miss, never wrong bytes
                    self._disk_forget_locked(h)
                    e2 = self._index.get(h)
                    if e2 is not None:
                        self._drop_entry_locked(e2)
                    self._restore_pending.discard(h)
                    if self.metrics is not None:
                        self._c_restore_failed.inc()
                    self._sync_gauges_locked()
                    return
            pages = dec[1]
            nbytes = sum(int(a.nbytes) for pks in pages.values()
                         for a in pks.values())
        with self._cond:
            if not self._take_credits_locked(0 if pages is None else nbytes):
                self._restore_pending.discard(h)
                return
            e = self._index.get(h)
            if e is None:
                self._restore_pending.discard(h)
                return
            if len(self._readyq) >= self.ready_blocks:
                self._restore_pending.discard(h)
                if self.metrics is not None:
                    self._c_restore_failed.inc()
                return
            self._readyq.append((e, pages, nbytes))
            if self.metrics is not None:
                self._c_restored.inc()
                self._c_restored_bytes.inc(nbytes)
        if self.tracer is not None:
            self.tracer.instant(
                "tier_restore", track="kvtier",
                args={"hash": h[:12], "bytes": nbytes})
