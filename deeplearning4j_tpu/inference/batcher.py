"""Continuous micro-batching for request/response inference.

The serving gap this closes: `serving/server.py` used to hold one global
lock around `net.output()` — N concurrent clients were N serialized device
dispatches of tiny batches. This module is the TensorFlow-Serving-style
batched-session layer (arXiv 1605.08695 §4.3) rebuilt for the JAX/XLA
substrate:

  - a bounded request queue with backpressure (`QueueFullError` once
    `max_queue` requests wait — admission control, not unbounded memory)
    and a per-request deadline (expired requests are failed with
    `RequestTimeoutError` *without* being dispatched);
  - a collator that aggregates waiting requests into ONE padded batch whose
    row count is drawn from a small set of bucketed shapes (powers of two
    up to `max_batch`), so XLA compiles once per bucket instead of once per
    observed request size;
  - a single dispatcher thread that owns every model call — the model needs
    no lock at all — and scatters result rows back to per-request futures.

Per-row independence is the contract: `forward_fn` must compute row i of
the output from row i of the input only (true of every inference forward
in this package — BN runs on global stats at inference). Under that
contract batched results are identical to per-request results, which
`tests/test_inference_engine.py` checks bit-for-bit.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..analysis.runtime import host_read
from . import failpoints
from .metrics import MetricsRegistry, default_registry
from .trace import FlightRecorder, default_recorder


class QueueFullError(RuntimeError):
    """Backpressure: the request queue is at capacity."""


class RequestTimeoutError(TimeoutError):
    """The request's deadline expired before results were ready."""


class InferenceFuture:
    """Completion handle for one submitted request (threading.Event based —
    no executor, the dispatcher resolves it directly)."""

    def __init__(self):
        self._done = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    def _resolve(self, result: np.ndarray) -> None:
        self._result = result
        self._done.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise RequestTimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("x", "future", "deadline", "t_enqueue")

    def __init__(self, x: np.ndarray, deadline: Optional[float]):
        self.x = x
        self.future = InferenceFuture()
        self.deadline = deadline
        self.t_enqueue = time.monotonic()


def pow2_buckets(max_batch: int) -> List[int]:
    """Ascending power-of-two sizes up to (and including) ``max_batch`` —
    the compile-once-per-bucket shape set. Shared by the request collator
    (batch-dimension buckets) and the decode scheduler's chunked prefill
    (prompt-chunk-length buckets, engine.py)."""
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def bucket_for(n: int, buckets: List[int]) -> int:
    """Smallest bucket covering ``n`` (buckets ascending — the output of
    `pow2_buckets`). One definition shared by the collator's batch
    padding, the scheduler's prefill-chunk sizing, and the paged-KV
    block-table widths, so every padded shape follows the same
    compile-once-per-bucket discipline."""
    return next(b for b in buckets if b >= n)


_pow2_buckets = pow2_buckets  # back-compat alias


class MicroBatcher:
    """Aggregates concurrent `submit()` calls into bucketed padded batches
    executed by one dispatcher thread.

    ``forward_fn``: np.ndarray [B, ...] -> array-like [B, ...], row-wise
    independent (typically ``lambda a: net.output(a)``).
    ``batch_window_s``: how long the collator waits for more requests after
    the first one arrives — the latency/occupancy knob.
    """

    def __init__(self, forward_fn: Callable[[np.ndarray], np.ndarray], *,
                 max_batch: int = 64, max_queue: int = 256,
                 batch_window_s: float = 0.002,
                 metrics: Optional[MetricsRegistry] = None,
                 tracer: Optional[FlightRecorder] = None,
                 name: str = "batcher"):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.forward_fn = forward_fn
        self.max_batch = int(max_batch)
        self.max_queue = int(max_queue)
        self.batch_window_s = float(batch_window_s)
        self.buckets = pow2_buckets(self.max_batch)
        self.metrics = metrics if metrics is not None else default_registry()
        # flight recorder (trace.py): one span per dispatched batch on
        # this batcher's OWN track + reject instants, so a slow /predict
        # is attributable to queueing vs the forward itself. The track
        # is scoped per instance — two per-signature batchers sharing a
        # recorder must not interleave same-name spans on one track
        self.tracer = tracer if tracer is not None else default_recorder()
        self._name = name
        self._track = name + self.tracer.track_scope(name)
        self._queue: List[_Request] = []
        self._cond = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # instruments (created eagerly so /metrics shows them at depth 0)
        m = self.metrics
        self._m_queue_depth = m.gauge(f"{name}_queue_depth")
        self._m_occupancy = m.histogram(f"{name}_batch_occupancy",
                                        lo=1.0, hi=float(self.max_batch) + 1,
                                        per_decade=12)
        self._m_rows = m.counter(f"{name}_rows_total")
        self._m_batches = m.counter(f"{name}_batches_total")
        self._m_requests = m.counter(f"{name}_requests_total")
        self._m_rejected = m.counter(f"{name}_rejected_total")
        self._m_timeouts = m.counter(f"{name}_timeouts_total")
        self._m_queue_time = m.histogram(f"{name}_queue_time_sec")
        self._m_latency = m.histogram(f"{name}_latency_sec")

    # -- client side -------------------------------------------------------
    def submit(self, x, timeout_s: Optional[float] = None) -> InferenceFuture:
        """Enqueue one request ([rows, ...features]) and return its future.
        Raises QueueFullError when `max_queue` requests already wait;
        `timeout_s` sets the request deadline (None = no deadline)."""
        x = np.asarray(x)
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        req = _Request(x, deadline)
        with self._cond:
            if not self._running:
                raise RuntimeError("batcher is not running (call start())")
            if len(self._queue) >= self.max_queue:
                self._m_rejected.inc()
                self.tracer.instant("reject", track=self._track,
                                    args={"reason": "queue_full",
                                          "waiting": len(self._queue)})
                raise QueueFullError(
                    f"queue full ({self.max_queue} requests waiting)")
            self._queue.append(req)
            self._m_requests.inc()
            self._m_queue_depth.set(len(self._queue))
            self._cond.notify()
        return req.future

    def predict(self, x, timeout_s: Optional[float] = None) -> np.ndarray:
        """Blocking submit; raises RequestTimeoutError past the deadline."""
        fut = self.submit(x, timeout_s=timeout_s)
        # grace on the client wait: the dispatcher enforces the deadline;
        # a request picked up right AT it still needs the forward to run
        try:
            return fut.result(timeout_s + 30.0
                              if timeout_s is not None else None)
        except RequestTimeoutError:
            if fut._error is None:  # client-wait expiry (forward still
                self._m_timeouts.inc()  # running) — dispatcher never counted it
            raise

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MicroBatcher":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"{self._name}-dispatch")
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._running = False
            pending = self._queue[:]
            self._queue.clear()
            self._cond.notify_all()
        for req in pending:
            req.future._fail(RuntimeError("batcher stopped"))
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- dispatcher --------------------------------------------------------
    def _take_batch(self) -> List[_Request]:
        """Block until work exists, then collate up to `max_batch` rows,
        waiting at most `batch_window_s` past the first arrival."""
        with self._cond:
            while self._running and not self._queue:
                self._cond.wait(timeout=0.1)
            if not self._running:
                return []
            window_end = time.monotonic() + self.batch_window_s
            while self._running:
                rows = sum(r.x.shape[0] for r in self._queue)
                left = window_end - time.monotonic()
                if rows >= self.max_batch or left <= 0:
                    break
                self._cond.wait(timeout=left)
            taken, rows = [], 0
            while self._queue:
                nxt = self._queue[0].x.shape[0]
                if taken and rows + nxt > self.max_batch:
                    break  # leave it for the next dispatch cycle
                req = self._queue.pop(0)
                taken.append(req)
                rows += nxt
            self._m_queue_depth.set(len(self._queue))
            return taken

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                with self._cond:
                    if not self._running:
                        return
                continue
            now = time.monotonic()
            live: List[_Request] = []
            for req in batch:
                if req.deadline is not None and now > req.deadline:
                    self._m_timeouts.inc()
                    req.future._fail(RequestTimeoutError(
                        "deadline expired after "
                        f"{now - req.t_enqueue:.3f}s in queue"))
                else:
                    self._m_queue_time.record(now - req.t_enqueue)
                    live.append(req)
            if not live:
                continue
            if self.tracer.enabled:  # keep tracing-off allocation-free
                self.tracer.begin(
                    "predict_batch", track=self._track,
                    args={"requests": len(live),
                          "rows": sum(r.x.shape[0] for r in live)})
            try:
                failpoints.fire("batcher.flush")  # chaos seam
                outs = self._dispatch([r.x for r in live])
            except Exception as e:  # model failure fails the REQUESTS,
                for req in live:    # never the dispatcher thread
                    req.future._fail(e)
                self.tracer.end("predict_batch", track=self._track,
                                args={"error": type(e).__name__})
                continue
            done = time.monotonic()
            self.tracer.end("predict_batch", track=self._track)
            for req, out in zip(live, outs):
                self._m_latency.record(done - req.t_enqueue)
                req.future._resolve(out)

    def _dispatch(self, xs: Sequence[np.ndarray]) -> List[np.ndarray]:
        """One padded bucketed forward over the concatenated requests;
        splits the result rows back out per request. Oversized single
        requests are chunked at `max_batch` (each chunk still bucketed)."""
        cat = np.concatenate(xs, axis=0) if len(xs) > 1 else xs[0]
        n = cat.shape[0]
        self._m_occupancy.record(len(xs))
        self._m_batches.inc()
        self._m_rows.inc(n)
        pieces = []
        for off in range(0, n, self.max_batch):
            chunk = cat[off:off + self.max_batch]
            bucket = bucket_for(chunk.shape[0], self.buckets)
            if bucket > chunk.shape[0]:
                pad = np.zeros((bucket - chunk.shape[0],) + chunk.shape[1:],
                               chunk.dtype)
                padded = np.concatenate([chunk, pad], axis=0)
            else:
                padded = chunk
            # the dispatcher's ONE sanctioned device->host readback per
            # batch: results must reach numpy to be scattered to futures
            out = host_read(self.forward_fn(padded))
            pieces.append(out[:chunk.shape[0]])
        full = np.concatenate(pieces, axis=0) if len(pieces) > 1 else pieces[0]
        outs, off = [], 0
        for x in xs:
            outs.append(full[off:off + x.shape[0]])
            off += x.shape[0]
        return outs
