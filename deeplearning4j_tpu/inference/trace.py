"""Request-lifecycle tracing: a span flight recorder for the serving stack.

`inference/metrics.py` answers "how is the fleet doing" — counters,
gauges, latency percentiles. It cannot answer "where did THIS request's
time go": a p99 TTFT may be queueing in the batcher, waiting for a free
slot, missing the prefix cache, or sitting behind another slot's prefill
chunks, and aggregates collapse all four into one number. TensorFlow's
runtime made the same move for its asynchronous executor — a built-in
step-timeline layer (Abadi et al., arXiv 1605.08695 §5) — and this module
is that layer for the decode scheduler: per-request causality, cheap
enough to stay on in production.

Design: a process-wide **flight recorder** — a fixed-capacity ring buffer
of span/event records. Appends are O(1) and lock-free:

  - the ring is preallocated (``[None] * capacity``) and never grows; an
    append builds ONE record tuple and stores it at ``seq % capacity``,
    overwriting the oldest record (flight-recorder semantics: the last N
    events always survive, history beyond that is intentionally lost);
  - the sequence numbers come from ``itertools.count()``, whose
    ``__next__`` is atomic in CPython (C-level, and internally locked on
    free-threaded builds) — concurrent writers (HTTP handler threads, the
    batcher dispatcher, the scheduler loop) each claim a distinct slot
    with no lock at all. Two writers a full ``capacity`` apart may target
    the same ring index; the younger record wins, which is exactly the
    overwrite semantics the ring already has. List item assignment is
    atomic, so a reader never observes a torn record — at worst a
    snapshot taken mid-write misses the very newest events.

Record taxonomy (the span tree every request gets):

  ``queued`` -> ``admit``(slot) -> ``prefix_restore``(hit_tokens) ->
  ``prefill`` [with per-chunk ``prefill_chunk``(bucket) spans on the slot
  track] -> ``decode``(iterations, tokens) -> ``finish``/``cancel``;
  plus scheduler-level instants: slot ``admit``/``free`` occupancy
  changes, ``pool_evict``/``pool_publish`` from the KV pool, ``compile``
  events (via `analysis.runtime.CompileCounter` cache-size deltas), and
  ``reject`` instants for backpressure 503s / 413s / 504s.

  Paged-KV engines (engine.paged) add block-lifecycle instants on the
  slot tracks — ``block_alloc`` (lazy allocation as ``pos`` crosses a
  block boundary), ``block_cow`` (copy-on-write duplication before a
  write into a shared block), ``preempt``/``resume`` (swap-out under
  pool pressure and later re-admission) — and a ``preempted`` span on
  the request track bridging the swap gap, so a preempted request's
  waterfall shows exactly where its wall time went while its blocks
  were lent out.

  Fault tolerance (`inference/supervisor.py`) adds a ``supervisor``
  track: ``engine_crash``/``engine_hang`` (the watchdog's verdict,
  with the exception type and iteration count), ``engine_restart``
  (backoff taken, requests recovering), ``degrade`` (ladder level
  changes), ``drain_begin``/``drain_swap``, and ``warmup_skipped``;
  plus a per-request ``recovered`` span on the request track bridging
  the gap between the crash and the resubmission's fresh ``queued`` —
  a recovered request's waterfall shows the outage it survived, and
  its ``finish`` instant carries a ``retries`` count.

Tracks: every record resolves to a named track at append time — a slot
track (``slot N``), a request track (``request <id>``), or a named
component track (``scheduler``, ``predict``, ``kvpool``, ``http``). The
Chrome trace-event export groups slot tracks under one process and
request tracks under another, so Perfetto renders the classic serving
waterfall: one row per slot showing interleaved prefill chunks, one row
per request showing its queued/prefill/decode life.

Exports:
  - ``snapshot(limit)``    -> JSON-able dict (``GET /trace?limit=N``);
                              ``snapshot(since=cursor)`` /
                              ``export(since=)`` tail the ring
                              incrementally — every response carries a
                              ``next_cursor`` the next poll passes back
                              (``GET /trace?since=N``), so pollers pay
                              O(new events), not O(ring)
  - ``chrome_trace(limit)``-> Chrome trace-event JSON, Perfetto-loadable
                              (``GET /trace?format=chrome``); every ``B``
                              is closed by a matching ``E`` even when the
                              ring wrapped mid-span (orphan begins are
                              closed at the last timestamp, orphan ends
                              dropped), and ``ts`` is monotonic per track
  - ``request_summaries(limit)`` -> per-request phase timings (the UI
                              ``/serving`` waterfall lines)
  - ``python -m deeplearning4j_tpu.inference.trace dump --url ...``
                              fetches a serving server's Chrome trace to
                              a file for Perfetto's "Open trace file"

Cross-process context (`serving/telemetry.py`, ISSUE 12): records carry
optional ``parent``/``origin`` fields — ``origin`` is a flow-edge id (a
hop's sender span id, derived from the fleet-wide ``X-Graft-Trace``
identity), ``parent`` the upstream process's span id, present only on
the receiving side. The Chrome export turns them into flow events
(``s`` at the originating span, ``f`` at each downstream span), so a
trace merged from several processes draws one arrowed waterfall per
request; :meth:`FlightRecorder.clock` is the monotonic-epoch + wall
handshake the fleet aggregator uses to put N processes' timestamps on
one axis.
"""
from __future__ import annotations

import itertools
import json
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["FlightRecorder", "default_recorder", "new_request_id",
           "render_chrome_events"]

# record tuple layout (kept positional: one tuple alloc per append);
# _PARENT/_ORIGIN are the cross-process trace-context fields (ISSUE 12),
# None for every purely-local record
_SEQ, _TS, _PH, _NAME, _TRACK, _ARGS, _PARENT, _ORIGIN = range(8)

_rid_counter = itertools.count(1)


def new_request_id() -> str:
    """Process-unique request id (``r000001``, ...): claimed lock-free
    from an `itertools.count`, same atomicity argument as the ring."""
    return f"r{next(_rid_counter):06d}"


class FlightRecorder:
    """Fixed-capacity ring buffer of span begin/end and instant events.

    ``capacity``: how many records the ring holds (oldest overwritten
    first). ``capacity <= 0`` or ``enabled=False`` builds a disabled
    recorder whose append methods return immediately — the hot-path cost
    of tracing-off is one attribute test.
    """

    def __init__(self, capacity: int = 8192, *, enabled: bool = True):
        self.capacity = max(0, int(capacity))
        self.enabled = bool(enabled) and self.capacity > 0
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._seq = itertools.count()
        self._scopes: Dict[str, int] = {}
        self._t0 = time.monotonic()

    def track_scope(self, kind: str) -> str:
        """Track-name suffix disambiguating multiple instances of one
        component kind writing to the SAME recorder (two per-signature
        batchers, two schedulers on the process-wide recorder): the
        first claimant gets "" (the pretty bare track names), later ones
        " (2)", " (3)", ... — without this, same-name spans from two
        writers interleave on one track and the export's LIFO pairing
        crosses their begin/ends. Called at component construction, not
        on the hot path."""
        n = self._scopes.get(kind, 0) + 1
        self._scopes[kind] = n
        return "" if n == 1 else f" ({n})"

    # -- hot path ----------------------------------------------------------
    def _append(self, ph: str, name: str, req: Optional[str],
                slot: Optional[int], track: Optional[str],
                args: Optional[dict], parent: Optional[str] = None,
                origin: Optional[str] = None) -> None:
        if track is None:
            if slot is not None:
                track = f"slot {slot}"
            elif req is not None:
                track = f"request {req}"
            else:
                track = "scheduler"
        seq = next(self._seq)  # atomic claim; no lock
        self._buf[seq % self.capacity] = (
            seq, time.monotonic(), ph, name, track, args, parent, origin)

    def begin(self, name: str, req: Optional[str] = None,
              slot: Optional[int] = None, track: Optional[str] = None,
              args: Optional[dict] = None, parent: Optional[str] = None,
              origin: Optional[str] = None) -> None:
        """Open a span on the resolved track (close with :meth:`end`).

        ``origin``: the flow-edge id this span belongs to (a hop's
        sender span id, derived from the fleet-wide ``X-Graft-Trace``
        identity) — the Chrome export emits a flow event binding the
        span into the cross-process request chain. ``parent``: the
        upstream process's span id; set (alongside ``origin``) on the
        RECEIVING side of a hop, absent on the originating side, so
        the export knows which side is the arrow's tail (``s``) and
        which the head (``f``)."""
        if self.enabled:
            self._append("B", name, req, slot, track, args, parent, origin)

    def end(self, name: str, req: Optional[str] = None,
            slot: Optional[int] = None, track: Optional[str] = None,
            args: Optional[dict] = None) -> None:
        if self.enabled:
            self._append("E", name, req, slot, track, args)

    def instant(self, name: str, req: Optional[str] = None,
                slot: Optional[int] = None, track: Optional[str] = None,
                args: Optional[dict] = None) -> None:
        if self.enabled:
            self._append("i", name, req, slot, track, args)

    def clock(self) -> dict:
        """Monotonic-epoch + wall handshake pair (``GET /trace/clock``):
        event ``ts`` values are seconds since this recorder's monotonic
        ``trace_t0``, so an aggregator that reads (monotonic, wall,
        trace_t0) in one response — and brackets the request with its
        OWN wall clock for an RTT bound — can place every event of this
        process on its local wall axis to within ±RTT/2."""
        return {"monotonic": time.monotonic(), "wall": time.time(),
                "trace_t0": self._t0}

    # -- read side ---------------------------------------------------------
    def _records(self) -> List[tuple]:
        """Surviving raw records, ts-ordered (seq breaks ties): one
        lock-free list copy, then sort — records written while copying
        either make it in whole or not at all (item assignment is
        atomic), never torn. Sorted by TIMESTAMP: seq claim and
        `time.monotonic()` stamp are two steps, so a preempted writer
        can hold an older seq with a newer ts — ts order is the true
        temporal order the exports guarantee per track."""
        recs = [r for r in list(self._buf) if r is not None]
        recs.sort(key=lambda r: (r[_TS], r[_SEQ]))
        return recs

    def _to_dicts(self, recs: List[tuple]) -> List[dict]:
        out = []
        for r in recs:
            e = {"seq": r[_SEQ], "ts": round(r[_TS] - self._t0, 6),
                 "ph": r[_PH], "name": r[_NAME], "track": r[_TRACK]}
            if r[_ARGS]:
                e["args"] = r[_ARGS]
            if r[_PARENT]:
                e["parent"] = r[_PARENT]
            if r[_ORIGIN]:
                e["origin"] = r[_ORIGIN]
            out.append(e)
        return out

    def events(self, limit: Optional[int] = None) -> List[dict]:
        """The surviving records, oldest first, as JSON-able dicts.
        ``limit`` keeps only the newest N."""
        recs = self._records()
        if limit is not None and limit > 0:
            recs = recs[-limit:]
        return self._to_dicts(recs)

    def snapshot(self, limit: Optional[int] = None,
                 since: Optional[int] = None) -> dict:
        """``GET /trace`` body: the events plus ring accounting (how many
        records ever written, how many the ring has since overwritten).

        ``since``: incremental-tail cursor — only events with ``seq >=
        since`` are returned, and the response's ``next_cursor`` is what
        the next poll should pass as ``since`` (`GET /trace?since=N`):
        the UI and external pollers tail the ring in O(new events)
        instead of re-downloading the whole buffer each poll. A cursor
        that fell behind the ring (older than ``total_recorded -
        capacity``) silently returns the oldest surviving events — the
        ``dropped`` delta tells the poller what it missed.

        Best-effort like every read of this lock-free ring: seq claim
        and slot store are two steps, so a writer preempted between
        them holds a seq BELOW a later writer's already-visible record;
        a poll snapshotting in that sub-microsecond window advances
        ``next_cursor`` past the in-flight seq and the tail never
        delivers it (the same class of loss as ring overwrite — the
        recorder trades completeness for its zero-lock hot path, and a
        full re-download shows the record).

        Cursor tails really are O(new events): records behind the
        cursor are dropped at the raw-tuple stage, BEFORE any dict
        building — a 20 Hz fleet poller against a full 8192-slot ring
        pays for what changed, not the whole buffer (the regression
        `bench.py trace_aggregation` floor-gates: scraping must not
        perturb the engines)."""
        recs = self._records()
        total = (max(r[_SEQ] for r in recs) + 1) if recs else 0
        cursor = total
        if since is not None and since >= 0:
            # since=0 is the documented INITIAL cursor and must take
            # this branch: falling through to the legacy newest-N limit
            # semantics would silently skip the oldest events on the
            # very first page of a tail
            recs = [r for r in recs if r[_SEQ] >= since]
            if limit is not None and 0 < limit < len(recs):
                # cursor mode pages FORWARD: keep the OLDEST N so the
                # next poll's since resumes exactly after the last
                # returned event — keeping the newest N here (the
                # legacy limit semantics) would silently skip the
                # middle of a burst and next_cursor would paper over it
                recs = recs[:limit]
                cursor = max(r[_SEQ] for r in recs) + 1
        elif limit is not None and limit > 0:
            recs = recs[-limit:]
        return {"capacity": self.capacity, "total_recorded": total,
                "dropped": max(0, total - self.capacity),
                "next_cursor": cursor,
                "events": self._to_dicts(recs)}

    def export(self, since: Optional[int] = None,
               limit: Optional[int] = None) -> dict:
        """Cursor-first alias of :meth:`snapshot` for programmatic
        pollers: ``cur = 0;  while ...: batch = tracer.export(since=cur);
        cur = batch["next_cursor"]`` tails the ring incrementally."""
        return self.snapshot(limit=limit, since=since)

    def clear(self) -> None:
        """Reset the ring (tests / between bench rounds). Not safe
        against concurrent writers — quiesce first: that contract (not a
        lock) is what orders this swap against `_append`'s lock-free
        slot claims, hence the reviewed CC005 suppression."""
        self._buf = [None] * self.capacity  # graftlint: disable=CC005
        self._seq = itertools.count()
        # same quiesce-first contract as _buf above: a concurrent
        # snapshot during clear() is caller error, not a data race
        self._t0 = time.monotonic()  # graftlint: disable=CC005

    # -- Chrome trace-event export -----------------------------------------
    def chrome_trace(self, limit: Optional[int] = None) -> dict:
        """Chrome trace-event JSON (Perfetto / chrome://tracing loadable).

        Tracks map to (pid, tid): slot tracks under the ``decode slots``
        process, request tracks under ``requests``, component tracks
        under ``serving``. Ring wraparound can orphan one side of a span:
        an ``E`` whose ``B`` was overwritten is dropped, a ``B`` whose
        ``E`` is missing (still open, or overwritten) is closed at the
        last exported timestamp — so every emitted ``B`` has a matching
        ``E``, properly nested per track, with monotonic ``ts``. Spans
        carrying cross-process context (``origin``) additionally emit a
        flow event, so a merged multi-process trace draws one arrow
        chain per request."""
        evs = self.events(limit)
        tids: Dict[str, tuple] = {}
        counters = {0: 0, 1: 0, 2: 0}

        def tid_of(track: str) -> tuple:
            if track not in tids:
                pid = (1 if track.startswith("slot ")
                       else 2 if track.startswith("request ") else 0)
                counters[pid] += 1
                tids[track] = (pid, counters[pid])
            return tids[track]

        out: List[dict] = []
        render_chrome_events(evs, tid_of, out)
        meta = [{"name": "process_name", "ph": "M", "pid": p, "tid": 0,
                 "args": {"name": label}}
                for p, label in ((0, "serving"), (1, "decode slots"),
                                 (2, "requests")) if counters[p]]
        meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                  "args": {"name": track}}
                 for track, (pid, tid) in sorted(tids.items())]
        return {"displayTimeUnit": "ms", "traceEvents": meta + out}

    # -- waterfall summaries -----------------------------------------------
    def request_summaries(self, limit: int = 16) -> List[dict]:
        """The newest N completed requests' phase timings, oldest first —
        scraped from the ``finish``/``cancel`` instants the scheduler
        stamps with the handle's timing breakdown. Feeds the UI
        ``/serving`` waterfall lines."""
        done = [e for e in self.events()
                if e["ph"] == "i" and e["name"] in ("finish", "cancel")
                and e.get("args", {}).get("request_id")]
        done = done[-max(1, limit):]
        return [{"outcome": e["name"], **e["args"]} for e in done]


def render_chrome_events(evs: List[dict],
                         tid_of: Callable[[str], Tuple[int, int]],
                         out: List[dict]) -> float:
    """Render ``events()``-shaped dicts into Chrome trace events on
    ``out`` — the core shared by :meth:`FlightRecorder.chrome_trace`
    (one process) and `serving.telemetry.TraceAggregator` (N processes
    merged onto one axis; the caller pre-aligns ``ts`` and maps each
    process to its own pid group via ``tid_of``).

    Guarantees: every ``B`` is closed by a matching ``E`` (orphan ends
    dropped, orphan begins closed at the last timestamp), LIFO-nested
    and ts-monotonic per (pid, tid). Spans carrying ``origin`` (the
    fleet-wide trace id) emit a flow event at the span's begin — phase
    ``s`` on the originating side (no ``parent``), phase ``f`` with
    ``bp: "e"`` (bind to enclosing slice) on each receiving side — so
    Perfetto draws one arrow chain per propagated request.

    Returns the last rendered timestamp (seconds)."""
    stacks: Dict[tuple, List[dict]] = {}
    last_ts = 0.0

    def emit(ph: str, name: str, ts: float, pid: int, tid: int,
             args: Optional[dict]) -> dict:
        e = {"name": name, "ph": ph, "ts": round(ts * 1e6, 1),
             "pid": pid, "tid": tid}
        if ph == "i":
            e["s"] = "t"  # thread-scoped instant
        if args:
            e["args"] = args
        out.append(e)
        return e

    for ev in evs:
        pid, tid = tid_of(ev["track"])
        ts = ev["ts"]
        last_ts = max(last_ts, ts)
        args = ev.get("args")
        if ev["ph"] == "B":
            stacks.setdefault((pid, tid), []).append(
                emit("B", ev["name"], ts, pid, tid, args))
            origin = ev.get("origin")
            if origin:
                # flow events share the slice's (ts, pid, tid) so the
                # binding slice is unambiguous; the id IS the fleet
                # trace id, so sides emitted by different processes
                # join into one flow once merged
                flow = {"name": "graft", "cat": "graft",
                        "id": str(origin), "ts": round(ts * 1e6, 1),
                        "pid": pid, "tid": tid}
                if ev.get("parent"):
                    flow["ph"] = "f"
                    flow["bp"] = "e"
                else:
                    flow["ph"] = "s"
                out.append(flow)
        elif ev["ph"] == "E":
            stack = stacks.get((pid, tid), [])
            if not any(b["name"] == ev["name"] for b in stack):
                continue  # orphan end: its begin was overwritten
            # close intervening opens first (their end was lost to
            # the ring, or the writer died mid-span) to keep nesting
            while stack and stack[-1]["name"] != ev["name"]:
                inner = stack.pop()
                emit("E", inner["name"], ts, pid, tid, None)
            stack.pop()
            emit("E", ev["name"], ts, pid, tid, args)
        else:
            emit("i", ev["name"], ts, pid, tid, args)
    for (pid, tid), stack in stacks.items():
        while stack:  # still-open spans close at the last timestamp
            b = stack.pop()
            emit("E", b["name"], last_ts, pid, tid, None)
    return last_ts


_default: Optional[FlightRecorder] = None


def default_recorder() -> FlightRecorder:
    """Process-wide recorder for components not handed an explicit one
    (same pattern as `metrics.default_registry`). Creation is idempotent
    enough lock-free: a lost race leaks one empty ring, never records."""
    global _default
    if _default is None:
        _default = FlightRecorder()
    return _default


# -- CLI: dump a serving server's trace for Perfetto ------------------------
def main(argv=None) -> int:
    import argparse
    import urllib.request

    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.inference.trace",
        description="Fetch a serving server's flight-recorder trace")
    sub = p.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("dump", help="write the Chrome trace-event JSON "
                                    "(load it at ui.perfetto.dev)")
    d.add_argument("--url", default="http://127.0.0.1:8080",
                   help="serving server base URL")
    d.add_argument("--out", default="trace.json",
                   help="output path (Chrome trace-event JSON)")
    d.add_argument("--limit", type=int, default=0,
                   help="newest N events only (0 = everything surviving)")
    args = p.parse_args(argv)
    url = f"{args.url.rstrip('/')}/trace?format=chrome"
    if args.limit:
        url += f"&limit={args.limit}"
    with urllib.request.urlopen(url) as resp:
        trace = json.loads(resp.read())
    with open(args.out, "w") as fh:
        json.dump(trace, fh)
    n = len(trace.get("traceEvents", []))
    tracks = len({(e.get("pid"), e.get("tid")) for e in
                  trace.get("traceEvents", []) if e.get("ph") != "M"})
    print(f"{args.out}: {n} events on {tracks} tracks "
          "(open at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
