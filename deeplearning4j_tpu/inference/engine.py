"""Slot-based continuous-batching decode scheduler for generative LMs.

`models/sampling.generate_transformer` decodes ONE sequence at a time: a
serving host running it back-to-back leaves (slots-1)/slots of every decode
step's batch dimension empty. This engine is the Orca-style iteration-level
scheduler (continuous batching) over the existing attention KV cache:

  - a fixed number of decode *slots* (the batch dimension of one shared,
    per-layer KV cache / recurrent state pytree);
  - each engine step runs ALL slots through ONE jitted single-token
    forward — int32 token ids in (the one-hot is built on device inside
    the program, so per-step host->device traffic is n_slots ints, not a
    dense [n_slots, 1, vocab] float batch), next-token distributions out.
    The XLA program is compiled exactly once and never recompiles as
    sequences come and go;
  - new sequences are admitted into free slots *between* steps (their
    slot's state rows are zeroed and, for attention layers, the per-slot
    cache position — `nn/layers/attention.py` vector-``pos`` plumbing —
    restarts at 0; stale K/V beyond a row's own position is causally
    masked, so slot reuse needs no cache wipe to be correct);
  - finished sequences (max tokens or EOS) are evicted the step they
    finish, freeing the slot for the next queued request.

Chunked prefill (the ISSUE 2 tentpole): prompts no longer prefill
token-by-token. A second family of jitted programs — one per power-of-two
chunk bucket (16/32/64/... up to ``prefill_chunk``, reusing the batcher's
bucket helper) — runs C prompt tokens through the net in ONE forward for a
single slot: the slot's state rows are sliced out of the shared pytree,
the chunk writes K/V rows ``[pos, pos+C)`` in one offset
`dynamic_update_slice` (RoPE phases from the slot's absolute positions,
causal masking within the chunk), and the rows are scattered back. Nets
with recurrent h/c state (LSTM/GRU facades) prefill through an equivalent
`lax.scan` chunk program — C single-token steps fused into one device
dispatch, padded steps masked out of the state carry. Time-to-first-token
drops from O(prompt_len) to O(prompt_len / C) engine steps.

Scheduling is Sarathi-style: each iteration runs AT MOST ONE bounded
prefill chunk alongside the regular all-slots decode step, so decode
latency for resident sequences stays protected while admitted prompts
still prefill C tokens per iteration. Slots that are mid-prefill (or idle)
are masked out of the decode step *inside* the jitted program — their
recurrent state and cache position are frozen by a `live` mask, so the
shared-batch step cannot corrupt a half-prefilled slot.

Prefix KV reuse (the ISSUE 4 tentpole, `inference/kvpool.py`): with
``prefix_cache_mb > 0`` the engine keeps a block pool + radix-trie prefix
index over completed prompts' prefill-written K/V. Admission walks the
trie over the prompt's full ``kv_block``-sized blocks, restores the
longest cached prefix into the slot's contiguous cache rows with ONE
jitted block-gather program (bucketed by chain length, same pow2 compile
discipline as prefill) and advances ``pos`` past the hit — chunked
prefill then only runs the cold suffix, so a repeated prompt reaches its
first token in ~1 engine step instead of O(prompt/C). When a sequence
finishes, its prompt's full blocks are published back into the pool
(copy out of the slot cache, functional scatter into pool storage) and
indexed; cached keys are stored pre-rotated at absolute positions, so a
pos-0-anchored prefix is bit-identical across requests.

Paged KV decode (the ISSUE 6 tentpole, ``kv_pool_mb > 0``): the live
decode cache itself becomes the block pool. Per-layer K/V moves from
``[n_slots, max_cache_len]`` stripes into pool-wide page arrays
(``[capacity+1, kv_block]`` rows, page 0 scratch) and each slot reaches
its rows through a host-authoritative int32 block table shipped per
dispatch, padded to pow2 bucket widths (one XLA program per bucket — no
per-length recompiles). HBM cost stops being ``slots × max_cache_len``:
admission is bounded by POOL bytes (oversize prompts 413 only when they
cannot fit the whole pool), blocks allocate lazily as ``pos`` crosses
block boundaries, prefix restore/publish degenerate to zero-copy
block-table remaps against the pool's trie (copy-on-write duplicates
the one shared block a full-prompt hit's refeed writes), and under pool
pressure the latest-submitted slot is preempted — blocks released,
sequence requeued at the front, resumed later by re-prefilling prompt +
generated-so-far (host RNG untouched, so the resumed output is
token-identical to an unpreempted run).

Token selection reuses `models/sampling.sample_logits`, so greedy engine
output is token-identical to solo `generate_transformer(use_cache=True)`
decoding (tested, chunked and token-by-token, prefix-restored and cold,
paged and contiguous), and seeded sampled output matches too (same
per-sequence RNG consumption order).

Works for both facades: transformer ComputationGraphs (KV-cache states)
and recurrent MultiLayerNetworks (h/c states — admitting a sequence zeroes
its slot's rows).
"""
from __future__ import annotations

import contextlib
import functools
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.runtime import (CompileCounter, device_index, host_read,
                                ledger_check_request, ledger_check_zero,
                                ledger_forget, ledger_note)
from ..models.sampling import sample_logits
from ..nn.layers.recurrent import (BaseRecurrentImpl,
                                   _materialize_rnn_states)
from ..nn.multilayer import _compute_dtype_of
from . import failpoints
from .batcher import QueueFullError, bucket_for, pow2_buckets
from .kvpool import (PAGE_KEYS, SCRATCH_BLOCK, KVPool, gather_blocks,
                     scatter_blocks)
from .logitproc import CompiledGrammar, LogitState, MaskPool
from .metrics import MetricsRegistry, default_registry
from .profiler import StepPhaseProfiler, program_costs
from .sharding import (TP_AXIS, decode_mesh, kv_heads_shardable,
                       shard_decode_params, state_shardings,
                       storage_shardings)
from .speculative import ForkGroup, accept_tokens, build_shallow_draft
from .trace import FlightRecorder, default_recorder, new_request_id

# chunk buckets never go below this (a 3-token tail still pads to one
# small program instead of compiling a 3-wide one-off); buckets smaller
# than 16 only exist when prefill_chunk itself is smaller
_MIN_CHUNK_BUCKET = 16

# the resource kinds THIS module's ledger seams own (graftleak's runtime
# half, `analysis.runtime.resource_ledger`): request-end and stop-time
# balance checks judge only these, so an in-process router's still-open
# journal record for the same request id is never misread as an engine
# leak
_LEDGER_KINDS = frozenset(
    ("trie_pin", "pool_block", "mask_row", "engine_slot"))


class _EngineFenced(Exception):
    """Internal: a fenced (supervisor-disowned) scheduler thread woke up
    mid-iteration; unwind out of the loop without touching handles."""


class PromptTooLongError(ValueError):
    """The request cannot fit the KV cache. Contiguous mode:
    ``len(prompt) + max_new_tokens - 1 > max_cache_len``. Paged mode the
    bound is the WHOLE pool — rejected only when the request's block
    count exceeds ``capacity_blocks`` (``blocks_needed`` /
    ``blocks_available`` attributes carry the admission math for the
    serving layer's 413 body). Raised at submit time (never admitted,
    never queued) so the serving layer can answer HTTP 413 instead of
    the sequence dying mid-decode on the attention layer's
    cache-overflow guard."""

    blocks_needed: Optional[int] = None
    blocks_available: Optional[int] = None


class LoadSheddedError(QueueFullError):
    """The request was dropped from the queue by the graceful-degradation
    ladder (`inference/supervisor.py` level >= 1: queued load below the
    surviving priority line is shed before the engine melts). A
    QueueFullError subclass so the serving layer's existing 503 mapping
    (retryable, not a client error) applies unchanged."""


class EngineCrashedError(RuntimeError):
    """The scheduler loop died (uncaught exception or injected fault)
    with this request in flight and no supervisor attached to recover
    it. Supervised engines never surface this — the supervisor requeues
    the request onto the rebuilt engine instead."""


class DecodeHandle:
    """Completion handle for one submitted generation request."""

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 request_id: Optional[str] = None, priority: int = 0):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.request_id = request_id or new_request_id()
        self.priority = int(priority)
        self.retries = 0  # crash-recovery resubmissions (supervisor)
        self.tokens: List[int] = []
        # why the request ended: "length" | "eos" | "stop" | "grammar"
        # | "cancelled" (None while decoding / on error) — echoed in
        # the /generate response and the SSE terminal event
        self.finish_reason: Optional[str] = None
        # per-request token event queue (logitproc.TokenStream) for SSE
        # streaming; the scheduler pushes released tokens as they
        # decode, _finish() closes it with the terminal event
        self.stream = None
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        # lifecycle timestamps stamped by the scheduler thread: the
        # request's wall time splits into four CONTIGUOUS phases —
        # queued [submit, admitted], restore [admitted, restored] (slot
        # reset + prefix-cache restore), prefill [restored, first token],
        # decode [first token, done] — so the `timings()` breakdown sums
        # to the end-to-end latency by construction
        self.t_admitted: Optional[float] = None
        self.t_restored: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        # engine iterations this sequence was stepped before its first
        # token (the bench's TTFT-in-steps: prompt_len token-by-token,
        # ceil(prompt_len / chunk) chunked)
        self.steps_to_first_token: Optional[int] = None

    def timings(self) -> Dict[str, float]:
        """Per-phase wall-time breakdown (ms). Phases are contiguous
        segments of [t_submit, t_done], so ``queue_ms + restore_ms +
        prefill_ms + decode_ms == total_ms`` (a request cancelled before
        a boundary reports 0 for the phases it never reached)."""
        end = self.t_done if self.t_done is not None else time.monotonic()
        admitted = self.t_admitted if self.t_admitted is not None else end
        restored = self.t_restored if self.t_restored is not None \
            else admitted
        first = self.t_first_token if self.t_first_token is not None else end
        first = max(first, restored)
        return {
            "queue_ms": round((admitted - self.t_submit) * 1e3, 3),
            "restore_ms": round((restored - admitted) * 1e3, 3),
            "prefill_ms": round((first - restored) * 1e3, 3),
            "decode_ms": round((end - first) * 1e3, 3),
            "total_ms": round((end - self.t_submit) * 1e3, 3),
        }

    def _finish(self, err: Optional[BaseException] = None) -> None:
        if self._done.is_set():
            return  # first finisher wins (supervisor shutdown can race
            # the engine's own teardown sweep over the same handle)
        self._error = err
        self.t_done = time.monotonic()
        self._done.set()
        if self.stream is not None:
            # the stream's terminal event (tokens are FINAL here — stop
            # truncation happens before _finish): flushes any tokens the
            # stop hold-back withheld, then the done record
            self.stream.close(self, err)

    def _reset_for_retry(self) -> None:
        """Crash recovery (`inference/supervisor.py`): wipe the partial
        progress so a resubmission re-runs the request from scratch on
        the rebuilt engine. Decode is deterministic per request — the
        resubmitted `_ActiveSeq` reseeds `default_rng(seed)` — so the
        re-run reproduces the SAME token sequence the crashed attempt
        was mid-way through (token-identity across restarts). t_submit
        survives: recovered-request latency is measured from the
        ORIGINAL submit, crash included."""
        assert not self._done.is_set(), \
            "cannot retry a handle that already finished"
        self.retries += 1
        self.tokens = []
        self._error = None
        # one statement, GIL-atomic per store: only the supervisor calls
        # this, only for handles of a FENCED engine (its thread joined or
        # exiting at the fence check), so no writer races it; a client
        # thread calling timings() mid-reset reads each phase stamp
        # either old or None — both of which timings() already clamps
        self.t_admitted = self.t_restored = None  # graftlint: disable=CC005
        self.t_first_token = self.t_done = None  # graftlint: disable=CC005
        self.steps_to_first_token = None
        self.finish_reason = None
        # self.stream is deliberately KEPT: its index-deduplicated
        # pushes make the token-identical re-decode invisible to a
        # streaming client (already-streamed indices are skipped)

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> None:
        """Ask the scheduler to evict this sequence at its next step.

        Without this, a caller that times out waiting on `result()` leaks
        its slot: the sequence keeps decoding to max_new_tokens with
        nobody reading the answer. Cancellation is asynchronous — the
        scheduler thread frees the slot, counts `decode_cancelled_total`,
        and marks the handle done (with whatever tokens were produced).
        Cancelling a finished handle is a no-op."""
        self._cancel.set()

    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("generation not finished")
        if self._error is not None:
            raise self._error
        return self.tokens


class _ActiveSeq:
    """Book-keeping for one slot-resident sequence."""
    __slots__ = ("handle", "prompt", "fed", "rng", "temperature", "top_k",
                 "top_p", "eos_id", "steps", "pool_node", "block_ids",
                 "shared", "written", "phase", "resumed", "folded",
                 "cow_starved", "fork", "draft_fed", "proc")

    def __init__(self, handle: DecodeHandle, prompt: Sequence[int],
                 temperature: float, top_k: Optional[int],
                 top_p: Optional[float], seed: int, eos_id: Optional[int]):
        self.handle = handle
        self.prompt = [int(t) for t in prompt]
        self.fed = 0  # prompt tokens fed so far
        self.rng = np.random.default_rng(seed)
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.steps = 0  # engine iterations that advanced this sequence
        self.pool_node = None  # locked trie node of the restored prefix
        # -- paged-mode bookkeeping (engine.paged) --
        self.block_ids: List[int] = []  # table entries, logical order
        self.shared: List[bool] = []    # True = trie-owned (COW on write)
        self.written = 0  # host mirror of the slot's device cache pos
        # request-track span currently open ("queued" -> "prefill" ->
        # "decode", with "preempted" bridging a swap-out) — the single
        # source of truth for span transitions, because a RESUMED
        # sequence re-enters prefill with t_first_token already stamped
        self.phase = "queued"
        self.resumed = False  # has been preempted at least once
        self.folded = 0  # generated tokens already folded into `prompt`
        # set when a COW duplicate could not get a page even by
        # preempting (every page backs this very prompt): the resume's
        # restore caps its hit one block short so no write ever lands in
        # a shared block — without this a full-pool full-prompt hit
        # would preempt/restore/starve forever
        self.cow_starved = False
        # -- best-of-n fork group (speculative.ForkGroup, or None) --
        self.fork = None
        # -- speculative decoding: tokens of `full_context()` the DRAFT
        # net has ingested (its contiguous cache row count / pos mirror)
        self.draft_fed = 0
        # per-request logit-processor pipeline (logitproc.LogitState):
        # penalty counts, grammar DFA state, stop matcher, device-mask
        # residency. None for plain requests — the hot path unchanged.
        self.proc: Optional[LogitState] = None

    def full_context(self) -> List[int]:
        """Every token the sequence is conditioned on so far (prompt —
        which absorbs preempt-folded generations — plus the unfolded
        generated tail). The draft net's catch-up target."""
        return self.prompt + self.handle.tokens[self.folded:]

    def known_tokens(self) -> int:
        """len(full_context()) without building the list."""
        return len(self.prompt) + len(self.handle.tokens) - self.folded

    def tail_context(self, k: int) -> List[int]:
        """The last ``k`` tokens of `full_context` as an O(k) slice —
        the speculative lockstep only ever feeds the trailing lag<=2
        tokens, and copying a multi-thousand-token context per slot per
        iteration onto the hot path would tax the very loop speculation
        exists to speed up."""
        gen = self.handle.tokens[self.folded:] if k > 0 else []
        if len(gen) >= k:
            return gen[len(gen) - k:]
        return self.prompt[len(self.prompt) - (k - len(gen)):] + gen

    def next_input(self) -> int:
        """Token to feed this step: the next prompt token while prefilling,
        else the last generated token."""
        if self.fed < len(self.prompt):
            return self.prompt[self.fed]
        return self.handle.tokens[-1]

    @property
    def sampling(self) -> bool:
        """Past the last prompt token, every step's output is sampled."""
        return self.fed >= len(self.prompt)


class DecodeScheduler:
    """Continuous-batching decode over a shared model and KV cache.

    ``net``: a trained ComputationGraph (e.g. `models/zoo.transformer_lm`,
    causal attention) or recurrent MultiLayerNetwork whose output is a
    next-token distribution. The engine owns a private state pytree — it
    never touches ``net._rnn_state``, so callers may keep using the net's
    own streaming API concurrently (single-threaded model access is still
    required; the engine's step thread is that single thread while
    running).

    ``prefill_chunk``: max prompt tokens per prefill program (the TTFT /
    decode-latency knob — bigger chunks reach the first token in fewer
    iterations but each chunked iteration holds the device longer, adding
    tail latency to resident decodes). <= 1 disables chunked prefill and
    restores token-by-token prompt feeding through the decode step.

    ``kv_pool_mb``: byte budget (MiB) for the PAGED live-decode KV pool
    (`inference/kvpool.py`, the ISSUE 6 tentpole). > 0 replaces the
    per-slot contiguous ``max_cache_len`` stripes with pool-wide
    fixed-size pages reached through per-slot block tables: slot
    capacity is bounded by pool bytes (admission is pool-sized, not
    ``max_cache_len``-sized), blocks allocate lazily as ``pos`` crosses
    block boundaries, prefix restore/publish are zero-copy block-table
    remaps against the built-in trie prefix index, and under pool
    pressure the latest-submitted slot is preempted (blocks released,
    sequence requeued and later resumed, token-identically). Attention
    nets only; recurrent nets fall back to contiguous with a warning.

    ``prefix_cache_mb``: byte budget (MiB) for the CONTIGUOUS-mode side
    prefix pool (ignored when ``kv_pool_mb`` is set — the paged pool is
    its own prefix cache); 0 disables prefix reuse. ``kv_block``:
    positions per pool block in either mode — only full blocks of a
    prompt are shared, so smaller blocks match more but cost more
    metadata. Pools only engage for attention nets (pos-0-anchored KV
    prefixes; recurrent h/c state has no position-addressed rows).

    ``tracer``: span flight recorder (`inference/trace.py`, default the
    process-wide one). Every request's lifecycle is recorded — queued /
    prefix_restore / prefill (per-chunk spans on the slot track) /
    decode / finish-or-cancel, plus slot occupancy, compile, and
    pool-eviction instants — as O(1) lock-free ring appends, cheap
    enough to stay on in production. `GET /trace` on the serving server
    and `DecodeHandle.timings()` read it back.

    ``mesh``: tensor-parallel device mesh (ISSUE 9). An int ``N > 1``
    builds a 1-D ``tp`` mesh over the first N local devices
    (`inference/sharding.py`); a `jax.sharding.Mesh` with a ``tp`` axis
    is used as-is. Attention heads and FFN hidden dims shard across the
    axis (Megatron pairing, output head replicated), the KV cache —
    contiguous stripes and paged ``k_pages``/``v_pages`` alike — shards
    on its Hkv head axis (``kv_pool_mb``/``prefix_cache_mb`` budgets
    become PER-DEVICE bytes: at fixed per-device HBM the pool holds
    ``tp×`` the blocks), and everything host-authoritative (block
    tables, ids, masks, ``pos``) replicates — so paged attention,
    prefix restore, COW, and preemption run unchanged per shard. The
    per-token program's only collectives are the two Megatron
    all-reduces per block (audited: `sharding.collective_counts`).
    Requires a transformer ComputationGraph whose every Hkv divides the
    axis size; otherwise tensor parallelism is DISABLED with a warning
    and the engine runs single-device. The engine never mutates
    ``net`` — it holds sharded param COPIES, so a live-trained net's
    updates stop reaching a sharded engine (rebuild to pick them up).

    ``speculate``: speculative decoding (ISSUE 10). ``G > 0`` drafts G
    tokens per decode-ready slot per iteration with a cheap draft model
    and verifies them in ONE multi-token target forward; acceptance
    samples each position from the TARGET distribution with the
    sequence's own RNG, so output is token-identical to ``G = 0`` by
    construction — only tokens/s changes (multiplicatively on
    high-acceptance traffic, mildly negative on adversarially random
    traffic). ``draft_blocks``: depth of the default SELF-speculative
    draft — the target's first K transformer blocks rewired into its
    own output head, params shared by reference (default: half the
    blocks). ``draft_net``: an explicit draft ComputationGraph instead
    (same vocab/head contract); required for models the shallow-exit
    surgery cannot cut (non-zoo graph shapes disable speculation with
    a RuntimeWarning).

    ``mask_rows``: device rows of the grammar mask table
    (`inference/logitproc.py`, ISSUE 14) — a fixed ``[mask_rows,
    vocab]`` additive table (row 0 reserved admit-all) that
    grammar-constrained requests' per-DFA-state token masks upload
    into once at admission; the masked decode/verify/draft program
    variants gather one row per slot and add it (0 allowed / -inf
    forbidden) to the output distribution. <= 1 disables the device
    table; grammars then mask host-side only (always correct — the
    exact allow row applies at sampling either way).

    ``kv_dtype``: ``"int8"`` quantizes the PAGED pool's page arrays
    (per-(position, head) max-abs scales stored alongside; quantize on
    write, dequantize on gather) — less than half the bytes per block,
    so a fixed ``kv_pool_mb`` holds 2x+ the blocks. Lossy: decode is
    plausible but not bit-identical to the f32 cache. Paged mode only.

    ``paged_kernel``: fused Pallas decode-kernel mode (ISSUE 15),
    paged layouts only. ``"auto"`` (default) lets the
    ops/pallas_kernels per-shape autotune pick the FlashDecoding-style
    page-walk kernel or the XLA gather per decode table bucket (silent
    XLA fallback when no kernel is registered — `pallas_kernels.
    enable()` arms it); ``"on"`` forces the kernel on every supported
    T=1 decode shape; ``"off"`` pins the XLA gather path. Either way
    prefill chunks, verify programs, and K/V writes stay in XLA, the
    decision is trace-time (no extra programs — decode stays <= 1
    program per table bucket), and outputs are token-identical by the
    seam contract. `paged_kernel_engaged` gauge + the ``paged_kernel``
    block of :meth:`debug_snapshot` report the per-bucket verdicts.

    ``transfer_guard``: device-residency audit mode. When set (e.g.
    "disallow"), every scheduler iteration runs under that thread-local
    ``jax.transfer_guard`` level: any *implicit* host<->device transfer in
    the hot loop raises, proving the loop only crosses the boundary at its
    declared points — `analysis.runtime.host_read` for the sampled-token
    readback, `device_index`/`jnp.asarray`-of-ndarray for the token feed.
    The tier-1 residency tests run the engine this way permanently.
    """

    def __init__(self, net, vocab_size: int, *, n_slots: int = 4,
                 max_queue: int = 64, prefill_chunk: int = 64,
                 prefix_cache_mb: float = 0.0, kv_block: int = 16,
                 kv_pool_mb: float = 0.0, kv_dtype: Optional[str] = None,
                 paged_kernel: str = "auto",
                 host_cache_mb: float = 0.0, disk_cache_mb: float = 0.0,
                 tier_dir: Optional[str] = None, tier_chunk_kib: int = 512,
                 mask_rows: int = 64,
                 mesh=None, speculate: int = 0,
                 draft_blocks: Optional[int] = None, draft_net=None,
                 metrics: Optional[MetricsRegistry] = None,
                 tracer: Optional[FlightRecorder] = None,
                 profiler: Optional[StepPhaseProfiler] = None,
                 profile: bool = True,
                 transfer_guard: Optional[str] = None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
        if paged_kernel not in ("auto", "on", "off"):
            raise ValueError(
                f"paged_kernel must be 'auto', 'on' or 'off', got "
                f"{paged_kernel!r}")
        self.net = net
        self.vocab_size = int(vocab_size)
        self.n_slots = int(n_slots)
        self.max_queue = int(max_queue)
        self.prefill_chunk = int(prefill_chunk)
        self.metrics = metrics if metrics is not None else default_registry()
        # span flight recorder (trace.py): every request's lifecycle is
        # recorded as spans/instants — O(1) lock-free ring appends, cheap
        # enough to default ON (the process-wide recorder). Tracks are
        # scoped per scheduler instance: a second scheduler sharing this
        # recorder must not interleave same-name spans on "scheduler"/
        # "slot N" tracks (the export pairs B/E LIFO per track)
        self.tracer = tracer if tracer is not None else default_recorder()
        # step-phase profiler + cost attribution (profiler.py, ISSUE 11):
        # per-iteration phase decomposition and the rolling FLOPs/MFU
        # window. Single-writer state written by the scheduler thread
        # only (the flight recorder's discipline); profile=False (or an
        # injected disabled profiler) reduces every stamp to one
        # attribute test — the bench-gated disarmed configuration
        self.profiler = profiler if profiler is not None else \
            StepPhaseProfiler(self.metrics, enabled=bool(profile))
        # serializes attribute_costs' seconds-long first computation:
        # two concurrent /debug/engine reads must not both trace the
        # whole program family (never touched by the scheduler thread)
        self._attr_lock = threading.Lock()
        self._attr_failed = False  # one-shot: a backend without a cost
        # model fails ONCE, not seconds of re-tracing per /debug poll
        sfx = self.tracer.track_scope("engine")
        self._sched_track = "scheduler" + sfx
        self._slot_tracks = [f"slot {i}{sfx}" for i in range(self.n_slots)]
        self._graph = hasattr(net.conf, "vertices")  # facade detection
        self._dtype = _compute_dtype_of(net.conf.conf)
        self._cache_cap = self._min_cache_len()
        # abstract shapes first (jax.eval_shape — no device allocation):
        # paged mode replaces the contiguous [n_slots, max_cache_len]
        # stripes with pool pages, and materializing stripes only to
        # throw them away would make startup peak HBM stripes + pool —
        # the exact cost the paged layout exists to eliminate
        abstract_states = jax.eval_shape(self._init_states)
        self._states = None  # materialized once the KV layout is known
        self._slots: List[Optional[_ActiveSeq]] = [None] * self.n_slots
        self._queue: List[_ActiveSeq] = []
        self._cond = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._transfer_guard = transfer_guard
        # -- fault-tolerance surface (inference/supervisor.py) --
        # heartbeat: stamped once per loop pass (idle passes included —
        # the idle wait wakes every 0.1s), so a watchdog distinguishes
        # "quiet" from "stuck" by staleness alone. Plain float store:
        # atomic under the GIL, torn-read-free.
        self.heartbeat = time.monotonic()
        self.iterations = 0  # loop passes completed (watchdog progress)
        # set (with the exception) when the loop dies instead of the old
        # behavior — a daemon thread evaporating and every in-flight
        # handle blocking out its full timeout in silence
        self.crashed: Optional[BaseException] = None
        # fence(): a supervisor that declared this engine dead sets the
        # fence BEFORE requeueing its in-flight work elsewhere; a hung
        # loop thread that later wakes sees the fence at its next
        # iteration boundary (and _consume guards it) and exits without
        # touching handles the replacement engine now owns
        self._fenced = False
        # supervisor crash hook: called (with the exception) from the
        # dying loop thread. When None, the engine self-cleans: every
        # in-flight/queued handle fails fast with EngineCrashedError
        self._on_crash = None
        # degradation ladder level >= 2 caps prefill chunks (the pow2
        # family already contains every smaller bucket — changing the
        # cap compiles nothing new)
        self.chunk_cap: Optional[int] = None
        if self.prefill_chunk > 1:
            lo = min(_MIN_CHUNK_BUCKET, self.prefill_chunk)
            self.prefill_buckets = [b for b in pow2_buckets(self.prefill_chunk)
                                    if b >= lo]
        else:
            self.prefill_buckets = []
        # dense chunk path needs every stateful layer to take a multi-token
        # inference step (true of the attention KV cache: offset
        # dynamic_update_slice writes + in-chunk causal mask). Recurrent
        # h/c state steps one token at a time, so those nets prefill
        # through the lax.scan chunk program instead.
        stateful = [impl for _, impl in self._impl_items()
                    if isinstance(impl, BaseRecurrentImpl)]
        self._chunk_dense = bool(stateful) and all(
            type(impl).__name__ == "SelfAttentionLayerImpl"
            for impl in stateful)
        attn_keys = [key for key, st in abstract_states.items()
                     if isinstance(st, dict) and "k" in st and "v" in st
                     and "pos" in st]
        # -- tensor-parallel mesh (inference/sharding.py, ISSUE 9) --
        # resolved BEFORE the KV layout: pool byte budgets are per-device
        # (each device holds Hkv/tp heads per block), and the pool must
        # know the shard factor to size capacity_blocks
        if isinstance(mesh, int):
            mesh = decode_mesh(mesh) if mesh > 1 else None
        self.mesh = None
        self.tp = 1
        self._repl = None  # replicated NamedSharding for host feeds
        if mesh is not None and mesh.shape.get(TP_AXIS, 1) <= 1:
            # a mesh without a real tp axis would be SILENTLY ignored
            # below — name the contract instead
            warnings.warn(
                f"mesh {dict(mesh.shape)} has no '{TP_AXIS}' axis of "
                "size > 1; tensor-parallel decode is DISABLED "
                "(build the mesh with inference.sharding.decode_mesh, "
                "or pass mesh=<device count>)",
                RuntimeWarning, stacklevel=2)
        if mesh is not None and mesh.shape.get(TP_AXIS, 1) > 1:
            tp = int(mesh.shape[TP_AXIS])
            if not (self._graph and self._chunk_dense
                    and kv_heads_shardable(abstract_states, attn_keys,
                                           tp)):
                warnings.warn(
                    f"mesh tp={tp} requested but tensor-parallel decode "
                    "is DISABLED (single-device engine instead): "
                    + ("the model is not a transformer ComputationGraph "
                       "with an attention KV cache to shard"
                       if not (self._graph and self._chunk_dense
                               and attn_keys)
                       else "an attention layer's n_kv_heads is not "
                            f"divisible by the tp axis size {tp} (the "
                            "head-sharded cache cannot split a head)"),
                    RuntimeWarning, stacklevel=2)
            else:
                self.mesh = mesh
                self.tp = tp
                from jax.sharding import NamedSharding, PartitionSpec
                self._repl = NamedSharding(mesh, PartitionSpec())
        self._sharded_params = self._sharded_variables = None
        if self.mesh is not None:
            # sharded COPIES — net keeps its own placement (a 1-device
            # reference engine over the same net stays single-device).
            # Unsharded engines read net.params LIVE at each dispatch
            # (the _params property), preserving the pre-mesh contract
            # that a retrained net's rebound params are picked up
            self._sharded_params, self._sharded_variables = \
                shard_decode_params(net, self.mesh)
        # KV memory layout (kvpool.py) — attention nets only: both modes
        # manage position-addressed K/V rows, which recurrent h/c state
        # does not have.
        #   kv_pool_mb > 0  -> PAGED: the pool IS the live decode cache
        #     (per-layer page arrays in self._states, per-slot block
        #     tables, zero-copy prefix restore/publish, preempt-and-swap)
        #   prefix_cache_mb -> contiguous slots + a side prefix pool
        #     restored by jitted block-gather (the ISSUE 4 layout, kept
        #     as the token-identity reference)
        self.kv_block = int(kv_block)
        self.kv_dtype: Optional[str] = None  # set when int8 KV engages
        # fused Pallas decode-kernel mode (ISSUE 15): injected into the
        # paged attention step as a trace-time constant next to the
        # block table; "auto" defers to the ops/pallas_kernels per-shape
        # autotune (silent XLA fallback when no kernel is registered)
        self.paged_kernel = paged_kernel
        self.pool: Optional[KVPool] = None
        self.paged = False
        self.restore_buckets: List[int] = []
        self.table_buckets: List[int] = []
        self._jrestore = None
        self._jpublish = None
        self._jsetpos = None
        self._jcow = None
        self._table: Optional[np.ndarray] = None
        if kv_pool_mb and kv_pool_mb > 0:
            if self._chunk_dense and attn_keys and self.kv_block >= 1:
                attn = {key: abstract_states[key] for key in attn_keys}
                pool = KVPool(attn, block=self.kv_block, paged=True,
                              budget_bytes=int(kv_pool_mb * (1 << 20)),
                              shard_factor=self.tp, cache_dtype=kv_dtype,
                              metrics=self.metrics, tracer=self.tracer)
                if pool.capacity_blocks > 0:
                    self.pool = pool
                    self.paged = True
                    # the contiguous [n_slots, max_cache_len] stripes are
                    # replaced by ONE pool-wide page array per layer
                    # (page 0 = scratch); a slot's reach is its block
                    # table, so capacity is pool bytes, not slots x cap
                    pages = pool.capacity_blocks + 1
                    # materialize straight into the paged layout: the
                    # contiguous stripes are never allocated. Zeros match
                    # init_state for every entry — paged requires
                    # _chunk_dense, so all stateful layers are attention.
                    # Under a mesh the page arrays stay HOST numpy here:
                    # the total pool is tp x one device's budget, so a
                    # device-side transient would OOM the very layout
                    # sharding exists to escape — the state_shardings
                    # device_put below ships each device ONLY its head
                    # slice (host zeros are calloc'd virtual pages, ~free)
                    zeros = (np.zeros if self.mesh is not None
                             else jnp.zeros)
                    self._states = {
                        key: jax.tree_util.tree_map(
                            lambda s: zeros(s.shape, s.dtype), st)
                        for key, st in abstract_states.items()
                        if key not in attn_keys}
                    self.kv_dtype = kv_dtype
                    for key in attn_keys:
                        st = abstract_states[key]
                        tail = st["k"].shape[2:]
                        if kv_dtype == "int8":
                            # quantized pages (int8 values + f32 per-row
                            # scales: attention quantizes on write and
                            # dequantizes on gather — halved-plus pool
                            # bytes per block, same paged step contract)
                            self._states[key] = {
                                "k_pages": zeros(
                                    (pages, self.kv_block) + tail,
                                    jnp.int8),
                                "v_pages": zeros(
                                    (pages, self.kv_block) + tail,
                                    jnp.int8),
                                "k_scales": zeros(
                                    (pages, self.kv_block) + tail[:-1],
                                    jnp.float32),
                                "v_scales": zeros(
                                    (pages, self.kv_block) + tail[:-1],
                                    jnp.float32),
                                "pos": zeros(st["pos"].shape,
                                             st["pos"].dtype),
                            }
                            continue
                        self._states[key] = {
                            "k_pages": zeros(
                                (pages, self.kv_block) + tail,
                                st["k"].dtype),
                            "v_pages": zeros(
                                (pages, self.kv_block) + tail,
                                st["v"].dtype),
                            "pos": zeros(st["pos"].shape,
                                         st["pos"].dtype),
                        }
                    self._cache_cap = pool.capacity_blocks * self.kv_block
                    self.table_buckets = pow2_buckets(pool.capacity_blocks)
                    self._table = np.full(
                        (self.n_slots, pool.capacity_blocks),
                        SCRATCH_BLOCK, np.int32)
            if not self.paged:
                warnings.warn(
                    f"kv_pool_mb={kv_pool_mb} requested but paged KV "
                    "decode is DISABLED (contiguous per-slot caches "
                    "instead): "
                    + ("the model has no attention KV cache to page"
                       if not self._chunk_dense or not attn_keys
                       else "the byte budget is smaller than two "
                            f"{self.kv_block}-position blocks"),
                    RuntimeWarning, stacklevel=2)
            elif prefix_cache_mb and prefix_cache_mb > 0:
                warnings.warn(
                    "prefix_cache_mb is ignored when kv_pool_mb is set: "
                    "the paged pool IS the prefix cache (finished "
                    "prompts' blocks are adopted by the trie in place, "
                    "zero-copy)", RuntimeWarning, stacklevel=2)
        if kv_dtype and not self.kv_dtype:
            warnings.warn(
                "kv_dtype='int8' requested but the paged KV pool did not "
                "engage (int8 KV quantization lives in the pool's page "
                "arrays); serving with the model-dtype cache instead",
                RuntimeWarning, stacklevel=2)
        # NOT elif: when kv_pool_mb was requested but paged could not
        # engage, a configured prefix_cache_mb must still buy the
        # contiguous side pool — silently dropping BOTH knobs would
        # leave the operator with no prefix cache and no warning
        if (not self.paged and prefix_cache_mb and prefix_cache_mb > 0
                and self._chunk_dense
                and self._cache_cap is not None
                and self.kv_block >= 1
                and self._cache_cap >= self.kv_block):
            attn = {key: abstract_states[key] for key in attn_keys}
            pool = KVPool(attn, block=self.kv_block,
                          budget_bytes=int(prefix_cache_mb * (1 << 20)),
                          shard_factor=self.tp,
                          metrics=self.metrics, tracer=self.tracer)
            if attn and pool.capacity_blocks > 0:
                self.pool = pool
                # one restore/publish program per pow2 block-chain bucket;
                # every bucket satisfies bucket*kv_block <= cache capacity,
                # so the fused row write always fits the slot's cache
                self.restore_buckets = pow2_buckets(
                    self._cache_cap // self.kv_block)
                self._jrestore = jax.jit(functools.partial(
                    gather_blocks, block=self.kv_block))
                # storage is donated: publish updates the pool in place
                # instead of re-materializing the whole budget's worth of
                # arrays per call; the caller rebinds pool.storage to the
                # result immediately, so the consumed buffers are never
                # touched again
                self._jpublish = jax.jit(functools.partial(
                    scatter_blocks, block=self.kv_block),
                    donate_argnums=(4,))
        if (not self.paged
                and prefix_cache_mb and prefix_cache_mb > 0
                and self.pool is None):
            # the knob was set but the pool could not engage — without
            # this the operator sees a phantom cache (banner/flags say
            # on, every prompt still pays full prefill, no prefix_*
            # instruments in /metrics)
            warnings.warn(
                f"prefix_cache_mb={prefix_cache_mb} requested but the "
                "prefix KV pool is DISABLED: "
                + ("the model has no attention KV cache to share"
                   if not self._chunk_dense or self._cache_cap is None
                   else f"kv_block={kv_block} exceeds "
                        f"max_cache_len={self._cache_cap}"
                   if self._cache_cap < max(self.kv_block, 1)
                   else "the byte budget is smaller than two "
                        f"{self.kv_block}-position blocks"),
                RuntimeWarning, stacklevel=2)
        if self._states is None:
            # contiguous layouts (and the LSTM fallback) materialize the
            # per-slot stripes the abstract pass only described
            self._states = self._init_states()
        if self.mesh is not None:
            # place the carried state on the mesh: K/V head-sharded,
            # everything else replicated. GSPMD propagates these
            # shardings through every program, so the carried output
            # stays head-sharded step over step — no resharding ever
            # (audited: sharding.collective_counts). The paged page
            # arrays arrive as HOST numpy (above), so each device
            # receives only its head slice — no single-device transient
            # of the tp-x-budget pool. Contiguous stripes (below) do
            # pass through device 0 first, but contiguous mode is by
            # definition single-chip-scale state
            self._states = jax.device_put(
                self._states, state_shardings(self._states, self.mesh))
            if self.pool is not None and self.pool.storage:
                # contiguous-mode side pool storage splits on the same
                # head axis, so restore's block gather never reshards
                self.pool.storage = jax.device_put(
                    self.pool.storage,
                    storage_shardings(self.pool.storage, self.mesh))
        self._jstep = jax.jit(
            self._step_paged_fn if self.paged else self._step_fn)
        # one prefill program per pow2 chunk bucket (the SAME jitted
        # callable; each distinct ids length C is its own XLA program,
        # compiled once and reused across requests — the batcher's
        # compile-once-per-bucket discipline applied to prefill). Paged
        # mode multiplies in the block-table width buckets: one program
        # per (chunk bucket, table bucket) pair, still a FIXED family.
        # n_real is data-dependent (real tokens in a padded chunk) and
        # MUST stay traced: static it would recompile per tail length,
        # defeating the bucket discipline.
        self._jprefill = jax.jit(
            self._prefill_paged_fn if self.paged
            else self._prefill_fn)  # graftlint: disable=JG004
        # slot admission zeroes one slot's rows in ONE fused program
        # (eagerly tree-mapped .at[].set(0) dispatched per leaf AND fed
        # the slot index as an implicit scalar transfer per leaf)
        self._jzero = jax.jit(self._zero_fn)
        if self.paged:
            # restore remaps the table host-side; the only device work is
            # setting the slot's pos past the hit (one tiny program) and
            # the occasional copy-on-write block duplication (one more)
            self._jsetpos = jax.jit(self._setpos_fn)
            self._jcow = jax.jit(self._cow_fn)
        # -- hierarchical KV tiering (ISSUE 19, kvtier.py) ------------------
        # opt-in (host_cache_mb=0 keeps the engine byte-identical to the
        # tierless build: no TierManager, no extra programs, no hot-path
        # work). When armed, pool evictions demote page rows to a host
        # ring (then disk) and trie hits promote them back; both device
        # programs are one fixed XLA program each (dynamic slice by a
        # traced block index), counted in the compile budget below.
        self.tier = None
        self._tier_chunk = int(tier_chunk_kib) << 10
        if host_cache_mb and host_cache_mb > 0:
            if not self.paged:
                warnings.warn(
                    f"host_cache_mb={host_cache_mb} requested but paged "
                    "KV decode is disabled — KV tiering needs the paged "
                    "pool and stays off", RuntimeWarning, stacklevel=2)
            else:
                from .kvtier import TierManager
                if disk_cache_mb and disk_cache_mb > 0 and not tier_dir:
                    import tempfile
                    tier_dir = tempfile.mkdtemp(prefix="kvtier-")
                self.tier = TierManager(
                    host_bytes=int(host_cache_mb * (1 << 20)),
                    disk_bytes=int(disk_cache_mb * (1 << 20)),
                    disk_dir=tier_dir,
                    chunk_bytes=self._tier_chunk,
                    metrics=self.metrics, tracer=self.tracer)
                self._jtier_spill = jax.jit(self._tier_spill_fn)
                self._jtier_restore = jax.jit(self._tier_restore_fn)
                self.pool.tier = self.tier
                self.tier.attach_engine(
                    self._tier_capture,
                    self.pool.bytes_per_block * self.pool.shard_factor,
                    self.kv_block)
        # -- grammar-constrained decoding (ISSUE 14, logitproc.py) ---------
        # a fixed [mask_rows, vocab] ADDITIVE device table (0 allowed,
        # -inf forbidden; row 0 reserved all-zeros = admit-all). Each
        # resident grammar's per-state rows upload ONCE at admission
        # (pow2-bucketed chunks — a fixed upload family, never per-token
        # work); the masked program variants gather one row per slot by
        # DFA state and add it to the output distribution, so the decode
        # family grows by at most one masked program per table bucket
        # and unconstrained traffic keeps dispatching the original
        # unmasked programs bit-for-bit.
        self.mask_rows = int(mask_rows)
        self.maskpool: Optional[MaskPool] = None
        self._masks = None
        self.mask_buckets: List[int] = []
        self._jstep_m = None
        self._jverify_m = None
        self._jdraft_step_m = None
        self._jmask_upload = None
        if self.mask_rows > 1:
            lo = min(8, self.mask_rows - 1)
            self.mask_buckets = [b for b in pow2_buckets(self.mask_rows - 1)
                                 if b >= lo]
            self.maskpool = MaskPool(self.mask_rows, self.mask_buckets)
            self._masks = self._dev_array(np.zeros(
                (self.mask_rows, self.vocab_size), np.dtype(self._dtype)))
            self._jstep_m = jax.jit(
                self._step_masked_paged_fn if self.paged
                else self._step_masked_fn)
            self._jmask_upload = jax.jit(self._mask_upload_fn)
        # -- speculative decoding (ISSUE 10 tentpole) ----------------------
        # a cheap draft proposes `speculate` tokens per decode-ready slot
        # per iteration; ONE multi-token verify program (the chunked-
        # prefill forward with every position's logits retained) scores
        # all gamma+1 positions, and `speculative.accept_tokens` keeps the
        # longest prefix the target's own sampling confirms — output is
        # token-identical to solo decode by construction. Rejected rows
        # roll back via pos (and paged block-table truncation); the draft
        # is a self-speculative shallow exit over the first `draft_blocks`
        # transformer blocks unless an explicit `draft_net` is passed.
        self.speculate = 0
        self.draft = None
        self.draft_blocks = 0
        self._draft_states = None
        self._draft_cap: Optional[int] = None
        self._sharded_draft_params = self._sharded_draft_variables = None
        self._jdraft_step = self._jdraft_prefill = None
        self._jdraft_zero = self._jverify = None
        self._jfixpos = self._jdraft_fixpos = None
        if speculate and int(speculate) > 0:
            reason = None
            if not (self._graph and self._chunk_dense and attn_keys):
                reason = ("the model is not a transformer "
                          "ComputationGraph with an attention KV cache "
                          "to verify against")
            elif not self.prefill_buckets:
                reason = ("chunked prefill is disabled (prefill_chunk "
                          "<= 1) and the draft needs its chunk programs")
            draft = draft_net
            kk = int(draft_blocks) if draft_blocks else \
                max(1, len(attn_keys) // 2)
            if reason is None and draft is None:
                # paged engines decode past the conf's max_cache_len
                # (capacity is pool bytes), but the draft's private
                # cache is DENSE per-slot stripes — sizing it to the
                # whole pool depth would cost n_slots x pool-depth
                # rows per draft layer, unbounded by any budget knob.
                # Cap it at the model's own max_cache_len: sequences
                # past that depth simply decode plain (_spec_ready's
                # draft-headroom check), they never break
                draft_depth = None
                if self.paged:
                    draft_depth = min(self._cache_cap,
                                      self._min_cache_len() or
                                      self._cache_cap)
                try:
                    draft = build_shallow_draft(
                        net, kk, max_cache_len=draft_depth)
                except ValueError as e:
                    reason = f"no self-speculative draft ({e})"
            if reason is not None:
                warnings.warn(
                    f"speculate={speculate} requested but speculative "
                    f"decoding is DISABLED: {reason}; pass draft_net= "
                    "for models the shallow-exit surgery cannot cut",
                    RuntimeWarning, stacklevel=2)
            else:
                self.speculate = int(speculate)
                self.draft = draft
                self.draft_blocks = kk if draft_net is None else 0
                caps = [int(getattr(impl.conf, "max_cache_len", 1024))
                        for _, impl in self._draft_impl_items()
                        if type(impl).__name__ == "SelfAttentionLayerImpl"]
                self._draft_cap = min(caps) if caps else None
                # the draft's private KV cache: contiguous per-slot
                # stripes even under a paged main cache (K layers only,
                # and its rows are always re-derivable — no pool
                # metadata, no sharing, no preemption bookkeeping)
                self._draft_states = self._init_draft_states()
                if self.mesh is not None:
                    # the draft joins the mesh: same Megatron specs (its
                    # conf is a prefix of the target's), same head-axis
                    # cache sharding — and the same collective audit
                    # (sharding.draft_program_hlo)
                    self._sharded_draft_params, \
                        self._sharded_draft_variables = \
                        shard_decode_params(draft, self.mesh)
                    self._draft_states = jax.device_put(
                        self._draft_states,
                        state_shardings(self._draft_states, self.mesh))
                self._jdraft_step = jax.jit(self._draft_step_fn)
                self._jdraft_prefill = jax.jit(self._draft_prefill_fn)  # graftlint: disable=JG004
                self._jdraft_zero = jax.jit(self._zero_fn)
                self._jverify = jax.jit(
                    self._verify_paged_fn if self.paged
                    else self._verify_fn)
                self._jfixpos = jax.jit(self._fixpos_fn)
                self._jdraft_fixpos = jax.jit(self._fixpos_fn)
                if self._masks is not None:
                    # masks compose with speculation: the draft proposes
                    # under the same mask the verify applies (per-round
                    # / per-position DFA states advanced host-side along
                    # the proposed chain), acceptance rule untouched
                    self._jverify_m = jax.jit(
                        self._verify_masked_paged_fn if self.paged
                        else self._verify_masked_fn)
                    self._jdraft_step_m = jax.jit(self._draft_step_masked_fn)
        self._prefill_next = 0  # round-robin over prefilling slots
        self._emitted_this_iter = 0  # scheduler-thread-only tally
        m = self.metrics
        if self.tp > 1:
            # mesh topology for /metrics, the serve banner, and the UI
            # /serving page (per-device pool bytes are kvpool.py gauges)
            m.gauge("decode_mesh_devices").set(self.tp)
        self._m_queue_depth = m.gauge("decode_queue_depth")
        self._m_active = m.gauge("decode_active_slots")
        self._m_occupancy = m.histogram("decode_slot_occupancy", lo=1.0,
                                        hi=float(self.n_slots) + 1,
                                        per_decade=12)
        self._m_tokens = m.counter("decode_tokens_total")
        self._m_seqs = m.counter("decode_sequences_total")
        self._m_rejected = m.counter("decode_rejected_total")
        self._m_cancelled = m.counter("decode_cancelled_total")
        self._m_latency = m.histogram("decode_seq_latency_sec")
        self._m_ttft = m.histogram("decode_time_to_first_token_sec")
        self._m_step_time = m.histogram("decode_step_time_sec")
        self._m_prefill_tokens = m.counter("prefill_tokens_total")
        # TTFT observability (ISSUE 14 satellite): the histogram SSE
        # clients and the load-test phase table read, recorded at the
        # same instant the request-track `first_token` trace instant is
        # stamped (exemplar = request id, so a slow bucket links
        # straight into /trace)
        self._m_first_token = m.histogram(
            "generate_first_token_seconds",
            help="submit -> first output token (TTFT), seconds")
        self._m_constrained = m.counter(
            "constrained_requests_total",
            help="requests submitted with a grammar constraint")
        if self.maskpool is not None:
            self._m_mask_rows = m.gauge(
                "grammar_mask_rows_resident",
                help="device mask-table rows held by resident grammars")
            self._m_mask_spill = m.counter(
                "grammar_mask_spills_total",
                help="grammar admissions that fell back to host-only "
                     "masking (mask table full or grammar too large)")
        self._m_prefill_chunk = m.histogram(
            "prefill_chunk_size", lo=1.0,
            hi=float(max(self.prefill_buckets or [1])) + 1, per_decade=12)
        if self.paged:
            # fused-decode-kernel observability (ISSUE 15): 1 when any
            # decode table bucket traced through the Pallas kernel
            # (refreshed at warmup and on every /debug/engine read)
            self._m_paged_kernel = m.gauge(
                "paged_kernel_engaged",
                help="fused Pallas paged-decode kernel engaged on at "
                     "least one decode table bucket")
            self._m_preempted = m.counter("decode_preempted_total")
            # best-of-n COW forks: candidates that attached to a fork
            # group's published prompt blocks (zero-copy remaps)
            self._m_forks = m.counter("decode_forks_total")
        if self.speculate:
            self._m_spec_proposed = m.counter("spec_tokens_proposed_total")
            self._m_spec_accepted = m.counter("spec_tokens_accepted_total")
            m.ratio("spec_acceptance_rate", self._m_spec_accepted,
                    self._m_spec_proposed)
        if self.pool is not None:
            self._m_prefix_lookups = m.counter("prefix_cache_lookups_total")
            self._m_prefix_hits = m.counter("prefix_cache_hits_total")
            self._m_prefix_lookup_tokens = m.counter(
                "prefix_cache_lookup_tokens_total")
            self._m_prefix_hit_tokens = m.counter(
                "prefix_cache_hit_tokens_total")
            m.ratio("prefix_cache_hit_rate", self._m_prefix_hit_tokens,
                    self._m_prefix_lookup_tokens)
        if self.tier is not None:
            self._m_tier_promoted = m.counter(
                "kv_tier_promoted_blocks_total",
                "tiered blocks adopted back into the HBM trie")
            self._m_tier_tokens = m.counter(
                "kv_tier_restored_tokens_total",
                "prompt tokens served from tier promotions instead of "
                "recompute (mid-prefill upgrades)")
        # compile-event tracing: the scheduler polls its own program
        # families' jit-cache sizes (the same CompileCounter budgets the
        # tests assert) once per iteration and stamps an instant event
        # whenever one grew — a chunk bucket's first-call compile shows
        # up ON the trace timeline, right where the stall happened
        self._compile_counter = CompileCounter.for_scheduler(self)
        self._compile_seen: Dict[str, int] = {}

    @property
    def _params(self):
        """Dispatch-time params: the sharded copies under a mesh, the
        net's LIVE tree otherwise (a rebound-after-fit() net keeps
        serving fresh weights — sharded engines must rebuild instead,
        as the class docstring documents)."""
        return self._sharded_params if self._sharded_params is not None \
            else self.net.params

    @property
    def _variables(self):
        return self._sharded_variables \
            if self._sharded_variables is not None else self.net.variables

    # -- host->device placement --------------------------------------------
    def _dev_array(self, a) -> jax.Array:
        """A host array as an EXPLICIT device transfer, placed the way
        the compiled programs expect it: committed-replicated on the
        mesh under tensor parallelism (argument placement is part of the
        jit cache key, so warmup and live dispatch MUST place
        identically or the budgets double), plain ``jnp.asarray``
        otherwise. `jax.device_put` of an ndarray is explicit under the
        transfer guard, same contract as `device_index`."""
        if self._repl is not None:
            # np.asarray of a HOST ndarray is a no-op normalization, not
            # a device sync; the device_put is the explicit transfer
            return jax.device_put(np.asarray(a), self._repl)  # graftlint: disable=JG006
        return jnp.asarray(a)

    def _dev_index(self, v: int) -> jax.Array:
        """`analysis.runtime.device_index` under the same mesh-placement
        contract as `_dev_array`."""
        if self._repl is not None:
            return jax.device_put(np.asarray([v], np.int32), self._repl)
        return device_index(v)

    # -- model plumbing ----------------------------------------------------
    def _impl_items(self):
        impls = self.net._impls
        return impls.items() if isinstance(impls, dict) else enumerate(impls)

    def _min_cache_len(self) -> Optional[int]:
        caps = []
        for _, impl in self._impl_items():
            if type(impl).__name__ == "SelfAttentionLayerImpl":
                caps.append(int(getattr(impl.conf, "max_cache_len", 1024)))
        return min(caps) if caps else None

    def _init_states(self) -> Dict[Any, Any]:
        """Private per-layer state with batch dim = n_slots; attention
        cache positions become [n_slots] vectors so each slot decodes at
        its own depth."""
        states = _materialize_rnn_states(self._impl_items(), {},
                                         self.n_slots, self._dtype)
        for key, st in states.items():
            if isinstance(st, dict) and "pos" in st and st["pos"].ndim == 0:
                states[key] = {**st,
                               "pos": jnp.zeros((self.n_slots,), jnp.int32)}
        return states

    def _forward(self, params, variables, x, states):
        """One forward of [B, T, vocab] one-hots through the net with
        explicit states: ([B, T, vocab] distributions, new states)."""
        if self._graph:
            acts, _, new_states = self.net._forward_impl(
                params, variables, [x], train=False, rng=None, states=states)
            out = acts[self.net.conf.network_outputs[0]]
        else:
            acts, _, new_states = self.net._forward_impl(
                params, variables, x, train=False, rng=None, states=states)
            out = acts[-1]
        return out, new_states

    def _freeze_states(self, new_states, old_states, live):
        """Keep only live slots' state transitions: masked rows (idle or
        mid-chunked-prefill slots stepped as padding of the shared batch)
        retain their previous recurrent state and cache position. K/V
        buffers are exempt — a masked slot's write lands at its own frozen
        `pos` row, which is overwritten by the slot's next real write (its
        next prefill chunk starts at `pos`) and causally invisible until
        then, so freezing the (large) cache buffers would be pure cost."""
        def sel(n, o):
            m = live.reshape((self.n_slots,) + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)
        out = {}
        for key, st in new_states.items():
            old = old_states[key]
            if isinstance(st, dict):
                # pages (and their int8 dequant scales) are exempt like
                # k/v: a masked slot's paged write was redirected to the
                # scratch page in-program (wmask), so there is nothing
                # to roll back
                out[key] = {k: (v if k in ("k", "v") + PAGE_KEYS
                                else sel(v, old[k]))
                            for k, v in st.items()}
            else:
                out[key] = sel(st, old)
        return out

    def _step_fn(self, params, variables, ids, live, states):
        """One single-token forward for all slots. ``ids``: [n_slots]
        int32 token ids (the one-hot is built HERE, on device — the host
        ships vocab-fold less data per step); ``live``: [n_slots] bool,
        False rows are batch padding whose state must not advance.
        Returns ([n_slots, vocab] next-token distributions, new states)."""
        x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)[:, None]
        out, new_states = self._forward(params, variables, x, states)
        return out[:, -1, :], self._freeze_states(new_states, states, live)

    def _inject_paged(self, states, table, wmask):
        """Hand the per-call block table (and write mask) to every paged
        attention state entry. The table is HOST-authoritative (the
        scheduler mutates it between steps) and shipped per dispatch —
        never part of the carried device state — so allocation, restore
        remaps, COW swaps, and preemption are plain numpy writes with no
        device program of their own.

        ``paged_kernel``/``mesh`` ride along as TRACE-TIME constants
        (this runs inside the jitted step body, so plain Python values
        in the state dict are static — the layer reads them to pick the
        fused decode kernel vs the XLA gather, ISSUE 15); like the
        table, the layer never returns them."""
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "k_pages" in st:
                out[key] = {**st, "table": table, "wmask": wmask,
                            "paged_kernel": self.paged_kernel,
                            "mesh": self.mesh}
            else:
                out[key] = st
        return out

    def _step_paged_fn(self, params, variables, ids, live, table, states):
        """Paged-mode decode step: `_step_fn` plus the block ``table``
        ([n_slots, nb], nb a pow2 bucket covering the deepest live slot).
        ``live`` doubles as the write mask — a masked (idle or
        mid-prefill) slot's K/V write is redirected to the scratch page
        inside the attention layer, so it can never corrupt a shared
        block at its own frontier (the contiguous-mode argument "the
        garbage row is overwritten by the slot's next real write" does
        not survive sharing). One XLA program per table bucket."""
        x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)[:, None]
        sts = self._inject_paged(states, table, live[:, None])
        out, new_states = self._forward(params, variables, x, sts)
        return out[:, -1, :], self._freeze_states(new_states, states, live)

    # -- grammar-mask programs (logitproc.py, ISSUE 14) --------------------
    def _mask_upload_fn(self, masks, start, rows):
        """Write one grammar's mask rows into the device table at
        ``start`` (1-element int32, same transfer contract as
        `_zero_fn`). ``rows`` is padded to a pow2 bucket; pad rows are
        zeros — admit-all rows inside the grammar's OWN allocation
        (MaskPool allocates bucket-sized chunks), never another
        grammar's. Admission-path only, one program per row bucket."""
        return jax.lax.dynamic_update_slice(masks, rows, (start[0], 0))

    def _step_masked_fn(self, params, variables, ids, live, mstate,
                        masks, states):
        """Decode step + grammar mask: gather each slot's current DFA
        state's ADDITIVE row (0 allowed / -inf forbidden) from the mask
        table and add it to the output distribution — one gather + add
        on top of the unchanged decode forward, so this family mirrors
        decode's bucketing exactly. Unconstrained slots point at row 0
        (all zeros): ``p + 0.0 == p`` bitwise, which is what makes an
        admit-everything grammar token-identical to unmasked decode."""
        out, new_states = self._step_fn(params, variables, ids, live,
                                        states)
        return out + jnp.take(masks, mstate, axis=0), new_states

    def _step_masked_paged_fn(self, params, variables, ids, live, table,
                              mstate, masks, states):
        out, new_states = self._step_paged_fn(params, variables, ids,
                                              live, table, states)
        return out + jnp.take(masks, mstate, axis=0), new_states

    def _verify_masked_fn(self, params, variables, ids, live, mstate2,
                          masks, states):
        """Masked multi-token verify: position j's row gets the mask of
        the DFA state the chain reaches after proposals[0..j-1]
        (``mstate2`` [n_slots, gamma+1], computed host-side while
        drafting) — the draft proposed under exactly these masks, so
        verify scores like with like and the acceptance rule (which
        re-applies the exact host-side allow row) is untouched."""
        out, new_states = self._verify_fn(params, variables, ids, live,
                                          states)
        return out + jnp.take(masks, mstate2, axis=0), new_states

    def _verify_masked_paged_fn(self, params, variables, ids, live,
                                table, mstate2, masks, states):
        out, new_states = self._verify_paged_fn(params, variables, ids,
                                                live, table, states)
        return out + jnp.take(masks, mstate2, axis=0), new_states

    def _draft_step_masked_fn(self, params, variables, ids, live, mstate,
                              masks, states):
        """Masked draft step: the lockstep proposal round under the SAME
        mask the verify applies — a draft that proposed out-of-grammar
        tokens would have its whole chain rejected every round, turning
        speculation into pure overhead on constrained traffic."""
        out, new_states = self._draft_step_fn(params, variables, ids,
                                              live, states)
        return out + jnp.take(masks, mstate, axis=0), new_states

    # -- chunked prefill programs ------------------------------------------
    def _slice_slot(self, states, slot):
        """One slot's rows of every state leaf, batch dim kept at 1.
        Paged page arrays pass through WHOLE by key (never sliced — they
        are pool-wide, and sniffing on ``shape[0] == n_slots`` could
        false-positive when the pool happens to hold n_slots+1 pages)."""
        def f(a):
            if hasattr(a, "ndim") and a.ndim >= 1 \
                    and a.shape[0] == self.n_slots:
                return jax.lax.dynamic_slice_in_dim(a, slot, 1, axis=0)
            return a
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "k_pages" in st:
                out[key] = {k: (v if k in PAGE_KEYS else f(v))
                            for k, v in st.items()}
            else:
                out[key] = jax.tree_util.tree_map(f, st)
        return out

    def _scatter_slot(self, states, sub, slot):
        """Write a batch-1 state pytree back into one slot's rows. Paged
        page arrays REPLACE the full-state ones (the batch-1 program
        updated the shared pages in place, there is no row to scatter)."""
        def f(full, part):
            if hasattr(full, "ndim") and full.ndim >= 1 \
                    and full.shape[0] == self.n_slots:
                return jax.lax.dynamic_update_slice_in_dim(
                    full, part, slot, axis=0)
            return part
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "k_pages" in st:
                out[key] = {k: (sub[key][k] if k in PAGE_KEYS
                                else f(v, sub[key][k]))
                            for k, v in st.items()}
            else:
                out[key] = jax.tree_util.tree_map(f, st, sub[key])
        return out

    def _prefill_fn(self, params, variables, slot, ids, n_real, states):
        """Prefill one chunk of ``ids`` (int32 [C], padded past ``n_real``)
        into ``slot``'s state, in ONE device dispatch. Returns the
        next-token distribution at the last REAL prompt token (only
        meaningful for the prompt's final chunk) and the updated shared
        states. Compiled once per chunk length C (the pow2 buckets).

        Dense path (attention nets): a single [1, C, vocab] forward —
        `nn/layers/attention.py` writes K/V rows [pos, pos+C) in one
        offset `dynamic_update_slice`, rotates RoPE at the slot's absolute
        positions, and masks causally within the chunk. Padded tail rows
        beyond n_real land at positions the corrected `pos` keeps causally
        invisible until the next real write overwrites them; `pos` itself
        advances by n_real, not C.

        Scan path (recurrent h/c state): C single-token steps fused into
        one `lax.scan` program; padded steps keep the carried state (the
        same mask-carry discipline the training scan uses).

        ``slot``/``n_real`` arrive as 1-element int32 arrays, not Python
        scalars: scalar feeds are *implicit* host->device transfers that
        the transfer-guard audit mode would reject every iteration."""
        slot = slot[0]
        n_real = n_real[0]
        sub = self._slice_slot(states, slot)
        if self._chunk_dense:
            x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)[None]
            out, new_sub = self._forward(params, variables, x, sub)
            probs = jax.lax.dynamic_index_in_dim(out, n_real - 1, axis=1,
                                                 keepdims=False)[0]
            fixed = {}
            for key, st in new_sub.items():
                if isinstance(st, dict) and "pos" in st:
                    # the layer advanced pos by the PADDED chunk length;
                    # the sequence is only n_real tokens deeper. But keep
                    # the layer's L_cap+1 overflow-freeze sentinel (ADVICE
                    # r3): a chunk that overran the cache must stay
                    # poisoned, not resume over a corrupted cache
                    pos = sub[key]["pos"] + n_real
                    if "k" in st:
                        cap = st["k"].shape[1]
                        pos = jnp.where(st["pos"] > cap, st["pos"], pos)
                    fixed[key] = {**st, "pos": pos}
                else:
                    fixed[key] = st
            new_sub = fixed
        else:
            keep = jnp.arange(ids.shape[0], dtype=jnp.int32) < n_real

            def body(carry, inp):
                tok, k = inp
                x = jax.nn.one_hot(tok[None, None], self.vocab_size,
                                   dtype=self._dtype)
                out, ns = self._forward(params, variables, x, carry)
                nxt = {}
                for key, st in ns.items():
                    old = carry[key]
                    if isinstance(st, dict):
                        nxt[key] = {k2: jnp.where(k, v2, old[k2])
                                    for k2, v2 in st.items()}
                    else:
                        nxt[key] = jnp.where(k, st, old)
                return nxt, out[0, -1, :]

            new_sub, probs_all = jax.lax.scan(body, sub, (ids, keep))
            probs = probs_all[n_real - 1]
        return probs, self._scatter_slot(states, new_sub, slot)

    def _prefill_paged_fn(self, params, variables, slot, ids, n_real,
                          table, states):
        """Paged-mode chunk prefill: `_prefill_fn`'s dense path with the
        chunk's K/V rows scattered into pool pages through the slot's
        block table instead of a contiguous stripe. Lanes past ``n_real``
        write to the scratch page (in-program mask from the traced
        n_real — so padding allocates no blocks), and the scheduler
        presents a table bucket covering the PADDED chunk end
        (``pos + bucket``) so the layer's overflow guard never fires on
        padding. One XLA program per (chunk bucket, table bucket)."""
        s = slot[0]
        nr = n_real[0]
        sub = self._slice_slot(states, s)
        trow = jax.lax.dynamic_slice_in_dim(table, s, 1, axis=0)  # [1, nb]
        wmask = (jnp.arange(ids.shape[0], dtype=jnp.int32) < nr)[None, :]
        sts = self._inject_paged(sub, trow, wmask)
        x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)[None]
        out, new_sub = self._forward(params, variables, x, sts)
        probs = jax.lax.dynamic_index_in_dim(out, nr - 1, axis=1,
                                             keepdims=False)[0]
        fixed = {}
        for key, st in new_sub.items():
            if isinstance(st, dict) and "k_pages" in st:
                # the layer advanced pos by the PADDED chunk length; the
                # sequence is only n_real tokens deeper (no overflow
                # sentinel to preserve — paged bucketing covers the
                # padded end by construction)
                fixed[key] = {**st, "pos": sub[key]["pos"] + nr}
            else:
                fixed[key] = st
        return probs, self._scatter_slot(states, fixed, s)

    # -- speculative decoding programs -------------------------------------
    def _draft_impl_items(self):
        impls = self.draft._impls
        return impls.items() if isinstance(impls, dict) else enumerate(impls)

    @property
    def _draft_params(self):
        """Draft dispatch params: sharded copies under a mesh, else the
        LIVE arrays by name — the shallow-exit draft shares the target's
        weights, so a rebound-after-fit() net keeps drafting fresh."""
        if self._sharded_draft_params is not None:
            return self._sharded_draft_params
        return {name: self.net.params.get(name, p)
                for name, p in self.draft.params.items()} \
            if self.draft_blocks else self.draft.params

    @property
    def _draft_variables(self):
        return self._sharded_draft_variables \
            if self._sharded_draft_variables is not None \
            else self.draft.variables

    def _init_draft_states(self) -> Dict[Any, Any]:
        """The draft net's private per-layer state (its own contiguous
        KV cache over the first K blocks), per-slot pos vectors like the
        main cache."""
        states = _materialize_rnn_states(self._draft_impl_items(), {},
                                         self.n_slots, self._dtype)
        for key, st in states.items():
            if isinstance(st, dict) and "pos" in st and st["pos"].ndim == 0:
                states[key] = {**st,
                               "pos": jnp.zeros((self.n_slots,), jnp.int32)}
        return states

    def _draft_forward(self, params, variables, x, states):
        """One forward through the DRAFT graph (shallow exit or explicit
        draft net) with explicit states — the draft-side `_forward`."""
        acts, _, new_states = self.draft._forward_impl(
            params, variables, [x], train=False, rng=None, states=states)
        return acts[self.draft.conf.network_outputs[0]], new_states

    def _draft_step_fn(self, params, variables, ids, live, states):
        """One single-token draft forward for all slots (the lockstep
        proposal round): `_step_fn` against the draft graph and its
        contiguous cache. One XLA program, mesh sizes included."""
        x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)[:, None]
        out, new_states = self._draft_forward(params, variables, x, states)
        return out[:, -1, :], self._freeze_states(new_states, states, live)

    def _draft_prefill_fn(self, params, variables, slot, ids, n_real,
                          states):
        """Chunked prefill into the draft cache: the dense path of
        `_prefill_fn` against the draft graph, one program per pow2
        chunk bucket. Runs piggybacked on every main prefill chunk (the
        draft must ingest the prompt to propose from it) and as the
        catch-up program after prefix restores/resumes jump the MAIN
        cache past tokens the draft never saw."""
        slot = slot[0]
        n_real = n_real[0]
        sub = self._slice_slot(states, slot)
        x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)[None]
        out, new_sub = self._draft_forward(params, variables, x, sub)
        probs = jax.lax.dynamic_index_in_dim(out, n_real - 1, axis=1,
                                             keepdims=False)[0]
        fixed = {}
        for key, st in new_sub.items():
            if isinstance(st, dict) and "pos" in st:
                pos = sub[key]["pos"] + n_real
                if "k" in st:
                    cap = st["k"].shape[1]
                    pos = jnp.where(st["pos"] > cap, st["pos"], pos)
                fixed[key] = {**st, "pos": pos}
            else:
                fixed[key] = st
        return probs, self._scatter_slot(states, fixed, slot)

    def _verify_fn(self, params, variables, ids, live, states):
        """THE multi-token verify program: one target-model forward over
        ``ids`` [n_slots, gamma+1] chains, per-slot positions, retaining
        EVERY position's next-token distribution ([n_slots, gamma+1,
        vocab]) — the chunked-prefill machinery pointed at decode.
        Chain rows are written into the cache at [pos, pos+gamma+1);
        rejected rows are rolled back host-side by `_fixpos_fn` (they
        sit beyond the corrected pos, causally invisible and overwritten
        by the next real write — the same invariant slot reuse rests
        on). Masked slots are frozen exactly like the decode step."""
        x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)
        out, new_states = self._forward(params, variables, x, states)
        return out, self._freeze_states(new_states, states, live)

    def _verify_paged_fn(self, params, variables, ids, live, table,
                         states):
        """Paged verify: `_verify_fn` writing through the block table.
        ``live`` doubles as the write mask (broadcast over the chain
        lanes) — a masked slot's rows redirect to the scratch page. The
        scheduler pre-allocates blocks covering pos+gamma+1 and
        truncates the table back after acceptance."""
        x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)
        sts = self._inject_paged(states, table, live[:, None])
        out, new_states = self._forward(params, variables, x, sts)
        return out, self._freeze_states(new_states, states, live)

    def _fixpos_fn(self, states, posv, mask):
        """Post-verify rollback: set every attention layer's cache
        position to ``posv`` [n_slots] where ``mask`` is True (the slots
        that speculated this iteration), freeze the rest. The verify
        program advanced pos by the full padded chain; acceptance is
        decided host-side, so the correction is a separate (tiny, single)
        program — the rejected tail rows become causally invisible the
        moment pos steps back over them."""
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "pos" in st \
                    and ("k" in st or "k_pages" in st):
                out[key] = {**st, "pos": jnp.where(mask, posv, st["pos"])}
            else:
                out[key] = st
        return out

    def _pick_chunk(self, seq: _ActiveSeq) -> Tuple[int, int]:
        """(bucket, n_real) for this sequence's next prefill chunk, or
        (0, 0) when no bucket fits the KV-cache headroom (the tail then
        prefills token-by-token through the decode step)."""
        remaining = len(seq.prompt) - seq.fed
        cap = self.prefill_chunk
        if self.chunk_cap:
            # degradation ladder (supervisor level >= 2): smaller chunks
            # shorten each iteration's device hold, trading TTFT for
            # decode tail latency under pressure. Smaller buckets are
            # already in the compiled family — no new programs.
            cap = max(1, min(cap, int(self.chunk_cap)))
        n_real = min(remaining, cap)
        bucket = bucket_for(n_real, self.prefill_buckets)
        if self._cache_cap is not None and \
                seq.fed + bucket > self._cache_cap:
            # padded writes past the cap would trip the layer's overflow
            # guard even though the real tokens fit: shrink to the largest
            # bucket inside the headroom
            fitting = [b for b in self.prefill_buckets
                       if seq.fed + b <= self._cache_cap]
            if not fitting:
                return 0, 0
            bucket = fitting[-1]
            n_real = min(n_real, bucket)
        return bucket, n_real

    def _zero_fn(self, states, slot):
        """Zero one slot's rows across every state leaf (KV rows, cache
        position, LSTM h/c) so an admitted sequence starts clean. Jitted:
        one fused device program per admission instead of one eager
        dispatch per leaf, and no implicit scalar transfers (``slot`` is
        a 1-element int32 array, same contract as `_prefill_fn`). Paged
        page arrays are never touched — they are SHARED storage (another
        slot's blocks live there); a fresh slot starts clean because its
        table is reset to scratch host-side and its ``pos`` row to 0."""
        s = slot[0]

        def zero_row(a):
            if hasattr(a, "ndim") and a.ndim >= 1 and \
                    a.shape[0] == self.n_slots:
                return a.at[s].set(0)
            return a
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "k_pages" in st:
                out[key] = {k: (v if k in PAGE_KEYS else zero_row(v))
                            for k, v in st.items()}
            else:
                out[key] = jax.tree_util.tree_map(zero_row, st)
        return out

    def _setpos_fn(self, states, slot, val):
        """Set one slot's attention cache position (paged prefix restore:
        the remap is host-side table surgery; the only device-visible
        effect is ``pos`` jumping past the hit). 1-element int32 array
        args, same transfer contract as `_zero_fn`."""
        s = slot[0]
        v = val[0]
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "k_pages" in st:
                out[key] = {**st, "pos": st["pos"].at[s].set(v)}
            else:
                out[key] = st
        return out

    def _cow_fn(self, states, src, dst):
        """Copy-on-write block duplication: copy page ``src`` into the
        freshly-allocated page ``dst`` across every layer's K/V pages.
        Dispatched host-side BEFORE a write that would land in a shared
        (trie-owned) block; the writer's table then points at ``dst``."""
        s = src[0]
        d = dst[0]
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "k_pages" in st:
                # scale pages (int8 KV mode) duplicate with their values
                out[key] = {
                    k: (v.at[d].set(v[s]) if k in PAGE_KEYS else v)
                    for k, v in st.items()
                }
            else:
                out[key] = st
        return out

    def _tier_spill_fn(self, states, bid):
        """Slice one page row (K/V pages + int8 scale rows) out of every
        layer's pool arrays — the device side of a tier demotion. The
        block index stays TRACED (dynamic slice), so the whole tier
        ladder costs exactly one XLA program regardless of which block
        spills; the result is an immutable functional snapshot, safe
        against immediate reuse of the freed page."""
        b = bid[0]
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "k_pages" in st:
                out[key] = {
                    pk: jax.lax.dynamic_index_in_dim(
                        st[pk], b, axis=0, keepdims=False)
                    for pk in PAGE_KEYS if pk in st}
        return out

    def _tier_restore_fn(self, states, bid, rows):
        """Write one promoted page row back into the pool arrays (the
        device side of a tier promotion) — the `_tier_spill_fn` slice in
        reverse, again one program for every block index."""
        b = bid[0]
        out = {}
        for key, st in states.items():
            if isinstance(st, dict) and "k_pages" in st and key in rows:
                st2 = dict(st)
                for pk, row in rows[key].items():
                    st2[pk] = jax.lax.dynamic_update_index_in_dim(
                        st[pk], row.astype(st[pk].dtype), b, axis=0)
                out[key] = st2
            else:
                out[key] = st
        return out

    def _tier_capture(self, bid: int):
        """TierManager capture hook (scheduler thread, from the pool's
        `_evict_lru`): dispatch the spill slice and hand the device
        snapshot to the tier worker — the actual device->host read
        happens on the worker thread under the pacing budget, never
        here."""
        return self._jtier_spill(self._states, self._dev_index(bid))

    def _reset_slot_state(self, slot: int) -> None:
        # _states is single-writer by protocol: only the scheduler thread
        # mutates it once start() returns. warmup() — the one cross-thread
        # reader — runs exclusively inside supervisor-owned windows
        # (construction / recovery / drain-swap) while this engine's loop
        # is idle-by-construction (no slot admitted yet), and stop()'s
        # sweep runs after the join. CC005 cannot see that protocol.
        self._states = self._jzero(self._states, self._dev_index(slot))  # graftlint: disable=CC005
        if self.speculate:
            # the draft cache is slot-aligned with the main cache: a
            # reused slot starts the draft at row 0 too
            self._draft_states = self._jdraft_zero(  # graftlint: disable=CC005
                self._draft_states, self._dev_index(slot))

    # -- prefix KV reuse (kvpool.py) ---------------------------------------
    def _try_restore(self, slot: int, seq: _ActiveSeq) -> None:
        """Walk the prefix trie for the admitted prompt and restore the
        longest cached block chain into the freshly-zeroed slot, advancing
        ``seq.fed``/``pos`` past the hit so chunked prefill only runs the
        cold suffix. The hit is capped one token short of the prompt: the
        LAST prompt token must always run through the model to produce
        the first output token's distribution."""
        B = self.pool.block
        max_hit = (len(seq.prompt) - 1) // B
        self._m_prefix_lookups.inc()
        self._m_prefix_lookup_tokens.inc(len(seq.prompt))
        if max_hit < 1:
            return
        n_blk, ids, node = self.pool.match(seq.prompt, max_hit)
        seq.pool_node = node  # holds one reference until the slot frees
        if node is not None:
            ledger_note("trie_pin", seq.handle.request_id, +1)
        if not n_blk:
            return
        bucket = bucket_for(n_blk, self.restore_buckets)
        idx = np.full((bucket,), SCRATCH_BLOCK, np.int32)
        idx[:n_blk] = ids
        self._states = self._jrestore(
            self._states, self._dev_index(slot), self._dev_array(idx),
            self._dev_index(n_blk), self.pool.storage)
        seq.fed = n_blk * B
        seq.written = seq.fed  # host pos mirror (speculation's fixpos)
        self._m_prefix_hits.inc()
        self._m_prefix_hit_tokens.inc(seq.fed)

    def _release_pool(self, seq: _ActiveSeq) -> None:
        """Drop the sequence's prefix-trie reference (every slot-freeing
        path — finish, cancel, stop — must come through here, or the
        matched blocks stay pinned against eviction forever)."""
        if seq.pool_node is not None:
            self.pool.release(seq.pool_node)
            seq.pool_node = None
            ledger_note("trie_pin", seq.handle.request_id, -1)

    def _publish_prompt(self, slot: int, seq: _ActiveSeq) -> None:
        """Index a finished sequence's prompt: insert its full blocks into
        the trie (allocating pool blocks, LRU-evicting unreferenced ones
        when full) and copy the slot's prefill-written cache rows into the
        new storage rows. The missing part is always a contiguous suffix
        of the prompt's block chain, covered by a greedy descending walk
        over the pow2 buckets — so publish compiles the same bounded
        program family as restore."""
        B = self.pool.block
        n_full = len(seq.prompt) // B
        if n_full < 1:
            return
        # the pool is scheduler-thread-only past start() (same protocol
        # as _states above; stop() touches it only after the join)
        start, new_ids = self.pool.insert(seq.prompt[:n_full * B])  # graftlint: disable=CC005
        off = 0
        while off < len(new_ids):
            b = max(k for k in self.restore_buckets
                    if k <= len(new_ids) - off)
            idx = np.zeros((b,), np.int32)
            idx[:] = new_ids[off:off + b]
            self.pool.storage = self._jpublish(
                self._states, self._dev_index(slot),
                self._dev_index(start + off), self._dev_array(idx),
                self.pool.storage)
            off += b

    # -- paged mode: block tables, lazy alloc, COW, preempt-and-swap -------
    def _blocks_for(self, positions: int) -> int:
        return -(-positions // self.kv_block)

    def _table_for(self, max_pos: int) -> np.ndarray:
        """The host table sliced to the pow2 bucket covering ``max_pos``
        positions — the per-step program shape. Shallow workloads gather
        (and attend over) only their own depth, not the whole pool."""
        nb = bucket_for(max(1, self._blocks_for(max_pos)),
                        self.table_buckets)
        return self._table[:, :nb]

    def _alloc_or_preempt(self, slot: int,
                          seq: _ActiveSeq) -> Optional[int]:
        """Claim one pool block under the preempt-and-swap policy: when
        allocation fails even after LRU-evicting unreferenced cached
        blocks, the LATEST-submitted live slot is preempted and the claim
        retried. None means ``seq`` itself was the victim (it is already
        requeued — the caller must skip its dispatch). The single home
        of the pool-pressure policy, shared by lazy growth and COW."""
        while True:
            bid = self.pool.alloc()
            if bid is not None:
                ledger_note("pool_block", seq.handle.request_id, +1)
                return bid
            victim = self._pick_victim()
            if victim is None or victim[1] is seq:
                self._preempt(slot, seq)
                return None
            self._preempt(*victim)

    def _ensure_blocks(self, slot: int, seq: _ActiveSeq,
                       upto_pos: int) -> bool:
        """Grow ``slot``'s block table to cover positions [0, upto_pos)
        — the lazy allocation of the paged layout: a block is claimed
        only when ``pos`` is about to cross into it. False means ``seq``
        was preempted by its own allocation (see _alloc_or_preempt)."""
        need = self._blocks_for(upto_pos)
        added = 0
        while len(seq.block_ids) < need:
            bid = self._alloc_or_preempt(slot, seq)
            if bid is None:
                return False
            j = len(seq.block_ids)
            seq.block_ids.append(bid)
            seq.shared.append(False)
            # host block table: scheduler-thread-only past start(), like
            # _states/pool above (stop() frees rows only after the join)
            self._table[slot, j] = bid  # graftlint: disable=CC005
            added += 1
        if added and self.tracer.enabled:
            self.tracer.instant(
                "block_alloc", track=self._slot_tracks[slot],
                args={"request": seq.handle.request_id, "blocks": added,
                      "free": self.pool.free_blocks})
        return True

    def _ensure_writable(self, slot: int, seq: _ActiveSeq,
                         pos: int) -> bool:
        """Copy-on-write before the first write into a SHARED block: a
        restored (trie-owned) block the slot is about to write — the
        one-token refeed when a prefix hit covers the whole prompt —
        is duplicated into a fresh page and the table repointed, so the
        cached original stays bit-intact for its other readers. Only the
        first block of a write span can be shared (everything past the
        restore frontier was freshly allocated)."""
        j = pos // self.kv_block
        if j >= len(seq.block_ids) or not seq.shared[j]:
            return True
        bid = self._alloc_or_preempt(slot, seq)
        if bid is None:
            # self-preempted for the COW page: when every page backs
            # this prompt's own (pinned) prefix, no amount of retrying
            # can produce the duplicate — the resume must restore one
            # block short instead
            seq.cow_starved = True
            return False
        src = seq.block_ids[j]
        self._states = self._jcow(self._states, self._dev_index(src),
                                  self._dev_index(bid))
        seq.block_ids[j] = bid
        seq.shared[j] = False
        self._table[slot, j] = bid
        if self.tracer.enabled:
            self.tracer.instant(
                "block_cow", track=self._slot_tracks[slot],
                args={"request": seq.handle.request_id, "src": src,
                      "dst": bid, "block_index": j})
        return True

    def _pick_victim(self) -> Optional[Tuple[int, _ActiveSeq]]:
        """Preemption victim: the latest-SUBMITTED live slot (LIFO — the
        earliest request keeps its progress, vLLM's policy). Keyed on
        t_submit, not t_admitted: re-admission re-stamps t_admitted, so
        an admitted-time key would make a just-resumed old request the
        preferred victim again and thrash its re-prefill. May be the
        requester itself when it is the youngest."""
        cands = [(s.handle.t_submit, i, s)
                 for i, s in enumerate(self._slots) if s is not None]
        if not cands:
            return None
        _, i, s = max(cands)
        return i, s

    def _preempt(self, slot: int, seq: _ActiveSeq) -> None:
        """Swap a sequence out under pool pressure: release its owned
        blocks and trie pin (KV is dropped, not spilled — recompute is a
        prefill, which chunking makes cheap), fold the tokens generated
        so far into its prompt, and requeue it at the FRONT. On
        re-admission the re-prefill recomputes the same K/V and the
        final chunk's distribution yields exactly the token the
        interrupted decode would have produced next — the sequence's
        host-side RNG is untouched, so resumed output is token-identical
        to an unpreempted run."""
        self._m_preempted.inc()
        h = seq.handle
        tr = self.tracer
        if tr.enabled:
            if seq.phase == "prefill":
                tr.end("prefill", req=h.request_id,
                       args={"fed_tokens": seq.fed})
            elif seq.phase == "decode":
                tr.end("decode", req=h.request_id,
                       args={"tokens": len(h.tokens), "preempted": True})
            tr.instant("preempt", track=self._slot_tracks[slot],
                       args={"request": h.request_id,
                             "blocks_released": sum(
                                 1 for sh in seq.shared if not sh),
                             "tokens_done": len(h.tokens)})
            # the swap gap on the request track: everything between
            # "preempt" and the matching "resume" is time the request
            # spent swapped out waiting for pool blocks
            tr.begin("preempted", req=h.request_id)
        self._release_pool(seq)
        self._release_slot_blocks(slot, seq)
        self._release_mask(seq)  # re-acquired (usually cached) on resume
        seq.prompt.extend(int(t) for t in h.tokens[seq.folded:])
        seq.folded = len(h.tokens)
        seq.fed = 0
        seq.written = 0
        seq.draft_fed = 0  # the draft cache re-ingests on resume too
        seq.phase = "preempted"
        seq.resumed = True
        # single-writer: _slots is mutated only on this scheduler thread
        # (same discipline as _step_once); _cond guards only the queue.
        # Cross-thread readers (inflight(), stop()'s post-join sweep)
        # read the list reference GIL-atomically and tolerate a one-
        # entry-stale view — CC005 cannot see the single-writer protocol
        self._slots[slot] = None  # graftlint: disable=CC004,CC005
        ledger_note("engine_slot", h.request_id, -1)
        with self._cond:
            self._queue.insert(0, seq)
            self._m_queue_depth.set(len(self._queue))
        self._m_active.set(sum(s is not None for s in self._slots))

    def _release_slot_blocks(self, slot: int, seq: _ActiveSeq,
                             keep: frozenset = frozenset()) -> None:
        """Return a slot's OWNED blocks to the pool (shared entries are
        trie-owned — releasing the trie pin is `_release_pool`'s job)
        and reset its table row to scratch. ``keep``: ids adopted by the
        trie at publish (ownership already transferred)."""
        freed = 0
        for bid, sh in zip(seq.block_ids, seq.shared):
            if not sh and bid not in keep:
                self.pool.free_block(bid)
                freed += 1
        if freed:
            ledger_note("pool_block", seq.handle.request_id, -freed)
        seq.block_ids = []
        seq.shared = []
        self._table[slot, :] = SCRATCH_BLOCK

    def _try_restore_paged(self, slot: int, seq: _ActiveSeq) -> None:
        """Paged prefix restore = block-table remap: point the slot's
        table at the cached blocks (refcounted via the trie pin) and set
        ``pos`` past the hit. ZERO K/V copies — the pages are referenced
        where they lie; the only device work is the one-row pos write.
        The hit may cover the WHOLE prompt (full blocks): the last
        prompt token is then re-fed to produce the first output
        distribution, and its write copy-on-writes the final shared
        block (`_ensure_writable`)."""
        B = self.pool.block
        self._m_prefix_lookups.inc()
        self._m_prefix_lookup_tokens.inc(len(seq.prompt))
        max_hit = len(seq.prompt) // B
        if seq.cow_starved:
            # the previous attempt's full hit left no page for the
            # refeed's COW duplicate: leave the tail block unpinned (it
            # becomes evictable, freeing the page the re-prefill needs).
            # One-shot — a later ordinary preempt/resume gets the full
            # hit again; if the trap recurs the flag is simply re-set
            max_hit -= 1
            seq.cow_starved = False
        if max_hit < 1:
            return
        n_blk, ids, node = self.pool.match(seq.prompt, max_hit)
        seq.pool_node = node  # holds one reference until the slot frees
        if node is not None:
            ledger_note("trie_pin", seq.handle.request_id, +1)
        if self.tier is not None:
            # tier directory lookup past the resident frontier: queue
            # host/disk blocks for background promotion. The slot does
            # NOT wait — it prefills its cold suffix as usual, and a
            # landed promotion upgrades it mid-prefill (_tier_tick)
            frontier = node.hash if node is not None else ""
            if frontier is not None:
                ext = self.tier.lookup_extension(
                    frontier, seq.prompt, n_blk, max_hit)
                if ext:
                    self.tier.request_restore(ext)
        if not n_blk:
            return
        seq.block_ids = [int(b) for b in ids]
        seq.shared = [True] * n_blk
        self._table[slot, :n_blk] = ids
        fed = min(n_blk * B, len(seq.prompt) - 1)
        self._states = self._jsetpos(self._states,
                                     self._dev_index(slot),
                                     self._dev_index(fed))
        seq.fed = fed
        seq.written = fed
        self._m_prefix_hits.inc()
        self._m_prefix_hit_tokens.inc(fed)
        if seq.fork is not None \
                and seq.fork.primary_handle is not seq.handle \
                and not seq.resumed:
            # a best-of-n FOLLOWER attached to its group's published
            # prompt blocks: the COW fork proper (n candidates, one
            # prompt's worth of KV). The primary's own trie hit and
            # preempt-resume re-restores are ordinary prefix hits, not
            # forks — counting them would inflate the metric past n-1
            self._m_forks.inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "fork", track=self._slot_tracks[slot],
                    args={"request": seq.handle.request_id,
                          "role": "attach", "blocks": n_blk})

    def _publish_paged(self, slot: int, seq: _ActiveSeq) -> frozenset:
        """Zero-copy publish: the finished sequence's full prompt blocks
        are ADOPTED by the trie in place (ownership transfer — the pages
        already hold the prefill-written K/V). Returns the transferred
        ids so the slot release does not free them. Blocks the trie
        already indexes (the restored prefix, or a COW'd duplicate of
        one) are skipped and freed normally."""
        B = self.pool.block
        n_full = len(seq.prompt) // B
        if n_full < 1 or n_full > len(seq.block_ids):
            return frozenset()
        adopted = frozenset(self.pool.adopt(
            seq.prompt[:n_full * B], seq.block_ids[:n_full]))
        if adopted:
            # ownership transfer: the trie owns these pages now — the
            # request's debt is settled without a free_block
            ledger_note("pool_block", seq.handle.request_id,
                        -len(adopted))
        return adopted

    # -- grammar mask residency (logitproc.MaskPool) -----------------------
    def _attach_mask(self, slot: int, seq: _ActiveSeq) -> None:
        """Make an admitted request's grammar device-resident: acquire
        (or ref) its mask-row range and upload the additive table on
        first residency — at ADMISSION, off the per-token path, so
        constrained decode steps pay only the in-program gather. A
        grammar that cannot fit falls back to HOST-ONLY masking
        (mask_base None): the exact allow row still applies at sampling
        — correctness never depends on residency, only the device-side
        assist (and the draft's in-grammar proposals) does."""
        proc = seq.proc
        if proc is None or proc.grammar is None or self.maskpool is None:
            return
        g = proc.grammar
        start, upload = self.maskpool.acquire(g)
        if start is None:
            proc.mask_base = None
            self._m_mask_spill.inc()
            return
        if upload:
            bucket = bucket_for(g.n_states, self.mask_buckets)
            rows = np.zeros((bucket, self.vocab_size),
                            np.dtype(self._dtype))
            rows[:g.n_states] = g.mask_table(np.dtype(self._dtype))
            # _masks is scheduler-thread-only past start() (attach runs
            # in _admit), same single-writer protocol as _states
            self._masks = self._jmask_upload(  # graftlint: disable=CC005
                self._masks, self._dev_index(start),
                self._dev_array(rows))
        proc.mask_base = start
        ledger_note("mask_row", seq.handle.request_id, +1)
        self._m_mask_rows.set(self.maskpool.resident_rows())
        if self.tracer.enabled:
            self.tracer.instant(
                "grammar_attach", track=self._slot_tracks[slot],
                args={"request": seq.handle.request_id,
                      "states": g.n_states, "row": start,
                      "uploaded": bool(upload)})

    def _release_mask(self, seq: _ActiveSeq) -> None:
        """Drop the request's mask-row reference (every slot-freeing
        path — finish, cancel, preempt, stop, crash — comes through
        here; the rows stay CACHED for the next request sharing the
        grammar until pool pressure evicts zero-ref entries)."""
        proc = seq.proc
        if proc is not None and proc.mask_base is not None:
            self.maskpool.release(proc.grammar.key)
            proc.mask_base = None
            ledger_note("mask_row", seq.handle.request_id, -1)
            self._m_mask_rows.set(self.maskpool.resident_rows())

    # -- client side -------------------------------------------------------
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int, *,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None, seed: int = 0,
               eos_id: Optional[int] = None,
               request_id: Optional[str] = None, priority: int = 0,
               stop: Optional[Sequence[Sequence[int]]] = None,
               grammar: Optional[CompiledGrammar] = None,
               repetition_penalty: Optional[float] = None,
               presence_penalty: Optional[float] = None,
               frequency_penalty: Optional[float] = None,
               stream=None,
               fork: Optional[ForkGroup] = None,
               _handle: Optional[DecodeHandle] = None,
               _front: bool = False) -> DecodeHandle:
        """``stop``: multi-token stop sequences (list of token-id lists)
        matched across token boundaries; a match truncates the output
        before the stop sequence and finishes the request
        (``finish_reason="stop"``). ``grammar``: a pre-compiled
        `logitproc.CompiledGrammar` (compiled AHEAD of admission — the
        serving layer caches compiles by content); forbidden tokens get
        probability exactly 0 and the grammar's device mask rows attach
        at admission. ``repetition_penalty`` / ``presence_penalty`` /
        ``frequency_penalty``: host-side probability-row penalties over
        generated-token counts. ``stream``: a `logitproc.TokenStream`
        the scheduler pushes released tokens into as they decode (the
        SSE backing; crash-recovery re-decodes dedupe by token index).

        ``priority``: degradation-ladder shedding order (higher
        survives longer; default 0). ``fork``: best-of-n candidate
        group (`speculative.ForkGroup`, see :meth:`generate_many`) —
        the first submission becomes the primary; follower candidates
        stay queued until the primary's prefill publishes the prompt's
        paged blocks, then restore them copy-on-write. ``_handle``/
        ``_front``: the supervisor's crash-recovery resubmission path —
        reuse the ORIGINAL (reset) handle so the caller blocked in
        ``result()`` never notices the restart, and front-queue
        recovered work so it does not wait behind requests submitted
        after the crash."""
        rid = _handle.request_id if _handle is not None \
            else (request_id or new_request_id())
        if not len(prompt_ids):
            raise ValueError("prompt_ids must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bad = [int(t) for t in prompt_ids
               if not 0 <= int(t) < self.vocab_size]
        if bad:
            # ids arrive from untrusted JSON (/generate); out-of-range ids
            # would one-hot to silent all-zero rows, decoding confidently
            # from a "no token" input
            raise ValueError(
                f"prompt ids out of range [0, {self.vocab_size}): "
                f"{bad[:5]}")
        needed = len(prompt_ids) + max(max_new_tokens - 1, 0)
        if self.paged:
            # pool-bytes admission: a prompt is rejected only when it
            # cannot fit the WHOLE pool — there is no per-slot stripe to
            # outgrow, so "too long" means more blocks than exist
            blocks_needed = self._blocks_for(needed)
            if blocks_needed > self.pool.capacity_blocks:
                self._m_rejected.inc()
                self.tracer.instant("reject", req=rid, args={
                    "request_id": rid, "reason": "prompt_too_long",
                    "blocks_needed": blocks_needed,
                    "blocks_available": self.pool.capacity_blocks})
                err = PromptTooLongError(
                    f"prompt ({len(prompt_ids)}) + max_new_tokens "
                    f"({max_new_tokens}) needs {blocks_needed} KV blocks "
                    f"of {self.kv_block} positions but the pool has "
                    f"{self.pool.capacity_blocks}")
                err.blocks_needed = blocks_needed
                err.blocks_available = self.pool.capacity_blocks
                raise err
        elif self._cache_cap is not None:
            if needed > self._cache_cap:
                # rejected up front (HTTP 413 at the serving layer), not
                # admitted to die mid-decode on the attention layer's
                # KV-overflow guard
                self._m_rejected.inc()
                self.tracer.instant("reject", req=rid, args={
                    "request_id": rid, "reason": "prompt_too_long",
                    "needed": needed, "cache": self._cache_cap})
                raise PromptTooLongError(
                    f"prompt ({len(prompt_ids)}) + max_new_tokens "
                    f"({max_new_tokens}) needs a KV cache of {needed} but "
                    f"max_cache_len={self._cache_cap}")
        # the per-request logit pipeline is built HERE — including the
        # supervisor's crash-recovery resubmission, whose kwargs carry
        # the same grammar/stop/penalty spec — so a token-identical
        # re-decode re-observes from a clean pipeline state
        proc = None
        if (grammar is not None or stop or repetition_penalty
                or presence_penalty or frequency_penalty):
            proc = LogitState(self.vocab_size, grammar=grammar, stop=stop,
                              repetition_penalty=repetition_penalty,
                              presence_penalty=presence_penalty,
                              frequency_penalty=frequency_penalty)
            if grammar is not None and _handle is None:
                # _handle set = the supervisor's crash-recovery
                # resubmission of a request already counted once
                self._m_constrained.inc()
        handle = _handle if _handle is not None else DecodeHandle(
            len(prompt_ids), max_new_tokens, request_id=rid,
            priority=priority)
        if stream is not None:
            handle.stream = stream
        seq = _ActiveSeq(handle, prompt_ids, temperature, top_k, top_p,
                         seed, eos_id)
        seq.proc = proc
        if fork is not None:
            fork.bind_primary(handle)
            seq.fork = fork
        with self._cond:
            if not self._running:
                raise RuntimeError("scheduler is not running (call start())")
            if len(self._queue) >= self.max_queue:
                self._m_rejected.inc()
                self.tracer.instant("reject", req=rid, args={
                    "request_id": rid, "reason": "queue_full",
                    "waiting": len(self._queue)})
                raise QueueFullError(
                    f"decode queue full ({self.max_queue} waiting)")
            if _front:
                self._queue.insert(0, seq)
            else:
                self._queue.append(seq)
            self._m_queue_depth.set(len(self._queue))
            # the request's first span opens while the queue lock is
            # still held — the scheduler needs _cond to pop this seq, so
            # its end("queued") can never be sequenced before this begin
            self.tracer.begin("queued", req=rid,
                              args={"prompt_tokens": len(seq.prompt),
                                    "max_new_tokens": max_new_tokens})
            self._cond.notify()
        return handle

    def generate_handle(self, prompt_ids: Sequence[int],
                        max_new_tokens: int,
                        timeout: Optional[float] = 120.0,
                        **kw) -> DecodeHandle:
        """Blocking submit returning the COMPLETED handle (tokens plus
        the request_id and per-phase `timings()` the serving layer echoes
        back). A timed-out wait CANCELS the request (the slot is
        reclaimed at the scheduler's next step instead of decoding to
        max_new_tokens for a caller that already gave up) — the one
        place this contract lives; `generate` and the HTTP `/generate`
        route both come through here."""
        handle = self.submit(prompt_ids, max_new_tokens, **kw)
        try:
            handle.result(timeout)
        except TimeoutError:
            handle.cancel()
            raise
        return handle

    def generate(self, prompt_ids: Sequence[int], max_new_tokens: int,
                 timeout: Optional[float] = 120.0, **kw) -> List[int]:
        """Blocking submit — drop-in for `generate_transformer` greedy."""
        return self.generate_handle(prompt_ids, max_new_tokens,
                                    timeout=timeout, **kw).tokens

    def generate_many(self, prompt_ids: Sequence[int], n: int,
                      max_new_tokens: int,
                      timeout: Optional[float] = 120.0, *, seed: int = 0,
                      **kw) -> List[DecodeHandle]:
        """Best-of-n over ONE prompt: ``n`` candidates submitted as a
        copy-on-write fork group (`speculative.submit_fork_group` — the
        shared submission protocol: seed+i per candidate, partial-
        submit failures cancel the already-submitted, a timeout cancels
        all unfinished). In paged mode the first candidate (the
        primary) prefills the prompt once and publishes its blocks the
        moment its prefill completes; follower candidates restore them
        as zero-copy block-table remaps and copy-on-write only the tail
        block they write — n candidates cost ~one prompt's worth of KV
        instead of n (`decode_forks_total` counts the attaches).
        Candidate 0 reproduces the n=1 output for the same seed
        exactly."""
        from .speculative import await_fork_group, submit_fork_group
        handles = submit_fork_group(self.submit, prompt_ids, n,
                                    max_new_tokens, seed=seed, **kw)
        await_fork_group(handles, timeout)
        return handles

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "DecodeScheduler":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-scheduler")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._fenced:
            # a fenced engine's handles are DISOWNED (the supervisor
            # requeued them onto a replacement): finishing them here
            # would fail requests another engine is actively serving.
            # Just drop the references; the stuck thread (if any) exits
            # at its next fence check.
            with self._cond:
                self._running = False
                self._queue.clear()
                self._cond.notify_all()
            if self._thread is not None:
                self._thread.join(timeout=1)
                self._thread = None
            # safe lock-free: the loop thread is joined (or, if it is a
            # hung zombie, exits at its fence check without writing)
            for seq in self._slots:  # graftlint: disable=CC004
                if seq is not None:
                    # disown, don't judge: the supervisor requeued this
                    # request onto a replacement engine, and this dead
                    # engine's pool (pins, blocks, mask rows and all)
                    # is garbage-collected wholesale
                    ledger_forget(seq.handle.request_id, _LEDGER_KINDS)
            self._slots = [None] * self.n_slots  # graftlint: disable=CC004
            if self.tier is not None:
                # disowned engine: stop the worker, skip the balance
                # check (the ledger entries were forgotten wholesale)
                self.tier.stop(check=False)
            return
        with self._cond:
            self._running = False
            pending = self._queue[:]
            self._queue.clear()
            self._cond.notify_all()
        for seq in pending:
            seq.handle._finish(RuntimeError("scheduler stopped"))
            self._trace_done("cancel", seq)
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # a pool-pressure preemption racing the drain above can requeue
        # a slot-resident sequence AFTER _queue was cleared; drain once
        # more now that the scheduler thread (the only other writer) is
        # joined, or that handle would never finish and its caller's
        # result() would block out its full timeout
        with self._cond:
            pending = self._queue[:]
            self._queue.clear()
        for seq in pending:
            seq.handle._finish(RuntimeError("scheduler stopped"))
            self._trace_done("cancel", seq)
        # safe lock-free: the scheduler thread (the only other _slots
        # writer) has been joined above
        for i, seq in enumerate(self._slots):  # graftlint: disable=CC004
            if seq is not None:
                if self.pool is not None:
                    self._release_pool(seq)
                    if self.paged:
                        self._release_slot_blocks(i, seq)
                self._release_mask(seq)
                seq.handle._finish(RuntimeError("scheduler stopped"))
                self._trace_done("cancel", seq, slot=i)
                self._slots[i] = None
                ledger_note("engine_slot", seq.handle.request_id, -1)
        if self.tier is not None:
            # joins the transfer worker and zeroes the tier ledger
            # (host_page / disk_block / directory_entry) before the
            # engine's own balance check below
            self.tier.stop()
        ledger_check_zero("engine.stop", _LEDGER_KINDS)

    # -- scheduler loop ----------------------------------------------------
    def _trace_done(self, outcome: str, seq: _ActiveSeq,
                    slot: Optional[int] = None) -> None:
        """Terminal trace records for one request: close whichever phase
        span is still open (a slot-resident request always has `prefill`
        or `decode` open; a never-admitted one has `queued`), then stamp
        one ``finish``/``cancel`` instant carrying the handle's full
        timing breakdown — the record `request_summaries` scrapes. Call
        AFTER `handle._finish()` so `timings()` sees t_done."""
        h = seq.handle
        rid = h.request_id
        tr = self.tracer
        if not tr.enabled:
            return
        self._close_phase_span(seq)
        tr.instant(outcome, req=rid,
                   args={"request_id": rid, "tokens": len(h.tokens),
                         **({"retries": h.retries} if h.retries else {}),
                         **h.timings()})
        if slot is not None:
            tr.instant("free", track=self._slot_tracks[slot],
                       args={"request": rid})

    def _close_phase_span(self, seq: _ActiveSeq) -> None:
        """End whichever request-track span is open. seq.phase (not the
        handle timestamps) names it: a resumed sequence is back in
        "prefill" with t_first_token long stamped, and one cancelled
        while swapped out has "preempted" open instead of "queued"."""
        h = seq.handle
        rid = h.request_id
        tr = self.tracer
        if seq.phase == "queued":
            tr.end("queued", req=rid)
        elif seq.phase == "prefill":
            tr.end("prefill", req=rid, args={"fed_tokens": seq.fed})
        elif seq.phase == "preempted":
            tr.end("preempted", req=rid)
        else:
            tr.end("decode", req=rid,
                   args={"tokens": len(h.tokens), "iterations": seq.steps})

    def _evict_cancelled(self) -> None:
        for i, seq in enumerate(self._slots):
            if seq is not None and seq.handle.cancelled():
                self._m_cancelled.inc()
                if self.pool is not None:
                    # a cancel during prefill still holds the restored
                    # prefix's trie reference — releasing here is what
                    # keeps refcounts leak-free (nothing is published:
                    # the prompt may be half-written)
                    self._release_pool(seq)
                    if self.paged:
                        self._release_slot_blocks(i, seq)
                # a cancel (incl. the streaming layer's client-
                # disconnect path) releases the grammar mask pin too
                self._release_mask(seq)
                seq.handle.finish_reason = "cancelled"
                seq.handle._finish()  # partial tokens, caller already left
                self._trace_done("cancel", seq, slot=i)
                self._slots[i] = None
                ledger_note("engine_slot", seq.handle.request_id, -1)
                ledger_check_request(seq.handle.request_id,
                                     _LEDGER_KINDS)

    def _pool_can_admit(self, seq: _ActiveSeq,
                        reclaim_memo: List[Optional[int]],
                        pending_blocks: int) -> bool:
        """Paged admission gate: only admit when the pool could actually
        back the prompt's prefill (free + evictable blocks) — admitting
        past that point would just preempt a live slot to make room.
        Always True when no slot is live (eviction alone must then cover
        it: submit() checked the prompt fits the whole pool).
        ``reclaim_memo`` caches the two-trie-walk reclaimable count for
        one _admit pass — nothing mutates the pool under _cond, so one
        walk per pass is exact, not stale. ``pending_blocks`` is what
        this pass's earlier admissions PLUS the already-resident slots'
        not-yet-allocated prefill blocks will claim (chunked prefill
        allocates lazily, at most one chunk per iteration, so a freshly
        admitted prompt's claim lands over the NEXT several passes —
        without the resident debit, admission races ahead of allocation
        and triggers exactly the admit-then-preempt churn this gate
        exists to prevent). Decode-time growth past the prompt is
        deliberately NOT reserved — that tail is what preempt-and-swap
        is for."""
        if not self.paged:
            return True
        if not any(s is not None for s in self._slots):
            return True
        if reclaim_memo[0] is None:
            reclaim_memo[0] = self.pool.reclaimable_blocks()
        return (reclaim_memo[0] - pending_blocks
                >= self._blocks_for(len(seq.prompt)))

    def _admit(self) -> None:
        admitted: List[Tuple[int, _ActiveSeq]] = []
        tr = self.tracer
        reclaim_memo: List[Optional[int]] = [None]
        pending_blocks = 0  # blocks promised but not yet allocated
        if self.paged:
            # resident slots' outstanding prefill claims (scheduler-
            # thread-only reads, same discipline as _step_once)
            pending_blocks = sum(
                max(0, self._blocks_for(len(s.prompt)) - len(s.block_ids))
                for s in self._slots if s is not None)  # graftlint: disable=CC004
        with self._cond:
            blocked = False
            for i in range(self.n_slots):
                if blocked or self._slots[i] is not None:
                    continue
                qi = 0
                while qi < len(self._queue):
                    seq = self._queue[qi]
                    if seq.handle.cancelled():  # gave up while queued
                        self._queue.pop(qi)
                        self._m_cancelled.inc()
                        seq.handle.finish_reason = "cancelled"
                        seq.handle._finish()
                        self._trace_done("cancel", seq)
                        continue
                    if (self.paged and seq.fork is not None
                            and seq.fork.waiting(seq.handle)):
                        # best-of-n FOLLOWER: stay queued until the
                        # primary's prefill publishes the prompt blocks
                        # this candidate exists to share — admitting it
                        # now would cold-prefill its own copy and defeat
                        # the fork. Bounded wait (one prefill), not
                        # starvation: the gate opens the moment the
                        # primary publishes, finishes, or dies.
                        qi += 1
                        continue
                    if not self._pool_can_admit(seq, reclaim_memo,
                                                pending_blocks):
                        # head-of-line blocking is deliberate: skipping
                        # ahead would starve the (front-requeued)
                        # preempted sequence the gate exists to protect
                        blocked = True
                        break
                    self._queue.pop(qi)
                    self._slots[i] = seq
                    ledger_note("engine_slot", seq.handle.request_id, +1)
                    if self.paged:
                        pending_blocks += self._blocks_for(len(seq.prompt))
                    if not seq.resumed:
                        self._m_seqs.inc()
                    admitted.append((i, seq))
                    break
            self._m_queue_depth.set(len(self._queue))
            self._m_active.set(sum(s is not None for s in self._slots))
        # device work happens OUTSIDE the condvar: the slot-reset and
        # prefix-restore dispatches (and a restore bucket's first-call
        # compile, which can take seconds) must not stall every submit()
        # caller blocked on _cond. _slots/_states/pool are scheduler-
        # thread-only, so no lock is needed past the queue handoff.
        for i, seq in admitted:
            h = seq.handle
            rid = h.request_id
            h.t_admitted = time.monotonic()
            if seq.phase == "preempted":
                tr.end("preempted", req=rid)
                tr.instant("resume", track=self._slot_tracks[i],
                           args={"request": rid,
                                 "refeed_tokens": len(seq.prompt)})
            else:
                tr.end("queued", req=rid)
            tr.instant("admit", track=self._slot_tracks[i],
                       args={"request": rid})
            tr.begin("prefix_restore", req=rid)
            self._reset_slot_state(i)
            if self.pool is not None:
                if self.paged:
                    self._try_restore_paged(i, seq)
                else:
                    self._try_restore(i, seq)
            # grammar mask upload rides the admission window too (a
            # preempted-and-resumed request re-acquires here — its rows
            # are usually still cached, so this is a refcount bump)
            self._attach_mask(i, seq)
            h.t_restored = time.monotonic()
            tr.end("prefix_restore", req=rid,
                   args={"hit_tokens": seq.fed, "slot": i,
                         **({"remap_blocks": len(seq.block_ids),
                             "kv_copies": 0} if self.paged else {})})
            tr.begin("prefill", req=rid,
                     args={"prompt_tokens": len(seq.prompt),
                           "restored_tokens": seq.fed, "slot": i})
            seq.phase = "prefill"

    def _consume(self, slot: int, seq: _ActiveSeq,
                 probs_row: np.ndarray) -> None:
        """Sample one output token from a next-token distribution row;
        finish + evict on max_new_tokens or EOS. Shared by the decode step
        and the final prefill chunk (whose last-real-token distribution
        yields the first output token). Token-count metrics are NOT
        updated here — the loop flushes one batched `inc(n)` per
        iteration instead of taking the counter lock once per token."""
        proc = seq.proc
        if proc is None:
            tok = sample_logits(probs_row, seq.temperature, seq.top_k,
                                seq.rng, seq.top_p)
        else:
            # penalty-adjust + EXACT host-side grammar mask (forbidden
            # tokens get probability 0 whatever the device mask did),
            # then observe — the pipeline's state advances on emitted
            # tokens only, in emission order
            tok = sample_logits(proc.adjust(probs_row), seq.temperature,
                                seq.top_k, seq.rng, seq.top_p,
                                allow=proc.allow_row())
            proc.advance(tok)
        self._emit(slot, seq, tok)

    def _fork_publish(self, slot: int, seq: _ActiveSeq) -> None:
        """Best-of-n early publish: the fork group's PRIMARY just
        finished prefill — run the SAME `_publish_paged` ownership
        transfer finish-time publish uses, just earlier, so queued
        sibling candidates restore the prompt blocks as zero-copy
        block-table remaps instead of each re-prefilling. The adopted
        blocks flip to shared in the slot's own bookkeeping (its next
        write into one — there is none before the decode tail — would
        COW), and the slot takes a trie pin so eviction cannot free
        rows it still reads."""
        group = seq.fork
        adopted = self._publish_paged(slot, seq)
        if adopted:
            for j, bid in enumerate(seq.block_ids):
                if bid in adopted:
                    seq.shared[j] = True
            self._release_pool(seq)
            n_full = len(seq.prompt) // self.pool.block
            _, _, node = self.pool.match(seq.prompt, n_full)
            seq.pool_node = node
            if node is not None:
                ledger_note("trie_pin", seq.handle.request_id, +1)
            if self.tracer.enabled:
                self.tracer.instant(
                    "fork", track=self._slot_tracks[slot],
                    args={"request": seq.handle.request_id,
                          "role": "publish", "blocks": len(adopted),
                          "candidates": group.n})
        group.published = True

    def _emit(self, slot: int, seq: _ActiveSeq, tok: int) -> None:
        """Append one ALREADY-SAMPLED output token to the handle;
        finish + evict on max_new_tokens or EOS. The single emission
        path shared by plain decode (`_consume` samples then emits) and
        the speculative acceptance loop (which sampled while walking
        the verify distributions)."""
        if self._fenced:
            # a fenced thread woke mid-iteration: this handle may
            # already be requeued on the replacement engine — appending
            # a token (or finishing) here would corrupt/duplicate it
            raise _EngineFenced
        h = seq.handle
        if h.done():
            return  # a speculative chain can run past a stop-sequence /
            # grammar finish: the tail tokens were sampled (RNG spent on
            # a finished request — harmless) but must not be appended
        h.tokens.append(tok)
        self._emitted_this_iter += 1
        now = time.monotonic()
        if h.t_first_token is None:
            h.t_first_token = now
            h.steps_to_first_token = seq.steps
            ttft = now - h.t_submit
            # two series, one value, deliberately: decode_time_to_
            # first_token_sec is the PR-1-era name dashboards already
            # scrape; generate_first_token_seconds (exemplar-linked
            # into /trace) is the ISSUE 14 streaming-TTFT contract
            self._m_ttft.record(ttft)
            self._m_first_token.record(ttft, exemplar=h.request_id)
            if self.tracer.enabled:
                # the request waterfall's TTFT marker (ISSUE 14
                # satellite): right where prefill hands off to decode
                self.tracer.instant(
                    "first_token", req=h.request_id,
                    args={"request_id": h.request_id,
                          "ttft_ms": round(ttft * 1e3, 3)})
        if seq.phase == "prefill":
            # phase boundary on the request track: prompt ingestion is
            # over the moment the first output token exists. Keyed on
            # seq.phase, not t_first_token — a RESUMED sequence re-runs
            # prefill with its first-token timestamp long stamped
            self.tracer.end("prefill", req=h.request_id,
                            args={"steps": seq.steps})
            self.tracer.begin("decode", req=h.request_id)
            seq.phase = "decode"
            if (self.paged and seq.fork is not None
                    and seq.fork.primary_handle is h
                    and not seq.fork.published):
                self._fork_publish(slot, seq)
        proc = seq.proc
        if proc is not None:
            # stop sequences match across token boundaries (Aho-Corasick
            # over the emitted stream — a stop split across speculative
            # bursts still matches); the matched tokens are truncated
            # OFF the output before the handle finishes
            matched = proc.stop_feed(tok)
            if matched:
                del h.tokens[len(h.tokens) - matched:]
                h.finish_reason = "stop"
                self._retire(slot, seq, now)
                return
        if h.stream is not None:
            # streaming release with stop hold-back: tokens that form a
            # live partial stop match are withheld (flushed by the next
            # mismatch, or discarded by the truncation above) so an SSE
            # client never sees half a stop sequence
            safe = len(h.tokens) - (proc.stop_pending
                                    if proc is not None else 0)
            for idx in range(h.stream.sent, safe):
                h.stream.push(idx, h.tokens[idx])
        if (len(h.tokens) >= h.max_new_tokens
                or (seq.eos_id is not None and tok == seq.eos_id)):
            h.finish_reason = ("eos" if seq.eos_id is not None
                               and tok == seq.eos_id else "length")
            self._retire(slot, seq, now)

    def _retire(self, slot: int, seq: _ActiveSeq,
                now: Optional[float] = None) -> None:
        """Finish + evict one slot-resident sequence — max tokens, EOS,
        stop-sequence match, or grammar completion. The single
        retirement path: publish the prompt's blocks for the next
        prefix sharer, drop pool + mask pins, finish the handle (which
        closes its token stream with the terminal event), free the
        slot."""
        if now is None:
            now = time.monotonic()
        h = seq.handle
        if self.pool is not None:
            # retain the prompt's prefill-written blocks for the next
            # request sharing this prefix, then drop our own pin.
            # Paged: pure ownership transfer (trie adopts the pages
            # in place); contiguous: jitted scatter into the side
            # pool's storage
            if self.paged:
                adopted = self._publish_paged(slot, seq)
                self._release_pool(seq)
                self._release_slot_blocks(slot, seq, keep=adopted)
            else:
                self._publish_prompt(slot, seq)
                self._release_pool(seq)
        self._release_mask(seq)
        h._finish()
        self._trace_done("finish", seq, slot=slot)
        self._m_latency.record(now - h.t_submit)
        self._slots[slot] = None
        ledger_note("engine_slot", h.request_id, -1)
        ledger_check_request(h.request_id, _LEDGER_KINDS)

    def _run_prefill_chunk(self) -> Optional[int]:
        """At most one bounded prefill chunk per iteration (round-robin
        over prefilling slots). Returns the chunked slot index, or None."""
        if not self.prefill_buckets:
            return None
        for off in range(self.n_slots):
            i = (self._prefill_next + off) % self.n_slots
            seq = self._slots[i]
            if seq is None or seq.fed >= len(seq.prompt):
                continue
            bucket, n_real = self._pick_chunk(seq)
            if not n_real:
                continue  # no cache headroom: token-by-token fallback
            if self.paged:
                # lazy allocation + COW happen HERE, host-side, before
                # the program runs: every block the chunk really writes
                # is allocated and exclusively owned by dispatch time
                if not self._ensure_blocks(i, seq, seq.written + n_real) \
                        or not self._ensure_writable(i, seq, seq.written):
                    continue  # seq itself was preempted for blocks
            ids = np.zeros((bucket,), np.int32)
            ids[:n_real] = seq.prompt[seq.fed:seq.fed + n_real]
            failpoints.fire("dispatch.prefill")
            self.profiler.count("prefill", bucket)
            if self.tracer.enabled:  # keep tracing-off allocation-free
                self.tracer.begin("prefill_chunk",
                                  track=self._slot_tracks[i],
                                  args={"request": seq.handle.request_id,
                                        "bucket": bucket, "tokens": n_real})
            if self.paged:
                # table bucket covers the PADDED chunk end so the
                # layer's overflow guard never trips on pad lanes
                probs, self._states = self._jprefill(
                    self._params, self._variables,
                    self._dev_index(i), self._dev_array(ids),
                    self._dev_index(n_real),
                    self._dev_array(self._table_for(seq.written + bucket)),
                    self._states)
                seq.written += n_real
            else:
                probs, self._states = self._jprefill(
                    self._params, self._variables,
                    self._dev_index(i), self._dev_array(ids),
                    self._dev_index(n_real), self._states)
                seq.written += n_real  # host pos mirror (spec fixpos)
            if self.speculate and seq.draft_fed == seq.fed \
                    and self._draft_cap is not None \
                    and seq.draft_fed + bucket <= self._draft_cap:
                # piggyback: the DRAFT ingests the same chunk (it must
                # hold the prompt to propose continuations of it) — one
                # extra shallow dispatch per chunk, the speculation tax
                # on TTFT. A restore-jumped sequence is out of sync
                # (draft_fed < fed) and catches up via
                # _run_draft_catchup instead.
                self.profiler.count("draft_prefill", bucket)
                _, self._draft_states = self._jdraft_prefill(
                    self._draft_params, self._draft_variables,
                    self._dev_index(i), self._dev_array(ids),
                    self._dev_index(n_real), self._draft_states)
                seq.draft_fed += n_real
            seq.fed += n_real
            seq.steps += 1
            self._m_prefill_tokens.inc(n_real)
            self._m_prefill_chunk.record(n_real)
            if seq.sampling:  # final chunk: its output is the first token
                self._consume(i, seq, host_read(probs))
            self.tracer.end("prefill_chunk", track=self._slot_tracks[i])
            self._prefill_next = (i + 1) % self.n_slots
            return i
        return None

    # -- speculative decoding: draft, verify, accept, roll back ------------
    def _spec_ready(self, seq: _ActiveSeq) -> bool:
        """Can this decode-ready slot speculate THIS iteration? Needs
        the draft within lockstep range (lag 1 after a plain accept, 2
        after a fully-accepted round — anything more is mid-catch-up),
        gamma+1 rows of cache headroom on both nets, and at least 2
        tokens still wanted (the last token is cheapest decoded plain)."""
        G = self.speculate
        h = seq.handle
        lag = seq.known_tokens() - seq.draft_fed
        # lag > G would make every lockstep round a catch-up round and
        # send ZERO proposals to the verify — speculate=1's post-full-
        # accept lag-2 state would pay draft+verify+fixpos per single
        # token forever; decoding plain instead grows lag past 2 and
        # _run_draft_catchup resyncs the draft for the next real round
        if not 1 <= lag <= min(2, G):
            return False
        if h.max_new_tokens - len(h.tokens) < 2:
            return False
        if self._cache_cap is not None and \
                seq.written + G + 1 > self._cache_cap:
            return False
        if self._draft_cap is not None and \
                seq.draft_fed + G > self._draft_cap:
            return False
        return True

    def _run_draft_catchup(self) -> Optional[int]:
        """At most one draft catch-up chunk per iteration: a decode-
        phase sequence whose MAIN cache jumped past tokens the draft
        never ingested (prefix restore, preempt-resume) re-feeds the
        gap through the draft's chunk-prefill program — the draft costs
        ~K/N of a forward, so a restored prefix still keeps most of its
        TTFT win. The slot decodes plain until lag re-enters lockstep
        range."""
        if not self.speculate:
            return None
        for i in range(self.n_slots):
            seq = self._slots[i]
            if seq is None or not seq.sampling:
                continue
            lag = seq.known_tokens() - seq.draft_fed
            if lag <= 2:
                continue
            # target full_len - 1: the LAST token is the lockstep
            # round's feed (its draft output is the first proposal)
            n_real = min(lag - 1, self.prefill_chunk)
            bucket = bucket_for(n_real, self.prefill_buckets)
            if self._draft_cap is not None and \
                    seq.draft_fed + bucket > self._draft_cap:
                fitting = [b for b in self.prefill_buckets
                           if seq.draft_fed + b <= self._draft_cap]
                if not fitting:
                    continue  # no draft headroom: stays plain decode
                bucket = fitting[-1]
                n_real = min(n_real, bucket)
            full = seq.full_context()
            ids = np.zeros((bucket,), np.int32)
            ids[:n_real] = full[seq.draft_fed:seq.draft_fed + n_real]
            self.profiler.count("draft_prefill", bucket)
            _, self._draft_states = self._jdraft_prefill(
                self._draft_params, self._draft_variables,
                self._dev_index(i), self._dev_array(ids),
                self._dev_index(n_real), self._draft_states)
            seq.draft_fed += n_real
            return i
        return None

    def _truncate_blocks(self, slot: int, seq: _ActiveSeq) -> int:
        """Paged rollback: pop the slot's table entries that now sit
        wholly beyond the accepted frontier (verify pre-allocated blocks
        through pos+gamma+1; acceptance may have stopped short) and
        return the owned pages to the pool. Shared (trie-owned) blocks
        never extend past the write frontier, but the guard keeps a
        refcount leak structurally impossible. Returns blocks freed."""
        need = self._blocks_for(seq.written)
        freed = owned = 0
        while len(seq.block_ids) > need:
            bid = seq.block_ids.pop()
            sh = seq.shared.pop()
            self._table[slot, len(seq.block_ids)] = SCRATCH_BLOCK
            if not sh:
                self.pool.free_block(bid)
                owned += 1
            freed += 1
        if owned:
            ledger_note("pool_block", seq.handle.request_id, -owned)
        return freed

    def _run_speculation(self, spec: List[Tuple[int, _ActiveSeq]]) -> None:
        """The speculative iteration for every eligible slot at once:

        1. DRAFT — gamma lockstep rounds of the cheap draft step
           (shallow exit / draft net), each round feeding the previous
           round's greedy output; round r < lag feeds catch-up tokens
           the draft hasn't ingested (lag 2 follows a fully-accepted
           round, where the bonus token was never drafted).
        2. VERIFY — ONE multi-token target forward over all chains
           (`[last_token, d_1..d_g]`, padded to gamma+1), every
           position's distribution retained.
        3. ACCEPT — `speculative.accept_tokens` samples each position
           from the TARGET distribution with the sequence's own RNG and
           keeps the longest draft-confirmed prefix (+1 bonus): output
           is token-identical to solo decode by construction.
        4. ROLL BACK — one fixpos program per net steps pos back over
           the rejected tail; paged mode also truncates the block table
           and returns the freed pages.
        """
        G = self.speculate
        tr = self.tracer
        dp, dv = self._draft_params, self._draft_variables
        info = []
        for i, seq in spec:
            known = seq.known_tokens()
            lag = known - seq.draft_fed
            # the lockstep only feeds the trailing lag (<= 2) tokens —
            # an O(lag) tail, never an O(context) copy per iteration
            info.append((i, seq, known, lag, seq.tail_context(lag), []))
        live = np.zeros((self.n_slots,), bool)
        for i, _seq, _k, _l, _t, _p in info:
            live[i] = True
        ldev = self._dev_array(live)
        # grammar composition: per-slot SPECULATIVE DFA state chain —
        # schain[i][j] is the state after proposals[0..j-1], starting
        # from the pipeline's live state (every emitted token already
        # observed). Drives the per-round draft mask, the per-position
        # verify mask, and the host-exact mask on draft argmax rows.
        schain: Dict[int, List[int]] = {}
        use_mask = False
        for i, seq, _k, _l, _t, _p in info:
            p = seq.proc
            if p is not None and p.grammar is not None:
                schain[i] = [p.gstate]
                if self._jdraft_step_m is not None \
                        and p.mask_base is not None:
                    use_mask = True
        for r in range(G):
            ids = np.zeros((self.n_slots,), np.int32)
            for i, seq, known, lag, tail, props in info:
                ids[i] = tail[r] if r < lag else props[r - lag]
            self.profiler.count("draft", 0)
            if use_mask:
                # the draft proposes under the same mask verify applies:
                # each round gathers the chain-state-so-far's mask row
                mstate = np.zeros((self.n_slots,), np.int32)
                for i, seq, _k, _l, _t, _p in info:
                    p = seq.proc
                    if p is not None and p.mask_base is not None:
                        mstate[i] = p.mask_base + schain[i][-1]
                dprobs, self._draft_states = self._jdraft_step_m(
                    dp, dv, self._dev_array(ids), ldev,
                    self._dev_array(mstate), self._masks,
                    self._draft_states)
            else:
                dprobs, self._draft_states = self._jdraft_step(
                    dp, dv, self._dev_array(ids), ldev,
                    self._draft_states)
            rows = host_read(dprobs)
            for i, seq, known, lag, tail, props in info:
                if r >= lag - 1:  # catch-up rounds' outputs are known
                    # rows is host numpy (the host_read above IS the
                    # sanctioned boundary); this int() syncs nothing
                    row = rows[i]
                    if i in schain:
                        # host-exact mask on the proposal argmax (covers
                        # host-only grammars the device never masked):
                        # softmax rows are >= 0, so -1 can never win
                        g = seq.proc.grammar
                        allow = g.allow[schain[i][-1]]
                        row = np.where(allow, row, -1.0)
                        prop = int(row.argmax())  # graftlint: disable=JG006
                        schain[i].append(g.step(schain[i][-1], prop))
                        props.append(prop)
                        continue
                    props.append(int(row.argmax()))  # graftlint: disable=JG006
        # seam BEFORE any span opens (the decode/prefill seam ordering:
        # an injected crash must not strand unclosed B-events)
        failpoints.fire("dispatch.verify")
        ids2 = np.zeros((self.n_slots, G + 1), np.int32)
        for i, seq, known, lag, tail, props in info:
            chain = [tail[-1]] + props
            chain += [chain[-1]] * (G + 1 - len(chain))  # pad lanes
            ids2[i] = chain
            if tr.enabled:
                tr.instant("draft", track=self._slot_tracks[i],
                           args={"request": seq.handle.request_id,
                                 "proposed": len(props)})
                tr.begin("verify", req=seq.handle.request_id,
                         args={"slot": i, "proposed": len(props)})
        mstate2 = None
        if use_mask:
            # position j's mask = the state after proposals[0..j-1]
            # (exactly what the draft proposed under); pad lanes repeat
            # the last state — their rows are never read
            mstate2 = np.zeros((self.n_slots, G + 1), np.int32)
            for i, seq, _k, _l, _t, props in info:
                p = seq.proc
                if p is not None and p.mask_base is not None:
                    chain = schain[i]
                    padded = chain + [chain[-1]] * (G + 1 - len(chain))
                    mstate2[i] = [p.mask_base + s
                                  for s in padded[:G + 1]]
        if self.paged:
            table = self._table_for(max(s.written + G + 1
                                        for _, s, _k, _l, _t, _p in info))
            self.profiler.count("verify", table.shape[1])
            if mstate2 is not None:
                vprobs, self._states = self._jverify_m(
                    self._params, self._variables, self._dev_array(ids2),
                    ldev, self._dev_array(table),
                    self._dev_array(mstate2), self._masks, self._states)
            else:
                vprobs, self._states = self._jverify(
                    self._params, self._variables, self._dev_array(ids2),
                    ldev, self._dev_array(table), self._states)
        else:
            self.profiler.count("verify", 0)
            if mstate2 is not None:
                vprobs, self._states = self._jverify_m(
                    self._params, self._variables, self._dev_array(ids2),
                    ldev, self._dev_array(mstate2), self._masks,
                    self._states)
            else:
                vprobs, self._states = self._jverify(
                    self._params, self._variables, self._dev_array(ids2),
                    ldev, self._states)
        rows2 = host_read(vprobs)
        posv = np.zeros((self.n_slots,), np.int32)
        dposv = np.zeros((self.n_slots,), np.int32)
        mask = np.zeros((self.n_slots,), bool)
        proposed = accepted = 0
        for i, seq, known, lag, tail, props in info:
            h = seq.handle
            remaining = h.max_new_tokens - len(h.tokens)
            emitted, matched = accept_tokens(
                rows2[i], props, seq.temperature, seq.top_k, seq.top_p,
                seq.rng, remaining, seq.eos_id, proc=seq.proc)
            proposed += len(props)
            accepted += matched
            seq.steps += 1
            seq.written += len(emitted)
            seq.draft_fed = known + min(G - lag, matched)
            for tok in emitted:
                self._emit(i, seq, tok)
            freed = 0
            if self.paged and self._slots[i] is seq:
                freed = self._truncate_blocks(i, seq)
            mask[i] = True
            posv[i] = seq.written
            dposv[i] = seq.draft_fed
            if tr.enabled:
                tr.end("verify", req=h.request_id,
                       args={"accepted": len(emitted),
                             "matched": matched})
                if len(emitted) < len(props) + 1:
                    tr.instant(
                        "rollback", track=self._slot_tracks[i],
                        args={"request": h.request_id,
                              "rejected": len(props) + 1 - len(emitted),
                              "blocks_freed": freed})
        mdev = self._dev_array(mask)
        self._states = self._jfixpos(self._states,
                                     self._dev_array(posv), mdev)
        self._draft_states = self._jdraft_fixpos(
            self._draft_states, self._dev_array(dposv), mdev)
        self._m_spec_proposed.inc(proposed)
        if accepted:
            self._m_spec_accepted.inc(accepted)

    # -- KV tiering (kvtier.py, ISSUE 19) ----------------------------------
    def _tier_tick(self) -> None:
        """Per-iteration tier maintenance on the scheduler thread: grant
        the worker its pacing credits, serve pending HBM copydowns
        (peer fetches), integrate promotions the worker staged, and
        upgrade mid-prefill slots onto newly resident blocks. Every
        step is bounded — the decode hot path never waits on a
        transfer; an un-landed promotion just means the slot keeps
        prefilling its cold suffix as today."""
        tier = self.tier
        idle = all(s is None for s in self._slots)
        # idle iterations run at the 10 Hz wake; grant a bigger budget
        # so a backlog drains fast when nobody is decoding
        grant = self._tier_chunk * (8 if idle else 1)
        tier.pace(grant)
        for h in tier.pending_copydowns(4):
            self._tier_copydown(h)
        promoted = False
        for entry, rows in tier.drain_ready(grant):
            promoted = self._integrate_promotion(entry, rows) or promoted
        if promoted:
            self._try_upgrade_slots()

    def _tier_copydown(self, h: str) -> None:
        """Capture an HBM-resident chain block into the host ring (no
        eviction) so /prefix/block can serve it to a peer."""
        tier = self.tier
        info = tier.entry_info(h)
        if info is None:
            return
        prefix, depth = info
        node, ids = self.pool._walk_prefix(list(prefix), depth)
        if len(ids) != depth or node.hash != h:
            return  # no longer resident; waiter times out / uses a tier
        tier.complete_copydown(h, self._tier_capture(node.block_id))

    def _integrate_promotion(self, entry, rows) -> bool:
        """Upload one promoted page row and adopt it into the trie via
        the zero-copy publish path. Any failure — injected fault, no
        free page, parent chain gone — drops the promotion; the prefix
        recomputes cold (correct, just slower)."""
        tier = self.tier
        tokens = list(entry.prefix)
        depth = int(entry.depth)
        node, ids = self.pool._walk_prefix(tokens, depth)
        if len(ids) == depth:
            tier.promotion_done(entry.hash, True)  # already resident
            return False
        if len(ids) != depth - 1:
            tier.promotion_done(entry.hash, False)  # parents not landed
            return False
        bid = self.pool.alloc()
        if bid is None:
            # pool fully referenced: promotion must never preempt live
            # work — drop it, the hot path wins
            tier.promotion_done(entry.hash, False)
            return False
        try:
            dev_rows = {
                lk: {pk: self._dev_array(a) for pk, a in pks.items()}
                for lk, pks in rows.items()}
            self._states = self._jtier_restore(  # graftlint: disable=CC005
                self._states, self._dev_index(bid), dev_rows)
        except Exception:
            self.pool.free_block(bid)
            tier.promotion_done(entry.hash, False)
            raise
        # zero-copy adopt: the trie takes over the freshly-written page
        # (note_resident fires inside, flipping the directory tier)
        self.pool.adopt(tokens, ids + [bid])
        tier.promotion_done(entry.hash, True)
        self._m_tier_promoted.inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "tier_restore", track="scheduler",
                args={"hash": entry.hash[:12], "depth": depth,
                      "block": bid})
        return True

    def _try_upgrade_slots(self) -> None:
        """Re-match mid-prefill slots against the trie after promotions
        landed: a slot whose cold suffix just became resident swaps its
        pin to the deeper node, remaps its table onto the shared
        blocks, and jumps ``pos`` past them — the restore-in-flight
        contract: prefill as usual until the pages land, then skip."""
        B = self.kv_block
        for i, seq in enumerate(self._slots):
            if seq is None or seq.fed >= len(seq.prompt) \
                    or seq.cow_starved:
                continue
            max_hit = len(seq.prompt) // B
            cur = seq.fed // B
            if max_hit <= cur:
                continue
            n2, ids2, node2 = self.pool.match(seq.prompt, max_hit)
            if node2 is None:
                continue
            if n2 * B <= seq.fed:
                self.pool.release(node2)
                continue
            rid = seq.handle.request_id
            if seq.pool_node is not None:
                self.pool.release(seq.pool_node)
                seq.pool_node = None
            else:
                ledger_note("trie_pin", rid, +1)
            seq.pool_node = node2
            freed = 0
            for j in range(cur, n2):
                bid2 = ids2[j]  # host ints from the trie walk
                if j < len(seq.block_ids):
                    if not seq.shared[j] \
                            and seq.block_ids[j] != bid2:
                        self.pool.free_block(seq.block_ids[j])
                        freed += 1
                    seq.block_ids[j] = bid2
                    seq.shared[j] = True
                else:
                    seq.block_ids.append(bid2)
                    seq.shared.append(True)
                self._table[i, j] = ids2[j]  # graftlint: disable=CC005
            if freed:
                ledger_note("pool_block", rid, -freed)
            fed = min(n2 * B, len(seq.prompt) - 1)
            gained = fed - seq.fed
            self._states = self._jsetpos(  # graftlint: disable=CC005
                self._states, self._dev_index(i), self._dev_index(fed))
            seq.fed = fed
            seq.written = fed
            self._m_tier_tokens.inc(gained)
            self._m_prefix_hits.inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "tier_restore", track=self._slot_tracks[i],
                    args={"request": rid, "tokens": gained,
                          "blocks": n2 - cur})

    def _step_once(self) -> bool:
        """One scheduler iteration (admission + at most one prefill chunk
        + the all-slots decode step). Returns False when it idled.

        Host<->device discipline: the ONLY blocking device reads are the
        two `host_read` calls (next-token distributions — the sampled
        token must reach the host to be fed back); everything else ships
        to device explicitly (`jnp.asarray` of ndarrays, `device_index`).
        Metric counters are flushed once per iteration, not per token."""
        if self._fenced:
            raise _EngineFenced
        failpoints.fire("scheduler.iteration")
        prof = self.profiler
        prof.iter_begin()
        self._evict_cancelled()
        if self.tier is not None:
            # pace the tier worker and integrate landed promotions
            # BEFORE admission, so an arriving prompt can match blocks
            # promoted this very iteration. Runs on idle passes too
            # (the 10 Hz idle wake in _loop) so spills/promotions drain
            # while the engine has nothing else to do.
            self._tier_tick()
        self._admit()
        # single-writer: _slots is mutated only by this scheduler thread
        # once start() returns (submit() touches only _queue, under
        # _cond); stop() joins the thread before its own sweep
        active = [(i, s) for i, s in enumerate(self._slots)  # graftlint: disable=CC004
                  if s is not None]
        if not active:
            return False  # idle pass: no laps recorded (a 10 Hz idle
            # wake stamping µs admit laps would swamp the histograms)
        prof.lap("admit")
        t0 = time.monotonic()
        self._emitted_this_iter = 0
        chunked = self._run_prefill_chunk()
        prof.lap("prefill")
        self._run_draft_catchup()
        prof.lap("draft")
        # decode step: every decode-ready slot, plus token-by-token
        # prefill for slots chunked prefill cannot serve (disabled, or
        # no bucket fits the remaining cache headroom). With speculation
        # armed, eligible slots ride the draft+verify path (`spec`)
        # instead of the single-token program; the rest — mid-catch-up,
        # out of gamma+1 headroom, one token from done — decode plain.
        fed: List[Tuple[int, _ActiveSeq]] = []
        spec: List[Tuple[int, _ActiveSeq]] = []
        G = self.speculate
        # oldest-first (same t_submit key as _pick_victim): a
        # pool-pressure preemption always victimizes the LATEST-submitted
        # slot, which is processed last here — so an already-vetted
        # candidate can never lose its blocks to a later one's allocation
        # (its removal would leave a stale fed entry writing into freed
        # pages)
        cands = sorted(active, key=lambda e: e[1].handle.t_submit)
        for i, seq in cands:
            if self._slots[i] is not seq or i == chunked:
                continue  # evicted/preempted above / consumed its turn
            if seq.sampling and seq.proc is not None \
                    and seq.proc.exhausted():
                # the grammar admits nothing more: the structured output
                # is COMPLETE — finish before any dispatch (sampling an
                # all-forbidden row has no meaning)
                seq.handle.finish_reason = "grammar"
                self._retire(i, seq)
                continue
            if not seq.sampling and self.prefill_buckets \
                    and self._pick_chunk(seq)[1]:
                continue  # mid-prefill: waits for its chunk turn
            want = G + 1 if G and seq.sampling and self._spec_ready(seq) \
                else 1
            if self.paged:
                if not self._ensure_blocks(i, seq, seq.written + want) \
                        or not self._ensure_writable(i, seq, seq.written):
                    continue  # seq itself was preempted for blocks
            (spec if want > 1 else fed).append((i, seq))
        prof.lap("pool")
        if fed:
            ids = np.zeros((self.n_slots,), np.int32)
            live = np.zeros((self.n_slots,), bool)
            for i, seq in fed:
                ids[i] = seq.next_input()
                live[i] = True
            # masked dispatch only when a DEVICE-RESIDENT grammar is in
            # the batch: pure unconstrained traffic (and host-only
            # fallback grammars) keeps the original program — the
            # single jitted decode program survives constrained serving
            mstate = None
            if self._masks is not None:
                for i, seq in fed:
                    p = seq.proc
                    if p is not None and p.mask_base is not None:
                        if mstate is None:
                            mstate = np.zeros((self.n_slots,), np.int32)
                        # unconstrained slots stay at row 0 (all zeros)
                        mstate[i] = p.mask_base + p.gstate
            failpoints.fire("dispatch.decode")
            if self.tracer.enabled:  # keep tracing-off allocation-free
                self.tracer.begin("decode_step", track=self._sched_track,
                                  args={"live_slots": len(fed)})
            if self.paged:
                table = self._table_for(max(s.written + 1
                                            for _, s in fed))
                prof.count("decode", table.shape[1])
                if mstate is not None:
                    probs, new_states = self._jstep_m(
                        self._params, self._variables,
                        self._dev_array(ids), self._dev_array(live),
                        self._dev_array(table), self._dev_array(mstate),
                        self._masks, self._states)
                else:
                    probs, new_states = self._jstep(
                        self._params, self._variables,
                        self._dev_array(ids), self._dev_array(live),
                        self._dev_array(table), self._states)
            else:
                prof.count("decode", 0)
                if mstate is not None:
                    probs, new_states = self._jstep_m(
                        self._params, self._variables,
                        self._dev_array(ids), self._dev_array(live),
                        self._dev_array(mstate), self._masks,
                        self._states)
                else:
                    probs, new_states = self._jstep(
                        self._params, self._variables,
                        self._dev_array(ids), self._dev_array(live),
                        self._states)
            self._states = new_states
            probs = host_read(probs)
            prof.lap("decode")
            for i, seq in fed:
                seq.steps += 1
                seq.written += 1
                was_sampling = seq.sampling
                if seq.fed < len(seq.prompt):
                    seq.fed += 1
                if not was_sampling and not seq.sampling:
                    continue  # still prefilling; output not sampled yet
                self._consume(i, seq, probs[i])
            self.tracer.end("decode_step", track=self._sched_track)
        prof.lap("accept")
        if spec:
            self._run_speculation(spec)
        prof.lap("verify")
        if self._emitted_this_iter:
            self._m_tokens.inc(self._emitted_this_iter)
        self._m_occupancy.record(len(active))
        self._m_step_time.record(time.monotonic() - t0)
        self._trace_compiles()
        prof.iter_end(tokens=self._emitted_this_iter)
        return True

    def _trace_compiles(self) -> None:
        """Instant event per NEW XLA program: the per-family jit-cache
        sizes (CompileCounter, the same counters the recompile-budget
        tests assert) are polled once per iteration; growth means this
        iteration paid a compile — stamped on the timeline so a
        seconds-long TTFT outlier is attributable to the bucket that
        compiled under it."""
        if not self.tracer.enabled:
            return
        for fam, n in self._compile_counter.counts().items():
            if n > self._compile_seen.get(fam, 0):
                self._compile_seen[fam] = n
                self.tracer.instant("compile", track=self._sched_track,
                                    args={"family": fam, "programs": n})

    def _loop(self) -> None:
        while True:
            self.heartbeat = time.monotonic()
            with self._cond:
                if not self._running:
                    return  # stop() fails any still-active handles
            guard = (jax.transfer_guard(self._transfer_guard)
                     if self._transfer_guard else contextlib.nullcontext())
            try:
                with guard:
                    stepped = self._step_once()
            except _EngineFenced:
                return  # a supervisor already disowned this engine
            except Exception as e:
                # loop death used to be SILENT: the daemon thread
                # evaporated, the HTTP tier kept admitting, and every
                # in-flight caller blocked out its full timeout. Now the
                # crash is recorded (self.crashed), traced, and either
                # handed to the supervisor (which requeues the in-flight
                # work onto a rebuilt engine) or failed fast
                self._crash(e)
                return
            # single-writer int bump; lock-free readers (the watchdog's
            # warmup-grace check, debug_snapshot) take a GIL-atomic
            # value one iteration stale at worst — the documented
            # diagnostics-read contract
            self.iterations += 1  # graftlint: disable=CC005
            if not stepped:
                # idle pass: decay the rate gauges (iter_end never runs
                # here, and frozen gauges would report the last burst's
                # tokens/s and MFU on an hour-idle engine)
                self.profiler.idle_tick()
                with self._cond:
                    if not self._running:
                        return
                    if not self._queue:
                        self._cond.wait(timeout=0.1)

    # -- crash / fence / degradation surface (inference/supervisor.py) ----
    def _crash(self, exc: BaseException) -> None:
        """Terminal bookkeeping on the dying loop thread. Supervised
        (`_on_crash` set): handles stay OPEN — the supervisor owns them
        now and will requeue each onto the rebuilt engine (their callers
        never see the crash). Unsupervised: fail every in-flight and
        queued handle fast with EngineCrashedError instead of leaving
        the callers to block out their timeouts against a dead loop."""
        if self._fenced:
            return  # already declared dead and disowned; nothing to own
        self.crashed = exc
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self.tracer.enabled:
            self.tracer.instant(
                "engine_crash", track=self._sched_track,
                args={"error": type(exc).__name__,
                      "detail": str(exc)[:200],
                      "iterations": self.iterations})
        if self._on_crash is not None:
            self._close_request_spans()
            # supervised crash: the handles stay open and the supervisor
            # requeues them — this engine's per-request resource debt
            # dies with its pool, so the ledger disowns it (the
            # replacement engine re-acquires under the same request ids
            # from a clean balance)
            for seq in self._slots:  # graftlint: disable=CC004
                if seq is not None:
                    ledger_forget(seq.handle.request_id, _LEDGER_KINDS)
            with self._cond:
                queued = self._queue[:]
            for seq in queued:
                ledger_forget(seq.handle.request_id, _LEDGER_KINDS)
            self._on_crash(exc)
        else:
            self._fail_all_inflight(EngineCrashedError(
                f"decode engine crashed: {type(exc).__name__}: {exc}"))

    def fence(self) -> None:
        """Disown this engine: a supervisor that declared it dead (hung
        heartbeat) fences it BEFORE requeueing its in-flight work onto a
        replacement — if the stuck loop thread ever wakes, it sees the
        fence at its next iteration boundary (and `_consume` refuses to
        touch handles) and exits instead of double-finishing requests
        the new engine now owns. The residual window — a thread awake
        and past the fence checks at the exact fencing instant — is one
        iteration wide; the supervisor additionally joins the thread
        with a grace timeout before resubmitting.

        The fence flag is DELIBERATELY a lock-free GIL-atomic bool: the
        hung loop thread it must reach may be stuck inside an XLA
        dispatch and can never be required to take a lock to learn it
        was disowned; the one-iteration staleness window is the
        documented contract."""
        self._fenced = True  # graftlint: disable=CC005
        with self._cond:
            self._running = False
            self._cond.notify_all()

    def _fail_all_inflight(self, exc: BaseException) -> None:
        """Fail every queued + slot-resident handle (crash path, loop
        thread — the only other `_slots` writer is this thread)."""
        with self._cond:
            pending = self._queue[:]
            self._queue.clear()
            self._m_queue_depth.set(0)
        for seq in pending:
            seq.handle._finish(exc)
            self._trace_done("cancel", seq)
        for i, seq in enumerate(self._slots):  # graftlint: disable=CC004
            if seq is not None:
                if self.pool is not None:
                    self._release_pool(seq)
                    if self.paged:
                        self._release_slot_blocks(i, seq)
                self._release_mask(seq)
                seq.handle._finish(exc)
                self._trace_done("cancel", seq, slot=i)
                self._slots[i] = None
                ledger_note("engine_slot", seq.handle.request_id, -1)
                ledger_check_request(seq.handle.request_id,
                                     _LEDGER_KINDS)
        self._m_active.set(0)

    def _close_request_spans(self) -> None:
        """Close every in-flight request's open phase span WITHOUT
        finishing its handle (supervised crash: the request lives on —
        the supervisor opens a `recovered` span bridging the gap until
        the resubmission's fresh `queued` begins)."""
        if not self.tracer.enabled:
            return
        with self._cond:
            seqs = self._queue[:]
        seqs += [s for s in self._slots if s is not None]  # graftlint: disable=CC004
        for seq in seqs:
            self._close_phase_span(seq)

    def inflight(self) -> int:
        """Queued + slot-resident request count (the drain condition)."""
        with self._cond:
            n = len(self._queue)
        return n + sum(s is not None for s in self._slots)  # graftlint: disable=CC004

    def queue_depth(self) -> int:
        """Waiting (not yet admitted) request count — the degradation
        ladder's pressure signal."""
        with self._cond:
            return len(self._queue)

    def warmup(self, masks: Optional[bool] = None) -> None:
        """Compile every program family up front by invoking each jitted
        callable once per bucket shape and DISCARDING the results (the
        programs are pure; nothing observable changes — no metrics, no
        trace records, no pool state, no slot bookkeeping).

        ``masks``: also warm the GRAMMAR-MASKED program variants
        (masked decode/verify/draft + the mask-upload family). Default
        (None) warms them only when grammars are already resident —
        unconstrained serving must not pay the near-2x warmup of a
        family it never dispatches (supervisor rebuilds run this inside
        the recovery window). A deployment expecting constrained
        traffic warms eagerly with ``warmup(masks=True)``; otherwise
        the first constrained dispatch pays one bounded lazy compile
        per family member, exactly like a cold chunk bucket.

        Why this exists: a rebuilt engine's jit caches start empty, and
        first-call compiles block the scheduler loop mid-iteration —
        exactly the heartbeat stall a tight supervisor watchdog reads
        as a hang. The supervisor warms every engine it spawns INSIDE
        the recovery/drain window it already owns, so post-swap traffic
        runs on hot caches and the watchdog judges only real stalls."""
        params, variables = self._params, self._variables
        # args go through the SAME placement helpers as live dispatch
        # (placement is part of the jit cache key: a warmup that placed
        # differently would compile a parallel family and blow budgets)
        ids = self._dev_array(np.zeros((self.n_slots,), np.int32))
        # all-masked: every slot's state transition is frozen in-program
        # (and paged writes redirect to the scratch page), so even the
        # discarded outputs never held corrupted rows
        live = self._dev_array(np.zeros((self.n_slots,), bool))
        slot0 = self._dev_index(0)
        one = self._dev_index(1)
        if self.paged:
            for nb in self.table_buckets:
                table = self._dev_array(np.full(
                    (self.n_slots, nb), SCRATCH_BLOCK, np.int32))
                self._jstep(params, variables, ids, live, table,
                            self._states)
            # the FULL budgeted prefill family: one program per (chunk
            # bucket, table bucket) pair — live dispatch selects the
            # table bucket from the slot's DEPTH (`_table_for(written +
            # bucket)`), so a multi-chunk prompt's later chunks use
            # wider tables than its first; warming only the depth-0
            # pair would leave those to compile mid-iteration after a
            # swap, when the watchdog no longer extends warmup grace
            for b in self.prefill_buckets:
                for nb in self.table_buckets:
                    table = self._dev_array(np.full(
                        (self.n_slots, nb), SCRATCH_BLOCK, np.int32))
                    self._jprefill(params, variables, slot0,
                                   self._dev_array(np.zeros((b,),
                                                            np.int32)),
                                   one, table, self._states)
            self._jsetpos(self._states, slot0, self._dev_index(0))
            self._jcow(self._states, self._dev_index(SCRATCH_BLOCK),
                       self._dev_index(SCRATCH_BLOCK))
            if self.tier is not None:
                # tier spill/restore: warm with the scratch row, fed
                # back through np.asarray + _dev_array — the EXACT
                # structure/dtypes/placement the live path uses (worker
                # device-get, scheduler upload), so one program each
                scratch = self._dev_index(SCRATCH_BLOCK)
                dev = self._jtier_spill(self._states, scratch)
                rows = {lk: {pk: self._dev_array(np.asarray(a))
                             for pk, a in pks.items()}
                        for lk, pks in dev.items()}
                self._jtier_restore(self._states, scratch, rows)
        else:
            self._jstep(params, variables, ids, live, self._states)
            for b in self.prefill_buckets:
                self._jprefill(params, variables, slot0,
                               self._dev_array(np.zeros((b,),
                                                        np.int32)),
                               one, self._states)
            if self.pool is not None:
                for b in self.restore_buckets:
                    idx = np.full((b,), SCRATCH_BLOCK, np.int32)
                    self._jrestore(self._states, slot0,
                                   self._dev_array(idx),
                                   one, self.pool.storage)
                    # publish donates its storage argument — rebind, or
                    # the pool would be left pointing at consumed
                    # buffers. Writing slot 0's (all-zero, fresh-engine)
                    # rows into unallocated block 0 is harmless: any
                    # future insert() scatters real data over it.
                    self.pool.storage = self._jpublish(
                        self._states, slot0, self._dev_index(0),
                        self._dev_array(np.zeros((b,), np.int32)),
                        self.pool.storage)
        self._jzero(self._states, slot0)
        if masks is None:
            masks = (self.maskpool is not None
                     and self.maskpool.resident_rows() > 0)
        if masks and self._masks is not None:
            # masked-decode family: one program per table bucket, like
            # decode — a constrained request after a supervisor swap
            # must not pay this compile mid-iteration
            mstate0 = self._dev_array(np.zeros((self.n_slots,), np.int32))
            if self.paged:
                for nb in self.table_buckets:
                    table = self._dev_array(np.full(
                        (self.n_slots, nb), SCRATCH_BLOCK, np.int32))
                    self._jstep_m(params, variables, ids, live, table,
                                  mstate0, self._masks, self._states)
            else:
                self._jstep_m(params, variables, ids, live, mstate0,
                              self._masks, self._states)
            if self.maskpool.resident_rows() == 0:
                # upload family (pure writes of zeros = admit-all rows).
                # Guarded: on a warm engine that already holds resident
                # grammar tables, re-zeroing rows [0, bucket) would
                # corrupt them — and those engines compiled the family
                # long ago anyway
                for b in self.mask_buckets:
                    self._masks = self._jmask_upload(
                        self._masks, slot0,
                        self._dev_array(np.zeros(
                            (b, self.vocab_size), np.dtype(self._dtype))))
        if self.speculate:
            # speculation's program family: the multi-token verify (per
            # table bucket in paged mode, like decode), the draft's
            # step/prefill/zero, and both fixpos rollback programs —
            # a rebuilt engine must not pay these compiles under traffic
            ids2 = self._dev_array(
                np.zeros((self.n_slots, self.speculate + 1), np.int32))
            if self.paged:
                for nb in self.table_buckets:
                    table = self._dev_array(np.full(
                        (self.n_slots, nb), SCRATCH_BLOCK, np.int32))
                    self._jverify(params, variables, ids2, live, table,
                                  self._states)
            else:
                self._jverify(params, variables, ids2, live, self._states)
            dp, dv = self._draft_params, self._draft_variables
            self._jdraft_step(dp, dv, ids, live, self._draft_states)
            if masks and self._jverify_m is not None:
                # speculation x grammar composition: the masked verify
                # mirrors verify's table bucketing, the masked draft
                # step is a singleton
                mstate0 = self._dev_array(np.zeros((self.n_slots,),
                                                   np.int32))
                mstate2 = self._dev_array(np.zeros(
                    (self.n_slots, self.speculate + 1), np.int32))
                if self.paged:
                    for nb in self.table_buckets:
                        table = self._dev_array(np.full(
                            (self.n_slots, nb), SCRATCH_BLOCK, np.int32))
                        self._jverify_m(params, variables, ids2, live,
                                        table, mstate2, self._masks,
                                        self._states)
                else:
                    self._jverify_m(params, variables, ids2, live,
                                    mstate2, self._masks, self._states)
                self._jdraft_step_m(dp, dv, ids, live, mstate0,
                                    self._masks, self._draft_states)
            for b in self.prefill_buckets:
                self._jdraft_prefill(
                    dp, dv, slot0,
                    self._dev_array(np.zeros((b,), np.int32)), one,
                    self._draft_states)
            self._jdraft_zero(self._draft_states, slot0)
            posv = self._dev_array(np.zeros((self.n_slots,), np.int32))
            nomask = self._dev_array(np.zeros((self.n_slots,), bool))
            self._jfixpos(self._states, posv, nomask)
            self._jdraft_fixpos(self._draft_states, posv, nomask)
        if self.paged:
            # the bucket loop above traced every decode program through
            # the paged_decode_attention seam, so the kernel variant is
            # compiled (and, in "auto", autotuned) INSIDE the same
            # per-bucket program family — CompileCounter budgets are
            # unchanged and a supervisor rebuild+warmup never pays a
            # kernel compile under traffic. Refresh the engagement gauge
            # now that every bucket has a verdict.
            self.paged_kernel_status()
        if self.profiler.enabled and not self.profiler.costs:
            # a REBUILT engine (supervisor crash recovery / drain swap
            # over the same net) re-ingests the process-wide cached
            # cost table here for free, so post-recovery traffic gets
            # MFU attribution immediately. The FIRST computation is
            # deliberately lazy (first /debug/engine read, bench, or an
            # explicit attribute_costs()) — tracing the whole program
            # family for cost analysis costs seconds on many-bucket
            # paged engines, and warmup's job is keeping the recovery
            # window tight, not paying optional analysis up front.
            from .profiler import cached_program_costs
            cached = cached_program_costs(self)
            if cached:
                self.profiler.ingest_costs(cached)

    def attribute_costs(self) -> None:
        """Lower every program family through the XLA cost model
        (`profiler.program_costs` — the AOT ``.lower()`` path, which
        never touches the jit call caches, so CompileCounter budgets
        are unaffected) and hand the per-invocation FLOPs/bytes table
        to the step-phase profiler. Computed once per (net, engine
        shape) process-wide; rebuilt engines re-ingest the cached table
        at warmup. Called lazily from :meth:`debug_snapshot`, eagerly
        by the bench and anyone who wants MFU before the first debug
        read. Best-effort: a backend without a cost model just leaves
        MFU at 0, it never breaks serving."""
        if not self.profiler.enabled:
            return
        with self._attr_lock:  # one tracer; losers reuse its table
            if self.profiler.costs or self._attr_failed:
                return
            try:
                self.profiler.ingest_costs(program_costs(self))
            except Exception as e:
                # memoized: /debug/engine is a POLL endpoint, and
                # re-tracing the whole family per poll only to fail
                # again would cost seconds of CPU forever
                self._attr_failed = True
                if self.tracer.enabled:
                    self.tracer.instant(
                        "cost_attribution_skipped",
                        track=self._sched_track,
                        args={"error": type(e).__name__,
                              "detail": str(e)[:200]})

    def paged_kernel_status(self) -> dict:
        """Fused-decode-kernel engagement view (ISSUE 15): the mode
        knob, whether ANY decode table bucket traced through the Pallas
        kernel, and the per-bucket verdict — the kernel's grid variant
        where it engaged, False where the trace fell back to XLA, None
        for buckets not traced yet (warmup() traces every bucket, so a
        warmed engine never shows None). Read-side only: consults the
        ops/pallas_kernels trace-time engagement registry, never
        triggers a compile or a probe."""
        out = {"mode": self.paged_kernel, "engaged": False,
               "buckets": {}}
        if not self.paged:
            return out
        from ..ops import helpers as ophelpers
        if (self.paged_kernel == "off"
                or ophelpers.get_helper("paged_decode_attention") is None):
            out["buckets"] = {nb: False for nb in self.table_buckets}
            return out
        from ..ops.pallas_kernels import paged_decode_decisions
        dec = paged_decode_decisions()
        # match THIS engine's traces exactly: batch/table/block dims,
        # the per-shard head geometry of its own attention layers,
        # compute dtype, int8-ness, AND its mode — the registry is
        # process-global, and a co-resident engine over different
        # shapes or another mode must not color these verdicts
        dt = jnp.dtype(self._dtype).name
        quant = self.kv_dtype == "int8"
        heads = set()
        for _, impl in self._impl_items():
            if type(impl).__name__ == "SelfAttentionLayerImpl":
                H = int(impl.conf.n_heads)
                heads.add((impl._kv_heads() // self.tp, H // self.tp,
                           int(impl.conf.n_out) // H))
        for nb in self.table_buckets:
            hits = [v for k, v in dec.items()
                    if k[0] == self.n_slots and k[1] == nb
                    and k[2] == self.kv_block and k[3:6] in heads
                    and k[6] == dt and k[7] == quant
                    and k[8] == self.paged_kernel]
            engaged = [v for v in hits if v]
            out["buckets"][nb] = (engaged[0] if engaged
                                  else (False if hits else None))
        out["engaged"] = any(bool(v) for v in out["buckets"].values())
        if getattr(self, "_m_paged_kernel", None) is not None:
            self._m_paged_kernel.set(1 if out["engaged"] else 0)
        return out

    def debug_snapshot(self) -> dict:
        """`GET /debug/engine`: one JSON view of the engine's live
        anatomy — slot table, queue, block-pool occupancy + trie stats,
        compile-cache census, speculative acceptance, mesh topology,
        per-family program costs and the rolling MFU/tokens-per-second
        estimates, and the step-phase decomposition.

        Read-side contract: called from HTTP handler threads against
        scheduler-thread-owned state, every read is a GIL-atomic
        ref/scalar load and the view is tolerant of being one iteration
        stale (the same discipline as `inflight()` and the supervisor's
        `status()`); the pool's trie walk is guarded because the
        scheduler may grow the trie mid-iteration."""
        slots = []
        for i, seq in enumerate(list(self._slots)):  # graftlint: disable=CC004,CC005
            if seq is None:
                slots.append(None)
                continue
            h = seq.handle
            slots.append({
                "slot": i, "request_id": h.request_id,
                "phase": seq.phase,
                "prompt_tokens": len(seq.prompt),
                "fed": seq.fed, "written": seq.written,
                "tokens_out": len(h.tokens),
                "max_new_tokens": h.max_new_tokens,
                "blocks": len(seq.block_ids),
                "resumed": seq.resumed,
            })
        out = {
            "n_slots": self.n_slots,
            "paged": self.paged,
            "iterations": self.iterations,
            "queue_depth": self.queue_depth(),
            "slots": slots,
            "compile_cache": self._compile_counter.counts(),
            "mesh": {"tp": self.tp},
            "chunk_cap": self.chunk_cap,
        }
        if self.maskpool is not None:
            out["grammar_masks"] = self.maskpool.stats()
        if self.paged:
            # fused-kernel plane (ISSUE 15): mode, per-bucket fused-vs-
            # XLA verdicts, and the paged family's autotune decisions
            pk = self.paged_kernel_status()
            try:
                from ..ops.pallas_kernels import autotune_decisions
                pk["autotune"] = {
                    "/".join(map(str, k[1:])): v
                    for k, v in autotune_decisions().items()
                    if k[0] == "paged_decode"}
            except Exception:
                pk["autotune"] = {}
            out["paged_kernel"] = pk
        if self.pool is not None:
            try:
                out["pool"] = self.pool.stats()
            except RuntimeError:
                # trie mutated mid-walk (dict changed size): a refresh
                # one poll later sees a settled view
                out["pool"] = {"error": "pool busy, retry"}
        if self.tier is not None:
            out["tier"] = self.tier.stats()
        if self.speculate:
            out["speculative"] = {
                "gamma": self.speculate,
                "draft_blocks": self.draft_blocks,
                "proposed": self._m_spec_proposed.value,
                "accepted": self._m_spec_accepted.value,
            }
        self.attribute_costs()  # lazy for never-warmed engines
        if self.profiler.enabled:
            out["costs"] = self.profiler.cost_snapshot()
            out["phases"] = self.profiler.decomposition()
        return out

    def shed_queued(self, target_depth: int) -> int:
        """Degradation ladder level >= 1: drop queued (never admitted)
        requests until at most ``target_depth`` wait, lowest priority
        first, newest first within a priority — each failed with
        LoadSheddedError (HTTP 503, retryable). Returns how many were
        shed."""
        shed: List[_ActiveSeq] = []
        with self._cond:
            excess = len(self._queue) - max(0, int(target_depth))
            if excess > 0:
                # sort (priority asc, submit time desc): victims first
                order = sorted(
                    self._queue,
                    key=lambda s: (s.handle.priority,
                                   -s.handle.t_submit))[:excess]
                doomed = set(map(id, order))
                self._queue[:] = [s for s in self._queue
                                  if id(s) not in doomed]
                shed = order
                self._m_queue_depth.set(len(self._queue))
        for seq in shed:
            self._m_rejected.inc()
            seq.handle._finish(LoadSheddedError(
                "request shed by the degradation ladder (queue under "
                "sustained pressure); retry with backoff"))
            self._trace_done("cancel", seq)
        return len(shed)
