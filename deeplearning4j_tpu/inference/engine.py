"""Slot-based continuous-batching decode scheduler for generative LMs.

`models/sampling.generate_transformer` decodes ONE sequence at a time: a
serving host running it back-to-back leaves (slots-1)/slots of every decode
step's batch dimension empty. This engine is the Orca-style iteration-level
scheduler (continuous batching) over the existing attention KV cache:

  - a fixed number of decode *slots* (the batch dimension of one shared,
    per-layer KV cache / recurrent state pytree);
  - each engine step runs ALL slots through ONE jitted single-token
    forward — int32 token ids in (the one-hot is built on device inside
    the program, so per-step host->device traffic is n_slots ints, not a
    dense [n_slots, 1, vocab] float batch), next-token distributions out.
    The XLA program is compiled exactly once and never recompiles as
    sequences come and go;
  - new sequences are admitted into free slots *between* steps (their
    slot's state rows are zeroed and, for attention layers, the per-slot
    cache position — `nn/layers/attention.py` vector-``pos`` plumbing —
    restarts at 0; stale K/V beyond a row's own position is causally
    masked, so slot reuse needs no cache wipe to be correct);
  - finished sequences (max tokens or EOS) are evicted the step they
    finish, freeing the slot for the next queued request.

Chunked prefill (the ISSUE 2 tentpole): prompts no longer prefill
token-by-token. A second family of jitted programs — one per power-of-two
chunk bucket (16/32/64/... up to ``prefill_chunk``, reusing the batcher's
bucket helper) — runs C prompt tokens through the net in ONE forward for a
single slot: the slot's state rows are sliced out of the shared pytree,
the chunk writes K/V rows ``[pos, pos+C)`` in one offset
`dynamic_update_slice` (RoPE phases from the slot's absolute positions,
causal masking within the chunk), and the rows are scattered back. Nets
with recurrent h/c state (LSTM/GRU facades) prefill through an equivalent
`lax.scan` chunk program — C single-token steps fused into one device
dispatch, padded steps masked out of the state carry. Time-to-first-token
drops from O(prompt_len) to O(prompt_len / C) engine steps.

Scheduling is Sarathi-style: each iteration runs AT MOST ONE bounded
prefill chunk alongside the regular all-slots decode step, so decode
latency for resident sequences stays protected while admitted prompts
still prefill C tokens per iteration. Slots that are mid-prefill (or idle)
are masked out of the decode step *inside* the jitted program — their
recurrent state and cache position are frozen by a `live` mask, so the
shared-batch step cannot corrupt a half-prefilled slot.

Prefix KV reuse (the ISSUE 4 tentpole, `inference/kvpool.py`): with
``prefix_cache_mb > 0`` the engine keeps a block pool + radix-trie prefix
index over completed prompts' prefill-written K/V. Admission walks the
trie over the prompt's full ``kv_block``-sized blocks, restores the
longest cached prefix into the slot's contiguous cache rows with ONE
jitted block-gather program (bucketed by chain length, same pow2 compile
discipline as prefill) and advances ``pos`` past the hit — chunked
prefill then only runs the cold suffix, so a repeated prompt reaches its
first token in ~1 engine step instead of O(prompt/C). When a sequence
finishes, its prompt's full blocks are published back into the pool
(copy out of the slot cache, functional scatter into pool storage) and
indexed; cached keys are stored pre-rotated at absolute positions, so a
pos-0-anchored prefix is bit-identical across requests.

Token selection reuses `models/sampling.sample_logits`, so greedy engine
output is token-identical to solo `generate_transformer(use_cache=True)`
decoding (tested, chunked and token-by-token, prefix-restored and cold),
and seeded sampled output matches too (same per-sequence RNG consumption
order).

Works for both facades: transformer ComputationGraphs (KV-cache states)
and recurrent MultiLayerNetworks (h/c states — admitting a sequence zeroes
its slot's rows).
"""
from __future__ import annotations

import contextlib
import functools
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.runtime import CompileCounter, device_index, host_read
from ..models.sampling import sample_logits
from ..nn.layers.recurrent import (BaseRecurrentImpl,
                                   _materialize_rnn_states)
from ..nn.multilayer import _compute_dtype_of
from .batcher import QueueFullError, pow2_buckets
from .kvpool import SCRATCH_BLOCK, KVPool, gather_blocks, scatter_blocks
from .metrics import MetricsRegistry, default_registry
from .trace import FlightRecorder, default_recorder, new_request_id

# chunk buckets never go below this (a 3-token tail still pads to one
# small program instead of compiling a 3-wide one-off); buckets smaller
# than 16 only exist when prefill_chunk itself is smaller
_MIN_CHUNK_BUCKET = 16


class PromptTooLongError(ValueError):
    """The request cannot fit the KV cache: ``len(prompt) +
    max_new_tokens - 1 > max_cache_len``. Raised at submit time (never
    admitted, never queued) so the serving layer can answer HTTP 413
    instead of the sequence dying mid-decode on the attention layer's
    cache-overflow guard."""


class DecodeHandle:
    """Completion handle for one submitted generation request."""

    def __init__(self, prompt_len: int, max_new_tokens: int,
                 request_id: Optional[str] = None):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.request_id = request_id or new_request_id()
        self.tokens: List[int] = []
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        # lifecycle timestamps stamped by the scheduler thread: the
        # request's wall time splits into four CONTIGUOUS phases —
        # queued [submit, admitted], restore [admitted, restored] (slot
        # reset + prefix-cache restore), prefill [restored, first token],
        # decode [first token, done] — so the `timings()` breakdown sums
        # to the end-to-end latency by construction
        self.t_admitted: Optional[float] = None
        self.t_restored: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None
        # engine iterations this sequence was stepped before its first
        # token (the bench's TTFT-in-steps: prompt_len token-by-token,
        # ceil(prompt_len / chunk) chunked)
        self.steps_to_first_token: Optional[int] = None

    def timings(self) -> Dict[str, float]:
        """Per-phase wall-time breakdown (ms). Phases are contiguous
        segments of [t_submit, t_done], so ``queue_ms + restore_ms +
        prefill_ms + decode_ms == total_ms`` (a request cancelled before
        a boundary reports 0 for the phases it never reached)."""
        end = self.t_done if self.t_done is not None else time.monotonic()
        admitted = self.t_admitted if self.t_admitted is not None else end
        restored = self.t_restored if self.t_restored is not None \
            else admitted
        first = self.t_first_token if self.t_first_token is not None else end
        first = max(first, restored)
        return {
            "queue_ms": round((admitted - self.t_submit) * 1e3, 3),
            "restore_ms": round((restored - admitted) * 1e3, 3),
            "prefill_ms": round((first - restored) * 1e3, 3),
            "decode_ms": round((end - first) * 1e3, 3),
            "total_ms": round((end - self.t_submit) * 1e3, 3),
        }

    def _finish(self, err: Optional[BaseException] = None) -> None:
        self._error = err
        self.t_done = time.monotonic()
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> None:
        """Ask the scheduler to evict this sequence at its next step.

        Without this, a caller that times out waiting on `result()` leaks
        its slot: the sequence keeps decoding to max_new_tokens with
        nobody reading the answer. Cancellation is asynchronous — the
        scheduler thread frees the slot, counts `decode_cancelled_total`,
        and marks the handle done (with whatever tokens were produced).
        Cancelling a finished handle is a no-op."""
        self._cancel.set()

    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("generation not finished")
        if self._error is not None:
            raise self._error
        return self.tokens


class _ActiveSeq:
    """Book-keeping for one slot-resident sequence."""
    __slots__ = ("handle", "prompt", "fed", "rng", "temperature", "top_k",
                 "top_p", "eos_id", "steps", "pool_node")

    def __init__(self, handle: DecodeHandle, prompt: Sequence[int],
                 temperature: float, top_k: Optional[int],
                 top_p: Optional[float], seed: int, eos_id: Optional[int]):
        self.handle = handle
        self.prompt = [int(t) for t in prompt]
        self.fed = 0  # prompt tokens fed so far
        self.rng = np.random.default_rng(seed)
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.steps = 0  # engine iterations that advanced this sequence
        self.pool_node = None  # locked trie node of the restored prefix

    def next_input(self) -> int:
        """Token to feed this step: the next prompt token while prefilling,
        else the last generated token."""
        if self.fed < len(self.prompt):
            return self.prompt[self.fed]
        return self.handle.tokens[-1]

    @property
    def sampling(self) -> bool:
        """Past the last prompt token, every step's output is sampled."""
        return self.fed >= len(self.prompt)


class DecodeScheduler:
    """Continuous-batching decode over a shared model and KV cache.

    ``net``: a trained ComputationGraph (e.g. `models/zoo.transformer_lm`,
    causal attention) or recurrent MultiLayerNetwork whose output is a
    next-token distribution. The engine owns a private state pytree — it
    never touches ``net._rnn_state``, so callers may keep using the net's
    own streaming API concurrently (single-threaded model access is still
    required; the engine's step thread is that single thread while
    running).

    ``prefill_chunk``: max prompt tokens per prefill program (the TTFT /
    decode-latency knob — bigger chunks reach the first token in fewer
    iterations but each chunked iteration holds the device longer, adding
    tail latency to resident decodes). <= 1 disables chunked prefill and
    restores token-by-token prompt feeding through the decode step.

    ``prefix_cache_mb``: byte budget (MiB) for the prefix KV pool
    (`inference/kvpool.py`); 0 disables prefix reuse. ``kv_block``:
    positions per pool block — only full blocks of a prompt are shared,
    so smaller blocks match more but cost more metadata. The pool only
    engages for attention nets (pos-0-anchored KV prefixes; recurrent
    h/c state has no position-addressed rows to share).

    ``tracer``: span flight recorder (`inference/trace.py`, default the
    process-wide one). Every request's lifecycle is recorded — queued /
    prefix_restore / prefill (per-chunk spans on the slot track) /
    decode / finish-or-cancel, plus slot occupancy, compile, and
    pool-eviction instants — as O(1) lock-free ring appends, cheap
    enough to stay on in production. `GET /trace` on the serving server
    and `DecodeHandle.timings()` read it back.

    ``transfer_guard``: device-residency audit mode. When set (e.g.
    "disallow"), every scheduler iteration runs under that thread-local
    ``jax.transfer_guard`` level: any *implicit* host<->device transfer in
    the hot loop raises, proving the loop only crosses the boundary at its
    declared points — `analysis.runtime.host_read` for the sampled-token
    readback, `device_index`/`jnp.asarray`-of-ndarray for the token feed.
    The tier-1 residency tests run the engine this way permanently.
    """

    def __init__(self, net, vocab_size: int, *, n_slots: int = 4,
                 max_queue: int = 64, prefill_chunk: int = 64,
                 prefix_cache_mb: float = 0.0, kv_block: int = 16,
                 metrics: Optional[MetricsRegistry] = None,
                 tracer: Optional[FlightRecorder] = None,
                 transfer_guard: Optional[str] = None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.net = net
        self.vocab_size = int(vocab_size)
        self.n_slots = int(n_slots)
        self.max_queue = int(max_queue)
        self.prefill_chunk = int(prefill_chunk)
        self.metrics = metrics if metrics is not None else default_registry()
        # span flight recorder (trace.py): every request's lifecycle is
        # recorded as spans/instants — O(1) lock-free ring appends, cheap
        # enough to default ON (the process-wide recorder). Tracks are
        # scoped per scheduler instance: a second scheduler sharing this
        # recorder must not interleave same-name spans on "scheduler"/
        # "slot N" tracks (the export pairs B/E LIFO per track)
        self.tracer = tracer if tracer is not None else default_recorder()
        sfx = self.tracer.track_scope("engine")
        self._sched_track = "scheduler" + sfx
        self._slot_tracks = [f"slot {i}{sfx}" for i in range(self.n_slots)]
        self._graph = hasattr(net.conf, "vertices")  # facade detection
        self._dtype = _compute_dtype_of(net.conf.conf)
        self._cache_cap = self._min_cache_len()
        self._states = self._init_states()
        self._slots: List[Optional[_ActiveSeq]] = [None] * self.n_slots
        self._queue: List[_ActiveSeq] = []
        self._cond = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._transfer_guard = transfer_guard
        self._jstep = jax.jit(self._step_fn)
        # one prefill program per pow2 chunk bucket (the SAME jitted
        # callable; each distinct ids length C is its own XLA program,
        # compiled once and reused across requests — the batcher's
        # compile-once-per-bucket discipline applied to prefill).
        # n_real is data-dependent (real tokens in a padded chunk) and
        # MUST stay traced: static it would recompile per tail length,
        # defeating the bucket discipline.
        self._jprefill = jax.jit(self._prefill_fn)  # graftlint: disable=JG004
        # slot admission zeroes one slot's rows in ONE fused program
        # (eagerly tree-mapped .at[].set(0) dispatched per leaf AND fed
        # the slot index as an implicit scalar transfer per leaf)
        self._jzero = jax.jit(self._zero_fn)
        if self.prefill_chunk > 1:
            lo = min(_MIN_CHUNK_BUCKET, self.prefill_chunk)
            self.prefill_buckets = [b for b in pow2_buckets(self.prefill_chunk)
                                    if b >= lo]
        else:
            self.prefill_buckets = []
        # dense chunk path needs every stateful layer to take a multi-token
        # inference step (true of the attention KV cache: offset
        # dynamic_update_slice writes + in-chunk causal mask). Recurrent
        # h/c state steps one token at a time, so those nets prefill
        # through the lax.scan chunk program instead.
        stateful = [impl for _, impl in self._impl_items()
                    if isinstance(impl, BaseRecurrentImpl)]
        self._chunk_dense = bool(stateful) and all(
            type(impl).__name__ == "SelfAttentionLayerImpl"
            for impl in stateful)
        # prefix KV reuse (kvpool.py): attention nets only — cached
        # prefixes are position-addressed K/V rows anchored at pos 0,
        # which recurrent h/c state does not have
        self.kv_block = int(kv_block)
        self.pool: Optional[KVPool] = None
        self.restore_buckets: List[int] = []
        self._jrestore = None
        self._jpublish = None
        if (prefix_cache_mb and prefix_cache_mb > 0 and self._chunk_dense
                and self._cache_cap is not None
                and self.kv_block >= 1
                and self._cache_cap >= self.kv_block):
            attn = {key: st for key, st in self._states.items()
                    if isinstance(st, dict) and "k" in st and "v" in st
                    and "pos" in st}
            pool = KVPool(attn, block=self.kv_block,
                          budget_bytes=int(prefix_cache_mb * (1 << 20)),
                          metrics=self.metrics, tracer=self.tracer)
            if attn and pool.capacity_blocks > 0:
                self.pool = pool
                # one restore/publish program per pow2 block-chain bucket;
                # every bucket satisfies bucket*kv_block <= cache capacity,
                # so the fused row write always fits the slot's cache
                self.restore_buckets = pow2_buckets(
                    self._cache_cap // self.kv_block)
                self._jrestore = jax.jit(functools.partial(
                    gather_blocks, block=self.kv_block))
                # storage is donated: publish updates the pool in place
                # instead of re-materializing the whole budget's worth of
                # arrays per call; the caller rebinds pool.storage to the
                # result immediately, so the consumed buffers are never
                # touched again
                self._jpublish = jax.jit(functools.partial(
                    scatter_blocks, block=self.kv_block),
                    donate_argnums=(4,))
        if prefix_cache_mb and prefix_cache_mb > 0 and self.pool is None:
            # the knob was set but the pool could not engage — without
            # this the operator sees a phantom cache (banner/flags say
            # on, every prompt still pays full prefill, no prefix_*
            # instruments in /metrics)
            warnings.warn(
                f"prefix_cache_mb={prefix_cache_mb} requested but the "
                "prefix KV pool is DISABLED: "
                + ("the model has no attention KV cache to share"
                   if not self._chunk_dense or self._cache_cap is None
                   else f"kv_block={kv_block} exceeds "
                        f"max_cache_len={self._cache_cap}"
                   if self._cache_cap < max(self.kv_block, 1)
                   else "the byte budget is smaller than two "
                        f"{self.kv_block}-position blocks"),
                RuntimeWarning, stacklevel=2)
        self._prefill_next = 0  # round-robin over prefilling slots
        self._emitted_this_iter = 0  # scheduler-thread-only tally
        m = self.metrics
        self._m_queue_depth = m.gauge("decode_queue_depth")
        self._m_active = m.gauge("decode_active_slots")
        self._m_occupancy = m.histogram("decode_slot_occupancy", lo=1.0,
                                        hi=float(self.n_slots) + 1,
                                        per_decade=12)
        self._m_tokens = m.counter("decode_tokens_total")
        self._m_seqs = m.counter("decode_sequences_total")
        self._m_rejected = m.counter("decode_rejected_total")
        self._m_cancelled = m.counter("decode_cancelled_total")
        self._m_latency = m.histogram("decode_seq_latency_sec")
        self._m_ttft = m.histogram("decode_time_to_first_token_sec")
        self._m_step_time = m.histogram("decode_step_time_sec")
        self._m_prefill_tokens = m.counter("prefill_tokens_total")
        self._m_prefill_chunk = m.histogram(
            "prefill_chunk_size", lo=1.0,
            hi=float(max(self.prefill_buckets or [1])) + 1, per_decade=12)
        if self.pool is not None:
            self._m_prefix_lookups = m.counter("prefix_cache_lookups_total")
            self._m_prefix_hits = m.counter("prefix_cache_hits_total")
            self._m_prefix_lookup_tokens = m.counter(
                "prefix_cache_lookup_tokens_total")
            self._m_prefix_hit_tokens = m.counter(
                "prefix_cache_hit_tokens_total")
            m.ratio("prefix_cache_hit_rate", self._m_prefix_hit_tokens,
                    self._m_prefix_lookup_tokens)
        # compile-event tracing: the scheduler polls its own program
        # families' jit-cache sizes (the same CompileCounter budgets the
        # tests assert) once per iteration and stamps an instant event
        # whenever one grew — a chunk bucket's first-call compile shows
        # up ON the trace timeline, right where the stall happened
        self._compile_counter = CompileCounter.for_scheduler(self)
        self._compile_seen: Dict[str, int] = {}

    # -- model plumbing ----------------------------------------------------
    def _impl_items(self):
        impls = self.net._impls
        return impls.items() if isinstance(impls, dict) else enumerate(impls)

    def _min_cache_len(self) -> Optional[int]:
        caps = []
        for _, impl in self._impl_items():
            if type(impl).__name__ == "SelfAttentionLayerImpl":
                caps.append(int(getattr(impl.conf, "max_cache_len", 1024)))
        return min(caps) if caps else None

    def _init_states(self) -> Dict[Any, Any]:
        """Private per-layer state with batch dim = n_slots; attention
        cache positions become [n_slots] vectors so each slot decodes at
        its own depth."""
        states = _materialize_rnn_states(self._impl_items(), {},
                                         self.n_slots, self._dtype)
        for key, st in states.items():
            if isinstance(st, dict) and "pos" in st and st["pos"].ndim == 0:
                states[key] = {**st,
                               "pos": jnp.zeros((self.n_slots,), jnp.int32)}
        return states

    def _forward(self, params, variables, x, states):
        """One forward of [B, T, vocab] one-hots through the net with
        explicit states: ([B, T, vocab] distributions, new states)."""
        if self._graph:
            acts, _, new_states = self.net._forward_impl(
                params, variables, [x], train=False, rng=None, states=states)
            out = acts[self.net.conf.network_outputs[0]]
        else:
            acts, _, new_states = self.net._forward_impl(
                params, variables, x, train=False, rng=None, states=states)
            out = acts[-1]
        return out, new_states

    def _freeze_states(self, new_states, old_states, live):
        """Keep only live slots' state transitions: masked rows (idle or
        mid-chunked-prefill slots stepped as padding of the shared batch)
        retain their previous recurrent state and cache position. K/V
        buffers are exempt — a masked slot's write lands at its own frozen
        `pos` row, which is overwritten by the slot's next real write (its
        next prefill chunk starts at `pos`) and causally invisible until
        then, so freezing the (large) cache buffers would be pure cost."""
        def sel(n, o):
            m = live.reshape((self.n_slots,) + (1,) * (n.ndim - 1))
            return jnp.where(m, n, o)
        out = {}
        for key, st in new_states.items():
            old = old_states[key]
            if isinstance(st, dict):
                out[key] = {k: (v if k in ("k", "v") else sel(v, old[k]))
                            for k, v in st.items()}
            else:
                out[key] = sel(st, old)
        return out

    def _step_fn(self, params, variables, ids, live, states):
        """One single-token forward for all slots. ``ids``: [n_slots]
        int32 token ids (the one-hot is built HERE, on device — the host
        ships vocab-fold less data per step); ``live``: [n_slots] bool,
        False rows are batch padding whose state must not advance.
        Returns ([n_slots, vocab] next-token distributions, new states)."""
        x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)[:, None]
        out, new_states = self._forward(params, variables, x, states)
        return out[:, -1, :], self._freeze_states(new_states, states, live)

    # -- chunked prefill programs ------------------------------------------
    def _slice_slot(self, states, slot):
        """One slot's rows of every state leaf, batch dim kept at 1."""
        def f(a):
            if hasattr(a, "ndim") and a.ndim >= 1 \
                    and a.shape[0] == self.n_slots:
                return jax.lax.dynamic_slice_in_dim(a, slot, 1, axis=0)
            return a
        return jax.tree_util.tree_map(f, states)

    def _scatter_slot(self, states, sub, slot):
        """Write a batch-1 state pytree back into one slot's rows."""
        def f(full, part):
            if hasattr(full, "ndim") and full.ndim >= 1 \
                    and full.shape[0] == self.n_slots:
                return jax.lax.dynamic_update_slice_in_dim(
                    full, part, slot, axis=0)
            return part
        return jax.tree_util.tree_map(f, states, sub)

    def _prefill_fn(self, params, variables, slot, ids, n_real, states):
        """Prefill one chunk of ``ids`` (int32 [C], padded past ``n_real``)
        into ``slot``'s state, in ONE device dispatch. Returns the
        next-token distribution at the last REAL prompt token (only
        meaningful for the prompt's final chunk) and the updated shared
        states. Compiled once per chunk length C (the pow2 buckets).

        Dense path (attention nets): a single [1, C, vocab] forward —
        `nn/layers/attention.py` writes K/V rows [pos, pos+C) in one
        offset `dynamic_update_slice`, rotates RoPE at the slot's absolute
        positions, and masks causally within the chunk. Padded tail rows
        beyond n_real land at positions the corrected `pos` keeps causally
        invisible until the next real write overwrites them; `pos` itself
        advances by n_real, not C.

        Scan path (recurrent h/c state): C single-token steps fused into
        one `lax.scan` program; padded steps keep the carried state (the
        same mask-carry discipline the training scan uses).

        ``slot``/``n_real`` arrive as 1-element int32 arrays, not Python
        scalars: scalar feeds are *implicit* host->device transfers that
        the transfer-guard audit mode would reject every iteration."""
        slot = slot[0]
        n_real = n_real[0]
        sub = self._slice_slot(states, slot)
        if self._chunk_dense:
            x = jax.nn.one_hot(ids, self.vocab_size, dtype=self._dtype)[None]
            out, new_sub = self._forward(params, variables, x, sub)
            probs = jax.lax.dynamic_index_in_dim(out, n_real - 1, axis=1,
                                                 keepdims=False)[0]
            fixed = {}
            for key, st in new_sub.items():
                if isinstance(st, dict) and "pos" in st:
                    # the layer advanced pos by the PADDED chunk length;
                    # the sequence is only n_real tokens deeper. But keep
                    # the layer's L_cap+1 overflow-freeze sentinel (ADVICE
                    # r3): a chunk that overran the cache must stay
                    # poisoned, not resume over a corrupted cache
                    pos = sub[key]["pos"] + n_real
                    if "k" in st:
                        cap = st["k"].shape[1]
                        pos = jnp.where(st["pos"] > cap, st["pos"], pos)
                    fixed[key] = {**st, "pos": pos}
                else:
                    fixed[key] = st
            new_sub = fixed
        else:
            keep = jnp.arange(ids.shape[0], dtype=jnp.int32) < n_real

            def body(carry, inp):
                tok, k = inp
                x = jax.nn.one_hot(tok[None, None], self.vocab_size,
                                   dtype=self._dtype)
                out, ns = self._forward(params, variables, x, carry)
                nxt = {}
                for key, st in ns.items():
                    old = carry[key]
                    if isinstance(st, dict):
                        nxt[key] = {k2: jnp.where(k, v2, old[k2])
                                    for k2, v2 in st.items()}
                    else:
                        nxt[key] = jnp.where(k, st, old)
                return nxt, out[0, -1, :]

            new_sub, probs_all = jax.lax.scan(body, sub, (ids, keep))
            probs = probs_all[n_real - 1]
        return probs, self._scatter_slot(states, new_sub, slot)

    def _pick_chunk(self, seq: _ActiveSeq) -> Tuple[int, int]:
        """(bucket, n_real) for this sequence's next prefill chunk, or
        (0, 0) when no bucket fits the KV-cache headroom (the tail then
        prefills token-by-token through the decode step)."""
        remaining = len(seq.prompt) - seq.fed
        n_real = min(remaining, self.prefill_chunk)
        bucket = next(b for b in self.prefill_buckets if b >= n_real)
        if self._cache_cap is not None and \
                seq.fed + bucket > self._cache_cap:
            # padded writes past the cap would trip the layer's overflow
            # guard even though the real tokens fit: shrink to the largest
            # bucket inside the headroom
            fitting = [b for b in self.prefill_buckets
                       if seq.fed + b <= self._cache_cap]
            if not fitting:
                return 0, 0
            bucket = fitting[-1]
            n_real = min(n_real, bucket)
        return bucket, n_real

    def _zero_fn(self, states, slot):
        """Zero one slot's rows across every state leaf (KV rows, cache
        position, LSTM h/c) so an admitted sequence starts clean. Jitted:
        one fused device program per admission instead of one eager
        dispatch per leaf, and no implicit scalar transfers (``slot`` is
        a 1-element int32 array, same contract as `_prefill_fn`)."""
        s = slot[0]

        def zero_row(a):
            if hasattr(a, "ndim") and a.ndim >= 1 and \
                    a.shape[0] == self.n_slots:
                return a.at[s].set(0)
            return a
        return jax.tree_util.tree_map(zero_row, states)

    def _reset_slot_state(self, slot: int) -> None:
        self._states = self._jzero(self._states, device_index(slot))

    # -- prefix KV reuse (kvpool.py) ---------------------------------------
    def _try_restore(self, slot: int, seq: _ActiveSeq) -> None:
        """Walk the prefix trie for the admitted prompt and restore the
        longest cached block chain into the freshly-zeroed slot, advancing
        ``seq.fed``/``pos`` past the hit so chunked prefill only runs the
        cold suffix. The hit is capped one token short of the prompt: the
        LAST prompt token must always run through the model to produce
        the first output token's distribution."""
        B = self.pool.block
        max_hit = (len(seq.prompt) - 1) // B
        self._m_prefix_lookups.inc()
        self._m_prefix_lookup_tokens.inc(len(seq.prompt))
        if max_hit < 1:
            return
        n_blk, ids, node = self.pool.match(seq.prompt, max_hit)
        seq.pool_node = node  # holds one reference until the slot frees
        if not n_blk:
            return
        bucket = next(b for b in self.restore_buckets if b >= n_blk)
        idx = np.full((bucket,), SCRATCH_BLOCK, np.int32)
        idx[:n_blk] = ids
        self._states = self._jrestore(
            self._states, device_index(slot), jnp.asarray(idx),
            device_index(n_blk), self.pool.storage)
        seq.fed = n_blk * B
        self._m_prefix_hits.inc()
        self._m_prefix_hit_tokens.inc(seq.fed)

    def _release_pool(self, seq: _ActiveSeq) -> None:
        """Drop the sequence's prefix-trie reference (every slot-freeing
        path — finish, cancel, stop — must come through here, or the
        matched blocks stay pinned against eviction forever)."""
        if seq.pool_node is not None:
            self.pool.release(seq.pool_node)
            seq.pool_node = None

    def _publish_prompt(self, slot: int, seq: _ActiveSeq) -> None:
        """Index a finished sequence's prompt: insert its full blocks into
        the trie (allocating pool blocks, LRU-evicting unreferenced ones
        when full) and copy the slot's prefill-written cache rows into the
        new storage rows. The missing part is always a contiguous suffix
        of the prompt's block chain, covered by a greedy descending walk
        over the pow2 buckets — so publish compiles the same bounded
        program family as restore."""
        B = self.pool.block
        n_full = len(seq.prompt) // B
        if n_full < 1:
            return
        start, new_ids = self.pool.insert(seq.prompt[:n_full * B])
        off = 0
        while off < len(new_ids):
            b = max(k for k in self.restore_buckets
                    if k <= len(new_ids) - off)
            idx = np.zeros((b,), np.int32)
            idx[:] = new_ids[off:off + b]
            self.pool.storage = self._jpublish(
                self._states, device_index(slot),
                device_index(start + off), jnp.asarray(idx),
                self.pool.storage)
            off += b

    # -- client side -------------------------------------------------------
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int, *,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None, seed: int = 0,
               eos_id: Optional[int] = None,
               request_id: Optional[str] = None) -> DecodeHandle:
        rid = request_id or new_request_id()
        if not len(prompt_ids):
            raise ValueError("prompt_ids must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bad = [int(t) for t in prompt_ids
               if not 0 <= int(t) < self.vocab_size]
        if bad:
            # ids arrive from untrusted JSON (/generate); out-of-range ids
            # would one-hot to silent all-zero rows, decoding confidently
            # from a "no token" input
            raise ValueError(
                f"prompt ids out of range [0, {self.vocab_size}): "
                f"{bad[:5]}")
        if self._cache_cap is not None:
            needed = len(prompt_ids) + max(max_new_tokens - 1, 0)
            if needed > self._cache_cap:
                # rejected up front (HTTP 413 at the serving layer), not
                # admitted to die mid-decode on the attention layer's
                # KV-overflow guard
                self._m_rejected.inc()
                self.tracer.instant("reject", req=rid, args={
                    "request_id": rid, "reason": "prompt_too_long",
                    "needed": needed, "cache": self._cache_cap})
                raise PromptTooLongError(
                    f"prompt ({len(prompt_ids)}) + max_new_tokens "
                    f"({max_new_tokens}) needs a KV cache of {needed} but "
                    f"max_cache_len={self._cache_cap}")
        handle = DecodeHandle(len(prompt_ids), max_new_tokens,
                              request_id=rid)
        seq = _ActiveSeq(handle, prompt_ids, temperature, top_k, top_p,
                         seed, eos_id)
        with self._cond:
            if not self._running:
                raise RuntimeError("scheduler is not running (call start())")
            if len(self._queue) >= self.max_queue:
                self._m_rejected.inc()
                self.tracer.instant("reject", req=rid, args={
                    "request_id": rid, "reason": "queue_full",
                    "waiting": len(self._queue)})
                raise QueueFullError(
                    f"decode queue full ({self.max_queue} waiting)")
            self._queue.append(seq)
            self._m_queue_depth.set(len(self._queue))
            # the request's first span opens while the queue lock is
            # still held — the scheduler needs _cond to pop this seq, so
            # its end("queued") can never be sequenced before this begin
            self.tracer.begin("queued", req=rid,
                              args={"prompt_tokens": len(seq.prompt),
                                    "max_new_tokens": max_new_tokens})
            self._cond.notify()
        return handle

    def generate_handle(self, prompt_ids: Sequence[int],
                        max_new_tokens: int,
                        timeout: Optional[float] = 120.0,
                        **kw) -> DecodeHandle:
        """Blocking submit returning the COMPLETED handle (tokens plus
        the request_id and per-phase `timings()` the serving layer echoes
        back). A timed-out wait CANCELS the request (the slot is
        reclaimed at the scheduler's next step instead of decoding to
        max_new_tokens for a caller that already gave up) — the one
        place this contract lives; `generate` and the HTTP `/generate`
        route both come through here."""
        handle = self.submit(prompt_ids, max_new_tokens, **kw)
        try:
            handle.result(timeout)
        except TimeoutError:
            handle.cancel()
            raise
        return handle

    def generate(self, prompt_ids: Sequence[int], max_new_tokens: int,
                 timeout: Optional[float] = 120.0, **kw) -> List[int]:
        """Blocking submit — drop-in for `generate_transformer` greedy."""
        return self.generate_handle(prompt_ids, max_new_tokens,
                                    timeout=timeout, **kw).tokens

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "DecodeScheduler":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-scheduler")
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._running = False
            pending = self._queue[:]
            self._queue.clear()
            self._cond.notify_all()
        for seq in pending:
            seq.handle._finish(RuntimeError("scheduler stopped"))
            self._trace_done("cancel", seq)
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # safe lock-free: the scheduler thread (the only other _slots
        # writer) has been joined above
        for i, seq in enumerate(self._slots):  # graftlint: disable=CC004
            if seq is not None:
                if self.pool is not None:
                    self._release_pool(seq)
                seq.handle._finish(RuntimeError("scheduler stopped"))
                self._trace_done("cancel", seq, slot=i)
                self._slots[i] = None

    # -- scheduler loop ----------------------------------------------------
    def _trace_done(self, outcome: str, seq: _ActiveSeq,
                    slot: Optional[int] = None) -> None:
        """Terminal trace records for one request: close whichever phase
        span is still open (a slot-resident request always has `prefill`
        or `decode` open; a never-admitted one has `queued`), then stamp
        one ``finish``/``cancel`` instant carrying the handle's full
        timing breakdown — the record `request_summaries` scrapes. Call
        AFTER `handle._finish()` so `timings()` sees t_done."""
        h = seq.handle
        rid = h.request_id
        tr = self.tracer
        if not tr.enabled:
            return
        if h.t_admitted is None:
            tr.end("queued", req=rid)
        elif h.t_first_token is None:
            tr.end("prefill", req=rid, args={"fed_tokens": seq.fed})
        else:
            tr.end("decode", req=rid,
                   args={"tokens": len(h.tokens), "iterations": seq.steps})
        tr.instant(outcome, req=rid,
                   args={"request_id": rid, "tokens": len(h.tokens),
                         **h.timings()})
        if slot is not None:
            tr.instant("free", track=self._slot_tracks[slot],
                       args={"request": rid})

    def _evict_cancelled(self) -> None:
        for i, seq in enumerate(self._slots):
            if seq is not None and seq.handle.cancelled():
                self._m_cancelled.inc()
                if self.pool is not None:
                    # a cancel during prefill still holds the restored
                    # prefix's trie reference — releasing here is what
                    # keeps refcounts leak-free (nothing is published:
                    # the prompt may be half-written)
                    self._release_pool(seq)
                seq.handle._finish()  # partial tokens, caller already left
                self._trace_done("cancel", seq, slot=i)
                self._slots[i] = None

    def _admit(self) -> None:
        admitted: List[Tuple[int, _ActiveSeq]] = []
        tr = self.tracer
        with self._cond:
            for i in range(self.n_slots):
                if self._slots[i] is not None:
                    continue
                while self._queue:
                    seq = self._queue.pop(0)
                    if seq.handle.cancelled():  # gave up while queued
                        self._m_cancelled.inc()
                        seq.handle._finish()
                        self._trace_done("cancel", seq)
                        continue
                    self._slots[i] = seq
                    self._m_seqs.inc()
                    admitted.append((i, seq))
                    break
            self._m_queue_depth.set(len(self._queue))
            self._m_active.set(sum(s is not None for s in self._slots))
        # device work happens OUTSIDE the condvar: the slot-reset and
        # prefix-restore dispatches (and a restore bucket's first-call
        # compile, which can take seconds) must not stall every submit()
        # caller blocked on _cond. _slots/_states/pool are scheduler-
        # thread-only, so no lock is needed past the queue handoff.
        for i, seq in admitted:
            h = seq.handle
            rid = h.request_id
            h.t_admitted = time.monotonic()
            tr.end("queued", req=rid)
            tr.instant("admit", track=self._slot_tracks[i],
                       args={"request": rid})
            tr.begin("prefix_restore", req=rid)
            self._reset_slot_state(i)
            if self.pool is not None:
                self._try_restore(i, seq)
            h.t_restored = time.monotonic()
            tr.end("prefix_restore", req=rid,
                   args={"hit_tokens": seq.fed, "slot": i})
            tr.begin("prefill", req=rid,
                     args={"prompt_tokens": len(seq.prompt),
                           "restored_tokens": seq.fed, "slot": i})

    def _consume(self, slot: int, seq: _ActiveSeq,
                 probs_row: np.ndarray) -> None:
        """Sample one output token from a next-token distribution row;
        finish + evict on max_new_tokens or EOS. Shared by the decode step
        and the final prefill chunk (whose last-real-token distribution
        yields the first output token). Token-count metrics are NOT
        updated here — the loop flushes one batched `inc(n)` per
        iteration instead of taking the counter lock once per token."""
        h = seq.handle
        tok = sample_logits(probs_row, seq.temperature, seq.top_k,
                            seq.rng, seq.top_p)
        h.tokens.append(tok)
        self._emitted_this_iter += 1
        now = time.monotonic()
        if h.t_first_token is None:
            h.t_first_token = now
            h.steps_to_first_token = seq.steps
            self._m_ttft.record(now - h.t_submit)
            # phase boundary on the request track: prompt ingestion is
            # over the moment the first output token exists
            self.tracer.end("prefill", req=h.request_id,
                            args={"steps": seq.steps})
            self.tracer.begin("decode", req=h.request_id)
        if (len(h.tokens) >= h.max_new_tokens
                or (seq.eos_id is not None and tok == seq.eos_id)):
            if self.pool is not None:
                # retain the prompt's prefill-written blocks for the next
                # request sharing this prefix, then drop our own pin
                self._publish_prompt(slot, seq)
                self._release_pool(seq)
            h._finish()
            self._trace_done("finish", seq, slot=slot)
            self._m_latency.record(now - h.t_submit)
            self._slots[slot] = None

    def _run_prefill_chunk(self) -> Optional[int]:
        """At most one bounded prefill chunk per iteration (round-robin
        over prefilling slots). Returns the chunked slot index, or None."""
        if not self.prefill_buckets:
            return None
        for off in range(self.n_slots):
            i = (self._prefill_next + off) % self.n_slots
            seq = self._slots[i]
            if seq is None or seq.fed >= len(seq.prompt):
                continue
            bucket, n_real = self._pick_chunk(seq)
            if not n_real:
                continue  # no cache headroom: token-by-token fallback
            ids = np.zeros((bucket,), np.int32)
            ids[:n_real] = seq.prompt[seq.fed:seq.fed + n_real]
            if self.tracer.enabled:  # keep tracing-off allocation-free
                self.tracer.begin("prefill_chunk",
                                  track=self._slot_tracks[i],
                                  args={"request": seq.handle.request_id,
                                        "bucket": bucket, "tokens": n_real})
            probs, self._states = self._jprefill(
                self.net.params, self.net.variables,
                device_index(i), jnp.asarray(ids),
                device_index(n_real), self._states)
            seq.fed += n_real
            seq.steps += 1
            self._m_prefill_tokens.inc(n_real)
            self._m_prefill_chunk.record(n_real)
            if seq.sampling:  # final chunk: its output is the first token
                self._consume(i, seq, host_read(probs))
            self.tracer.end("prefill_chunk", track=self._slot_tracks[i])
            self._prefill_next = (i + 1) % self.n_slots
            return i
        return None

    def _step_once(self) -> bool:
        """One scheduler iteration (admission + at most one prefill chunk
        + the all-slots decode step). Returns False when it idled.

        Host<->device discipline: the ONLY blocking device reads are the
        two `host_read` calls (next-token distributions — the sampled
        token must reach the host to be fed back); everything else ships
        to device explicitly (`jnp.asarray` of ndarrays, `device_index`).
        Metric counters are flushed once per iteration, not per token."""
        self._evict_cancelled()
        self._admit()
        # single-writer: _slots is mutated only by this scheduler thread
        # once start() returns (submit() touches only _queue, under
        # _cond); stop() joins the thread before its own sweep
        active = [(i, s) for i, s in enumerate(self._slots)  # graftlint: disable=CC004
                  if s is not None]
        if not active:
            return False
        t0 = time.monotonic()
        self._emitted_this_iter = 0
        chunked = self._run_prefill_chunk()
        # decode step: every decode-ready slot, plus token-by-token
        # prefill for slots chunked prefill cannot serve (disabled, or
        # no bucket fits the remaining cache headroom)
        fed: List[Tuple[int, _ActiveSeq]] = []
        for i, seq in active:
            if self._slots[i] is not seq or i == chunked:
                continue  # evicted above / consumed its iteration
            if not seq.sampling and self.prefill_buckets \
                    and self._pick_chunk(seq)[1]:
                continue  # mid-prefill: waits for its chunk turn
            fed.append((i, seq))
        if fed:
            ids = np.zeros((self.n_slots,), np.int32)
            live = np.zeros((self.n_slots,), bool)
            for i, seq in fed:
                ids[i] = seq.next_input()
                live[i] = True
            if self.tracer.enabled:  # keep tracing-off allocation-free
                self.tracer.begin("decode_step", track=self._sched_track,
                                  args={"live_slots": len(fed)})
            probs, new_states = self._jstep(
                self.net.params, self.net.variables, jnp.asarray(ids),
                jnp.asarray(live), self._states)
            self._states = new_states
            probs = host_read(probs)
            for i, seq in fed:
                seq.steps += 1
                was_sampling = seq.sampling
                if seq.fed < len(seq.prompt):
                    seq.fed += 1
                if not was_sampling and not seq.sampling:
                    continue  # still prefilling; output not sampled yet
                self._consume(i, seq, probs[i])
            self.tracer.end("decode_step", track=self._sched_track)
        if self._emitted_this_iter:
            self._m_tokens.inc(self._emitted_this_iter)
        self._m_occupancy.record(len(active))
        self._m_step_time.record(time.monotonic() - t0)
        self._trace_compiles()
        return True

    def _trace_compiles(self) -> None:
        """Instant event per NEW XLA program: the per-family jit-cache
        sizes (CompileCounter, the same counters the recompile-budget
        tests assert) are polled once per iteration; growth means this
        iteration paid a compile — stamped on the timeline so a
        seconds-long TTFT outlier is attributable to the bucket that
        compiled under it."""
        if not self.tracer.enabled:
            return
        for fam, n in self._compile_counter.counts().items():
            if n > self._compile_seen.get(fam, 0):
                self._compile_seen[fam] = n
                self.tracer.instant("compile", track=self._sched_track,
                                    args={"family": fam, "programs": n})

    def _loop(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    return  # stop() fails any still-active handles
            guard = (jax.transfer_guard(self._transfer_guard)
                     if self._transfer_guard else contextlib.nullcontext())
            with guard:
                stepped = self._step_once()
            if not stepped:
                with self._cond:
                    if not self._running:
                        return
                    if not self._queue:
                        self._cond.wait(timeout=0.1)
