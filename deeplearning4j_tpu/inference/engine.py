"""Slot-based continuous-batching decode scheduler for generative LMs.

`models/sampling.generate_transformer` decodes ONE sequence at a time: a
serving host running it back-to-back leaves (slots-1)/slots of every decode
step's batch dimension empty. This engine is the Orca-style iteration-level
scheduler (continuous batching) over the existing attention KV cache:

  - a fixed number of decode *slots* (the batch dimension of one shared,
    per-layer KV cache / recurrent state pytree);
  - each engine step runs ALL slots through ONE jitted single-token
    forward — the XLA program is compiled exactly once, for the
    [n_slots, 1, vocab] shape, and never recompiles as sequences come
    and go;
  - new sequences are admitted into free slots *between* steps (their
    slot's state rows are zeroed and, for attention layers, the per-slot
    cache position — `nn/layers/attention.py` vector-``pos`` plumbing —
    restarts at 0; stale K/V beyond a row's own position is causally
    masked, so slot reuse needs no cache wipe to be correct);
  - finished sequences (max tokens or EOS) are evicted the step they
    finish, freeing the slot for the next queued request.

Prompts are prefilled token-by-token through the same step — prefill and
decode are one program, which is what keeps admission recompile-free. Token
selection reuses `models/sampling.sample_logits`, so greedy engine output
is token-identical to solo `generate_transformer(use_cache=True)` decoding
(tested), and seeded sampled output matches too (same per-sequence RNG
consumption order).

Works for both facades: transformer ComputationGraphs (KV-cache states)
and recurrent MultiLayerNetworks (h/c states — admitting a sequence zeroes
its slot's rows).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.sampling import sample_logits
from ..nn.layers.recurrent import (BaseRecurrentImpl,
                                   _materialize_rnn_states)
from ..nn.multilayer import _compute_dtype_of
from .metrics import MetricsRegistry, default_registry


class DecodeHandle:
    """Completion handle for one submitted generation request."""

    def __init__(self, prompt_len: int, max_new_tokens: int):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.tokens: List[int] = []
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        self.t_done: Optional[float] = None

    def _finish(self, err: Optional[BaseException] = None) -> None:
        self._error = err
        self.t_done = time.monotonic()
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("generation not finished")
        if self._error is not None:
            raise self._error
        return self.tokens


class _ActiveSeq:
    """Book-keeping for one slot-resident sequence."""
    __slots__ = ("handle", "prompt", "fed", "rng", "temperature", "top_k",
                 "top_p", "eos_id")

    def __init__(self, handle: DecodeHandle, prompt: Sequence[int],
                 temperature: float, top_k: Optional[int],
                 top_p: Optional[float], seed: int, eos_id: Optional[int]):
        self.handle = handle
        self.prompt = [int(t) for t in prompt]
        self.fed = 0  # prompt tokens fed so far
        self.rng = np.random.default_rng(seed)
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id

    def next_input(self) -> int:
        """Token to feed this step: the next prompt token while prefilling,
        else the last generated token."""
        if self.fed < len(self.prompt):
            return self.prompt[self.fed]
        return self.handle.tokens[-1]

    @property
    def sampling(self) -> bool:
        """Past the last prompt token, every step's output is sampled."""
        return self.fed >= len(self.prompt)


class DecodeScheduler:
    """Continuous-batching decode over a shared model and KV cache.

    ``net``: a trained ComputationGraph (e.g. `models/zoo.transformer_lm`,
    causal attention) or recurrent MultiLayerNetwork whose output is a
    next-token distribution. The engine owns a private state pytree — it
    never touches ``net._rnn_state``, so callers may keep using the net's
    own streaming API concurrently (single-threaded model access is still
    required; the engine's step thread is that single thread while
    running).
    """

    def __init__(self, net, vocab_size: int, *, n_slots: int = 4,
                 max_queue: int = 64,
                 metrics: Optional[MetricsRegistry] = None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.net = net
        self.vocab_size = int(vocab_size)
        self.n_slots = int(n_slots)
        self.max_queue = int(max_queue)
        self.metrics = metrics if metrics is not None else default_registry()
        self._graph = hasattr(net.conf, "vertices")  # facade detection
        self._dtype = _compute_dtype_of(net.conf.conf)
        self._cache_cap = self._min_cache_len()
        self._states = self._init_states()
        self._slots: List[Optional[_ActiveSeq]] = [None] * self.n_slots
        self._queue: List[_ActiveSeq] = []
        self._cond = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._jstep = jax.jit(self._step_fn)
        m = self.metrics
        self._m_queue_depth = m.gauge("decode_queue_depth")
        self._m_active = m.gauge("decode_active_slots")
        self._m_occupancy = m.histogram("decode_slot_occupancy", lo=1.0,
                                        hi=float(self.n_slots) + 1,
                                        per_decade=12)
        self._m_tokens = m.counter("decode_tokens_total")
        self._m_seqs = m.counter("decode_sequences_total")
        self._m_rejected = m.counter("decode_rejected_total")
        self._m_latency = m.histogram("decode_seq_latency_sec")
        self._m_ttft = m.histogram("decode_time_to_first_token_sec")
        self._m_step_time = m.histogram("decode_step_time_sec")

    # -- model plumbing ----------------------------------------------------
    def _impl_items(self):
        impls = self.net._impls
        return impls.items() if isinstance(impls, dict) else enumerate(impls)

    def _min_cache_len(self) -> Optional[int]:
        caps = []
        for _, impl in self._impl_items():
            if type(impl).__name__ == "SelfAttentionLayerImpl":
                caps.append(int(getattr(impl.conf, "max_cache_len", 1024)))
        return min(caps) if caps else None

    def _init_states(self) -> Dict[Any, Any]:
        """Private per-layer state with batch dim = n_slots; attention
        cache positions become [n_slots] vectors so each slot decodes at
        its own depth."""
        states = _materialize_rnn_states(self._impl_items(), {},
                                         self.n_slots, self._dtype)
        for key, st in states.items():
            if isinstance(st, dict) and "pos" in st and st["pos"].ndim == 0:
                states[key] = {**st,
                               "pos": jnp.zeros((self.n_slots,), jnp.int32)}
        return states

    def _step_fn(self, params, variables, x, states):
        """One single-token forward for all slots: [n_slots, 1, V] one-hot
        in, last-position next-token distribution [n_slots, V] out."""
        if self._graph:
            acts, _, new_states = self.net._forward_impl(
                params, variables, [x], train=False, rng=None, states=states)
            out = acts[self.net.conf.network_outputs[0]]
        else:
            acts, _, new_states = self.net._forward_impl(
                params, variables, x, train=False, rng=None, states=states)
            out = acts[-1]
        return out[:, -1, :], new_states

    def _reset_slot_state(self, slot: int) -> None:
        """Zero one slot's rows across every state leaf (KV rows, cache
        position, LSTM h/c) so an admitted sequence starts clean."""
        def zero_row(a):
            if hasattr(a, "ndim") and a.ndim >= 1 and \
                    a.shape[0] == self.n_slots:
                return a.at[slot].set(0)
            return a
        self._states = jax.tree_util.tree_map(zero_row, self._states)

    def _reset_idle_positions(self, idle: List[int]) -> None:
        """Pin idle slots' cache positions back to 0 (they are stepped with
        zero inputs as part of the batch, so their depth would otherwise
        creep toward the cache cap). Their stale K/V needs no wipe — it is
        zeroed at admission and causally masked until then."""
        if not idle:
            return
        idx = jnp.asarray(idle)
        for key, st in self._states.items():
            if isinstance(st, dict) and "pos" in st and st["pos"].ndim:
                self._states[key] = {**st,
                                     "pos": st["pos"].at[idx].set(0)}

    # -- client side -------------------------------------------------------
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int, *,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None, seed: int = 0,
               eos_id: Optional[int] = None) -> DecodeHandle:
        if not len(prompt_ids):
            raise ValueError("prompt_ids must be non-empty")
        if self._cache_cap is not None:
            needed = len(prompt_ids) + max(max_new_tokens - 1, 0)
            if needed > self._cache_cap:
                raise ValueError(
                    f"prompt ({len(prompt_ids)}) + max_new_tokens "
                    f"({max_new_tokens}) needs a KV cache of {needed} but "
                    f"max_cache_len={self._cache_cap}")
        handle = DecodeHandle(len(prompt_ids), max_new_tokens)
        seq = _ActiveSeq(handle, prompt_ids, temperature, top_k, top_p,
                         seed, eos_id)
        with self._cond:
            if not self._running:
                raise RuntimeError("scheduler is not running (call start())")
            if len(self._queue) >= self.max_queue:
                self._m_rejected.inc()
                raise RuntimeError(
                    f"decode queue full ({self.max_queue} waiting)")
            self._queue.append(seq)
            self._m_queue_depth.set(len(self._queue))
            self._cond.notify()
        return handle

    def generate(self, prompt_ids: Sequence[int], max_new_tokens: int,
                 timeout: Optional[float] = 120.0, **kw) -> List[int]:
        """Blocking submit — drop-in for `generate_transformer` greedy."""
        return self.submit(prompt_ids, max_new_tokens, **kw).result(timeout)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "DecodeScheduler":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-scheduler")
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._running = False
            pending = self._queue[:]
            self._queue.clear()
            self._cond.notify_all()
        for seq in pending:
            seq.handle._finish(RuntimeError("scheduler stopped"))
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        for i, seq in enumerate(self._slots):
            if seq is not None:
                seq.handle._finish(RuntimeError("scheduler stopped"))
                self._slots[i] = None

    # -- scheduler loop ----------------------------------------------------
    def _admit(self) -> None:
        with self._cond:
            for i in range(self.n_slots):
                if self._slots[i] is not None or not self._queue:
                    continue
                seq = self._queue.pop(0)
                self._reset_slot_state(i)
                self._slots[i] = seq
                self._m_seqs.inc()
            self._m_queue_depth.set(len(self._queue))
            self._m_active.set(sum(s is not None for s in self._slots))

    def _loop(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    return  # stop() fails any still-active handles
            self._admit()
            active = [(i, s) for i, s in enumerate(self._slots)
                      if s is not None]
            if not active:
                with self._cond:
                    if not self._running:
                        return
                    if not self._queue:
                        self._cond.wait(timeout=0.1)
                continue
            t0 = time.monotonic()
            x = np.zeros((self.n_slots, 1, self.vocab_size), np.float32)
            for i, seq in active:
                x[i, 0, seq.next_input()] = 1.0
            probs, new_states = self._jstep(self.net.params,
                                            self.net.variables,
                                            jnp.asarray(x), self._states)
            self._states = new_states
            probs = np.asarray(probs)
            self._m_occupancy.record(len(active))
            self._m_step_time.record(time.monotonic() - t0)
            for i, seq in active:
                was_sampling = seq.sampling
                if seq.fed < len(seq.prompt):
                    seq.fed += 1
                if not was_sampling and not seq.sampling:
                    continue  # still prefilling; output not sampled yet
                h = seq.handle
                tok = sample_logits(probs[i], seq.temperature, seq.top_k,
                                    seq.rng, seq.top_p)
                h.tokens.append(tok)
                self._m_tokens.inc()
                now = time.monotonic()
                if h.t_first_token is None:
                    h.t_first_token = now
                    self._m_ttft.record(now - h.t_submit)
                if (len(h.tokens) >= h.max_new_tokens
                        or (seq.eos_id is not None and tok == seq.eos_id)):
                    h._finish()
                    self._m_latency.record(now - h.t_submit)
                    self._slots[i] = None
            # frozen-depth guard: a free slot's position must not keep
            # advancing toward the cache cap while the slot idles
            self._reset_idle_positions(
                [i for i in range(self.n_slots) if self._slots[i] is None])
