"""Logit-processor pipeline: stop sequences, repetition penalties, and
grammar-constrained decoding compiled to device-side token masks.

The decode scheduler (`inference/engine.py`) samples every output token
from a next-token distribution row. This module is the per-request seam
that SHAPES that row before sampling — the piece that turns the engine
into something agents and structured-output clients can sit on
(ROADMAP item 2):

  - :class:`StopMatcher` — multi-token stop sequences matched ACROSS
    token boundaries (an Aho-Corasick automaton over token ids, so a
    stop sequence split over two speculative bursts still matches).
    The matcher also reports how many trailing tokens are a live
    partial match: the streaming layer withholds exactly those tokens,
    so an SSE client never sees half a stop sequence that the next
    token completes.
  - penalty processors (:class:`LogitState.adjust`) — repetition /
    presence / frequency penalties over the GENERATED-token counts,
    applied host-side to the probability row. All multiplicative
    (``p^r`` for seen tokens, ``p·e^-(α·seen+β·count)``), so
    `models/sampling.sample_logits` — which renormalizes — needs no
    second softmax. With no penalty configured the row passes through
    UNTOUCHED (the same object): unconstrained decode stays bitwise
    identical.
  - :class:`CompiledGrammar` — grammar-constrained decoding as a DFA
    over the vocabulary, compiled AHEAD of admission: per-state token
    masks (``allow``) plus a dense transition table. Builders:
    :func:`admit_all` (the identity grammar — one state, everything
    allowed, the token-identity reference), :func:`compile_trie`
    (admit exactly one of a set of token sequences), and
    :func:`compile_json_schema` (a restricted JSON-schema subset
    compiled through a character-level Thompson-NFA → subset-construction
    DFA, then composed with the token→string alphabet so multi-char
    tokens transition through the char automaton in one step).
  - :class:`MaskPool` — host bookkeeping for the engine's DEVICE-side
    mask rows: each resident grammar's per-state mask rows upload once
    into a fixed ``[mask_rows, vocab]`` additive table (0 allowed,
    ``-inf`` forbidden), allocated in pow2-bucket chunks so the upload
    program family stays fixed. Row 0 is reserved all-zeros (the
    admit-all row unconstrained slots gather), refcounted entries are
    cached across requests sharing a grammar, and zero-ref entries are
    LRU-evicted under pressure. A grammar that cannot fit falls back to
    HOST-ONLY masking — always correct (the host applies the exact
    ``allow`` row at sampling), just without the device-side assist
    the speculative draft uses to propose in-grammar.
  - :class:`TokenStream` — the thread-safe per-request event queue SSE
    streaming drains: token events pushed by the scheduler thread as
    they decode (index-deduplicated, so a crash-recovery re-decode —
    token-identical by construction — re-emits without duplicates) and
    one terminal event carrying the final tokens / timings /
    finish_reason.

Composition invariants (test-pinned in tests/test_logitproc.py):

  - an admit-everything grammar is TOKEN-IDENTICAL to unconstrained
    decode (the device mask adds ``0.0`` to every probability — bitwise
    identity — and the host-side ``allow`` row is all-True, which
    `sample_logits` treats as a no-op);
  - masks compose with speculative decoding: the draft proposes under
    the same mask the verify program applies (per-round device mask
    states advanced host-side along the proposed chain), so the
    acceptance rule — and token identity — are untouched;
  - grammar state, penalty counts, and stop matching advance only on
    EMITTED tokens, so preempt-resume (tokens folded into the prompt,
    never re-emitted) and crash recovery (a fresh LogitState re-observes
    the token-identical re-decode) both stay consistent.
"""
from __future__ import annotations

import hashlib
import json
import queue
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["CompiledGrammar", "GrammarError", "LogitState", "MaskPool",
           "StopMatcher", "TokenStream", "admit_all", "compile_json_schema",
           "compile_trie"]

# transition-table sentinel: no edge (the token is forbidden here)
_DEAD = -1

# subset-construction safety valve: a schema whose automaton explodes
# past this many DFA states is refused at COMPILE time (ahead of
# admission), never discovered as an OOM mid-decode
_MAX_DFA_STATES = 4096


class GrammarError(ValueError):
    """The grammar/schema cannot be compiled (unsupported construct, a
    literal character no token can produce, or a state-count blowup).
    Raised at compile time — ahead of admission — so the serving layer
    answers HTTP 400 instead of a request dying mid-decode."""


class CompiledGrammar:
    """A deterministic finite automaton over TOKEN ids.

    ``allow``: bool ``[n_states, vocab]`` — token t may be emitted from
    state s. ``next_state``: int32 ``[n_states, vocab]`` — the state
    after emitting t (``-1`` where forbidden). ``accepting``: bool
    ``[n_states]`` — the output so far is complete here (builders bake
    ``eos_id`` into accepting states' allow rows; a state whose allow
    row is all-False ends the request: the engine finishes it with
    ``finish_reason="grammar"``).

    ``key`` is a stable content hash — the engine's device-mask cache
    key, so two requests carrying equal grammars share one resident
    mask-row range.
    """

    def __init__(self, vocab_size: int, allow: np.ndarray,
                 next_state: np.ndarray, accepting: np.ndarray):
        self.vocab_size = int(vocab_size)
        self.allow = np.ascontiguousarray(allow, dtype=bool)
        self.next_state = np.ascontiguousarray(next_state, dtype=np.int32)
        self.accepting = np.ascontiguousarray(accepting, dtype=bool)
        if self.allow.shape != (self.n_states, self.vocab_size):
            raise ValueError(
                f"allow shape {self.allow.shape} != "
                f"({self.n_states}, {self.vocab_size})")
        if self.next_state.shape != self.allow.shape:
            raise ValueError("next_state/allow shape mismatch")
        self.key = hashlib.sha1(
            self.allow.tobytes() + self.next_state.tobytes()
            + self.accepting.tobytes()).hexdigest()

    @property
    def n_states(self) -> int:
        return self.next_state.shape[0]

    def step(self, state: int, tok: int) -> int:
        """The state after emitting ``tok`` (stays put on a forbidden
        token — the engine never emits one, but a caller replaying a
        foreign token stream must not index row ``-1``)."""
        ns = int(self.next_state[state, tok])
        return ns if ns >= 0 else int(state)

    def allow_row(self, state: int) -> np.ndarray:
        return self.allow[state]

    def live(self, state: int) -> bool:
        """False when no token is admissible from ``state`` — the
        grammar is complete and the request should finish."""
        return bool(self.allow[state].any())

    def mask_table(self, dtype=np.float32) -> np.ndarray:
        """The ADDITIVE device mask: ``0.0`` where allowed, ``-inf``
        where forbidden — added to the model's probability row inside
        the masked decode program. An all-allowed state's row is all
        zeros, so ``p + row == p`` bitwise: the admit-all grammar is
        token-identical to unconstrained decode by construction."""
        table = np.where(self.allow, 0.0, -np.inf)
        return np.ascontiguousarray(table, dtype=dtype)


def admit_all(vocab_size: int) -> CompiledGrammar:
    """The identity grammar: one state, every token allowed, self-loop.
    Its mask row is all zeros — the token-identity reference the bench
    and the constrained-decode tests pin."""
    v = int(vocab_size)
    return CompiledGrammar(
        v, np.ones((1, v), bool), np.zeros((1, v), np.int32),
        np.ones((1,), bool))


def compile_trie(sequences: Sequence[Sequence[int]], vocab_size: int,
                 eos_id: Optional[int] = None) -> CompiledGrammar:
    """Admit exactly one of ``sequences`` (a trie/DFA over the vocab —
    the ISSUE's minimal grammar shape). After a full sequence the state
    is accepting: ``eos_id`` (when given) becomes the only admissible
    token there; without one the allow row goes empty and the engine
    finishes the request."""
    v = int(vocab_size)
    if not sequences:
        raise GrammarError("compile_trie needs at least one sequence")
    if eos_id is not None and not 0 <= int(eos_id) < v:
        # same guard as compile_json_schema: a negative eos_id would
        # silently index from the END of the vocab row
        raise GrammarError(f"eos_id {eos_id} out of range [0, {v})")
    children: List[Dict[int, int]] = [{}]
    terminal = [False]
    for seq in sequences:
        if not len(seq):
            raise GrammarError("empty stop/trie sequence")
        s = 0
        for t in seq:
            t = int(t)
            if not 0 <= t < v:
                raise GrammarError(f"token {t} out of range [0, {v})")
            if t not in children[s]:
                children.append({})
                terminal.append(False)
                children[s][t] = len(children) - 1
            s = children[s][t]
        terminal[s] = True
    n = len(children)
    allow = np.zeros((n, v), bool)
    nxt = np.full((n, v), _DEAD, np.int32)
    for s, kids in enumerate(children):
        for t, ns in kids.items():
            allow[s, t] = True
            nxt[s, t] = ns
        if terminal[s] and eos_id is not None:
            allow[s, eos_id] = True
            nxt[s, eos_id] = s  # engine finishes at EOS before stepping on
    return CompiledGrammar(v, allow, nxt, np.asarray(terminal, bool))


# ---------------------------------------------------------------------------
# JSON-schema → character NFA → DFA → token DFA
# ---------------------------------------------------------------------------

class _Nfa:
    """Thompson-construction scratchpad: integer states, char-labelled
    and epsilon edges. Fragments are (start, end) pairs; combinators
    take FACTORIES where a sub-automaton must be duplicated (bounded
    repetition), because fragments share the one state arena."""

    def __init__(self):
        self.edges: List[List[Tuple[str, int]]] = []
        self.eps: List[List[int]] = []

    def state(self) -> int:
        self.edges.append([])
        self.eps.append([])
        return len(self.edges) - 1

    def lit(self, text: str) -> Tuple[int, int]:
        s = cur = self.state()
        for ch in text:
            nxt = self.state()
            self.edges[cur].append((ch, nxt))
            cur = nxt
        return s, cur

    def charclass(self, chars: str) -> Tuple[int, int]:
        s, e = self.state(), self.state()
        for ch in sorted(set(chars)):
            self.edges[s].append((ch, e))
        return s, e

    def seq(self, frags: Sequence[Tuple[int, int]]) -> Tuple[int, int]:
        if not frags:
            s = self.state()
            return s, s
        for (_, e1), (s2, _) in zip(frags, frags[1:]):
            self.eps[e1].append(s2)
        return frags[0][0], frags[-1][1]

    def alt(self, frags: Sequence[Tuple[int, int]]) -> Tuple[int, int]:
        s, e = self.state(), self.state()
        for fs, fe in frags:
            self.eps[s].append(fs)
            self.eps[fe].append(e)
        return s, e

    def repeat(self, factory: Callable[[], Tuple[int, int]],
               lo: int, hi: int) -> Tuple[int, int]:
        """``factory()`` between ``lo`` and ``hi`` times (bounded — the
        DFA must stay finite, and JSON consumers want bounded outputs
        anyway)."""
        frags = [factory() for _ in range(lo)]
        opt_starts: List[Tuple[int, int]] = []
        for _ in range(max(0, hi - lo)):
            opt_starts.append(factory())
        frag = self.seq(frags) if frags else None
        end = self.state()
        if frag is None:
            start = self.state()
            self.eps[start].append(end)
            cur = start
        else:
            start, cur = frag
            cur_end = frag[1]
            self.eps[cur_end].append(end)
            cur = cur_end
        for fs, fe in opt_starts:
            self.eps[cur].append(fs)
            self.eps[fe].append(end)
            cur = fe
        return start, end


def _eps_closure(nfa: _Nfa, states: frozenset) -> frozenset:
    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in out:
                out.add(t)
                stack.append(t)
    return frozenset(out)


def _nfa_to_dfa(nfa: _Nfa, start: int, accept: int):
    """Subset construction: (transitions: List[Dict[char, int]],
    accepting: List[bool], start_id)."""
    d0 = _eps_closure(nfa, frozenset([start]))
    ids: Dict[frozenset, int] = {d0: 0}
    trans: List[Dict[str, int]] = [{}]
    acc: List[bool] = [accept in d0]
    work = [d0]
    while work:
        cur = work.pop()
        cid = ids[cur]
        by_char: Dict[str, set] = {}
        for s in cur:
            for ch, t in nfa.edges[s]:
                by_char.setdefault(ch, set()).add(t)
        for ch, targets in by_char.items():
            dst = _eps_closure(nfa, frozenset(targets))
            if dst not in ids:
                if len(ids) >= _MAX_DFA_STATES:
                    raise GrammarError(
                        f"schema automaton exceeds {_MAX_DFA_STATES} "
                        "states; simplify the schema (shorter strings, "
                        "fewer alternatives)")
                ids[dst] = len(ids)
                trans.append({})
                acc.append(accept in dst)
                work.append(dst)
            trans[cid][ch] = ids[dst]
    return trans, acc, 0


_JSON_STRING_DEFAULT_LEN = 8
_JSON_INT_DEFAULT_DIGITS = 3


def _schema_fragment(nfa: _Nfa, schema: dict, charset: str,
                     depth: int = 0) -> Tuple[int, int]:
    """One schema node as an NFA fragment. Supported subset (documented
    in docs/serving.md): const/enum, boolean, null, integer (bounded
    digits), string (bounded length, restricted charset), array
    (bounded items), object (properties emitted in declaration order —
    canonical-form JSON, which is what a constrained DECODER produces;
    a validator accepts any order, so parse-compatibility holds)."""
    if depth > 16:
        raise GrammarError("schema nesting deeper than 16 levels")
    if not isinstance(schema, dict):
        raise GrammarError(f"schema node must be an object, got "
                           f"{type(schema).__name__}")
    if "const" in schema:
        return nfa.lit(json.dumps(schema["const"]))
    if "enum" in schema:
        opts = schema["enum"]
        if not opts:
            raise GrammarError("empty enum")
        return nfa.alt([nfa.lit(json.dumps(v)) for v in opts])
    t = schema.get("type")
    if t == "boolean":
        return nfa.alt([nfa.lit("true"), nfa.lit("false")])
    if t == "null":
        return nfa.lit("null")
    if t == "integer":
        digits = int(schema.get("maxDigits", _JSON_INT_DEFAULT_DIGITS))
        if digits < 1:
            raise GrammarError("integer maxDigits must be >= 1")
        lead = nfa.alt([nfa.lit("0"),
                        nfa.seq([nfa.charclass("123456789"),
                                 nfa.repeat(
                                     lambda: nfa.charclass("0123456789"),
                                     0, digits - 1)])])
        if schema.get("minimum", -1) >= 0:
            return lead
        return nfa.seq([nfa.repeat(lambda: nfa.lit("-"), 0, 1), lead])
    if t == "string":
        chars = schema.get("charset")
        if chars is None:
            chars = "".join(c for c in charset
                            if c not in '"\\' and c >= " ")
        else:
            missing = [c for c in chars if c not in charset]
            if missing:
                raise GrammarError(
                    f"string charset chars {missing!r} not producible "
                    "by any token")
            if any(c in '"\\' for c in chars):
                raise GrammarError(
                    'string charset must not contain \'"\' or backslash '
                    "(no escape support in the compiled automaton)")
        if not chars:
            raise GrammarError(
                "no token can produce a JSON string character")
        lo = int(schema.get("minLength", 0))
        hi = int(schema.get("maxLength", _JSON_STRING_DEFAULT_LEN))
        if not 0 <= lo <= hi:
            raise GrammarError(f"bad string length bounds [{lo}, {hi}]")
        body = nfa.repeat(lambda: nfa.charclass(chars), lo, hi)
        return nfa.seq([nfa.lit('"'), body, nfa.lit('"')])
    if t == "array":
        items = schema.get("items")
        if items is None:
            raise GrammarError("array schema needs items")
        lo = int(schema.get("minItems", 1))
        hi = int(schema.get("maxItems", 3))
        if not 0 <= lo <= hi:
            raise GrammarError(f"bad array item bounds [{lo}, {hi}]")
        counts = []
        for k in range(lo, hi + 1):
            if k == 0:
                counts.append(nfa.lit(""))
                continue
            parts = []
            for i in range(k):
                if i:
                    parts.append(nfa.lit(","))
                parts.append(_schema_fragment(nfa, items, charset,
                                              depth + 1))
            counts.append(nfa.seq(parts))
        return nfa.seq([nfa.lit("["), nfa.alt(counts), nfa.lit("]")])
    if t == "object":
        props = schema.get("properties")
        if not props:
            raise GrammarError("object schema needs properties")
        parts: List[Tuple[int, int]] = [nfa.lit("{")]
        for i, (name, sub) in enumerate(props.items()):
            if i:
                parts.append(nfa.lit(","))
            parts.append(nfa.lit(json.dumps(name) + ":"))
            parts.append(_schema_fragment(nfa, sub, charset, depth + 1))
        parts.append(nfa.lit("}"))
        return nfa.seq(parts)
    raise GrammarError(f"unsupported schema node: {schema!r} (supported: "
                       "const/enum/boolean/null/integer/string/array/"
                       "object)")


def compile_json_schema(schema: dict,
                        token_strs: Union[str, Sequence[str]],
                        eos_id: Optional[int] = None) -> CompiledGrammar:
    """Compile a (restricted) JSON schema into a token-level
    :class:`CompiledGrammar`.

    ``token_strs`` maps token id → the text that token decodes to: a
    string treats each character as one token (the char-LM case), a
    list supports multi-character tokens — a token's transition is the
    composition of its characters' transitions through the char DFA, so
    a token whose text crosses a structural boundary (``":``) is
    admitted exactly when every character in it is.

    Every literal character the schema requires must be producible by
    some token (checked here, at compile time — a gap would otherwise
    dead-end the automaton mid-decode and surface as a confusing
    ``finish_reason="grammar"`` half-way through an object).
    """
    if isinstance(token_strs, str):
        strs = list(token_strs)
    else:
        strs = [str(s) for s in token_strs]
    v = len(strs)
    if eos_id is not None and not 0 <= int(eos_id) < v:
        raise GrammarError(f"eos_id {eos_id} out of range [0, {v})")
    charset = "".join(sorted({c for s in strs for c in s}))
    nfa = _Nfa()
    frag = _schema_fragment(nfa, schema, charset)
    # compile-time coverage check: every literal char the automaton can
    # demand must exist in some token (charclasses were intersected
    # above; literals were not)
    need = {ch for edges in nfa.edges for ch, _ in edges}
    missing = sorted(need - set(charset))
    if missing:
        raise GrammarError(
            f"schema requires characters {missing!r} no token produces")
    trans, acc, dstart = _nfa_to_dfa(nfa, frag[0], frag[1])

    def tok_step(ds: int, tok: int) -> int:
        for ch in strs[tok]:
            nxt = trans[ds].get(ch)
            if nxt is None:
                return _DEAD
            ds = nxt
        return ds

    # BFS over token-level reachability: only char states reachable by
    # WHOLE tokens become grammar states (multi-char tokens skip the
    # intermediate char states entirely)
    ids: Dict[int, int] = {dstart: 0}
    order = [dstart]
    rows: List[np.ndarray] = []
    nxts: List[np.ndarray] = []
    accs: List[bool] = []
    i = 0
    while i < len(order):
        ds = order[i]
        i += 1
        allow = np.zeros((v,), bool)
        nxt = np.full((v,), _DEAD, np.int32)
        for tok in range(v):
            if not strs[tok]:
                continue  # an empty-text token can never advance JSON
            t2 = tok_step(ds, tok)
            if t2 == _DEAD:
                continue
            if t2 not in ids:
                ids[t2] = len(order)
                order.append(t2)
            allow[tok] = True
            nxt[tok] = ids[t2]
        if acc[ds] and eos_id is not None and 0 <= eos_id < v:
            allow[eos_id] = True
            nxt[eos_id] = ids[ds]
        rows.append(allow)
        nxts.append(nxt)
        accs.append(bool(acc[ds]))
    return CompiledGrammar(v, np.stack(rows), np.stack(nxts),
                           np.asarray(accs, bool))


# ---------------------------------------------------------------------------
# stop sequences
# ---------------------------------------------------------------------------

class StopMatcher:
    """Aho-Corasick matcher over token ids for MULTI-token stop
    sequences, matched across token boundaries (a stop sequence split
    over a speculative burst or two decode steps still matches).

    ``feed(tok)`` returns the length of the stop sequence that COMPLETED
    at this token (0 otherwise — the longest, when several end here).
    ``pending`` is the number of trailing emitted tokens that form a
    live partial match: the streaming layer withholds exactly those, so
    a client never receives the head of a stop sequence the next token
    would complete (and the withheld tokens flush the moment the match
    dies)."""

    def __init__(self, sequences: Sequence[Sequence[int]]):
        seqs = [[int(t) for t in s] for s in sequences]
        if not seqs or any(not s for s in seqs):
            raise ValueError("stop sequences must be non-empty")
        goto: List[Dict[int, int]] = [{}]
        depth = [0]
        out_len = [0]
        for s in seqs:
            node = 0
            for t in s:
                if t not in goto[node]:
                    goto.append({})
                    depth.append(depth[node] + 1)
                    out_len.append(0)
                    goto[node][t] = len(goto) - 1
                node = goto[node][t]
            out_len[node] = max(out_len[node], len(s))
        # BFS fail links; out_len inherits through the suffix chain so a
        # shorter stop ending inside a longer partial match still fires
        fail = [0] * len(goto)
        work = list(goto[0].values())
        while work:
            node = work.pop(0)
            for t, child in goto[node].items():
                work.append(child)
                f = fail[node]
                while f and t not in goto[f]:
                    f = fail[f]
                fail[child] = goto[f].get(t, 0) if goto[f].get(t, 0) != child \
                    else 0
                out_len[child] = max(out_len[child], out_len[fail[child]])
        self._goto = goto
        self._fail = fail
        self._depth = depth
        self._out = out_len
        self._state = 0

    def feed(self, tok: int) -> int:
        s = self._state
        while s and tok not in self._goto[s]:
            s = self._fail[s]
        s = self._goto[s].get(tok, 0)
        self._state = s
        return self._out[s]

    @property
    def pending(self) -> int:
        """Trailing tokens currently withheld as a live partial match."""
        return self._depth[self._state]


# ---------------------------------------------------------------------------
# the per-request pipeline
# ---------------------------------------------------------------------------

class LogitState:
    """Per-request logit-processor state: penalty counts, grammar DFA
    position, stop matcher, and the device-mask residency handle.

    Owned by the scheduler thread (it lives on the `_ActiveSeq`); built
    fresh by every `engine.submit` — including the supervisor's crash-
    recovery resubmission, so a token-identical re-decode re-observes
    from a clean state. Grammar state and penalty counts advance only on
    EMITTED tokens (prompt tokens are conditioning, not output)."""

    __slots__ = ("vocab", "grammar", "gstate", "stop",
                 "rep", "presence", "freq", "_counts", "mask_base")

    def __init__(self, vocab_size: int, *,
                 grammar: Optional[CompiledGrammar] = None,
                 stop: Optional[Sequence[Sequence[int]]] = None,
                 repetition_penalty: Optional[float] = None,
                 presence_penalty: Optional[float] = None,
                 frequency_penalty: Optional[float] = None):
        self.vocab = int(vocab_size)
        if grammar is not None and grammar.vocab_size != self.vocab:
            raise ValueError(
                f"grammar vocab {grammar.vocab_size} != engine vocab "
                f"{self.vocab}")
        self.grammar = grammar
        self.gstate = 0
        self.stop = StopMatcher(stop) if stop else None
        self.rep = float(repetition_penalty) if repetition_penalty else None
        self.presence = float(presence_penalty) if presence_penalty else 0.0
        self.freq = float(frequency_penalty) if frequency_penalty else 0.0
        penal = (self.rep is not None or self.presence or self.freq)
        self._counts = np.zeros((self.vocab,), np.int64) if penal else None
        # first device row of this grammar's resident mask range (set by
        # the engine at admission; None = host-only masking fallback)
        self.mask_base: Optional[int] = None

    @property
    def active(self) -> bool:
        return (self.grammar is not None or self.stop is not None
                or self._counts is not None)

    def adjust(self, row: np.ndarray) -> np.ndarray:
        """Penalty-adjusted probability row (the SAME object when no
        penalty applies — the bitwise-identity fast path). Multiplicative
        in probability space == additive in log space, and
        `sample_logits` renormalizes, so no softmax is needed here:
        repetition penalty r scales seen tokens' (negative) log-probs by
        r (``p^r``), presence/frequency subtract ``α·seen + β·count``
        from the logit (``·e^-…``)."""
        counts = self._counts
        if counts is None:
            return row
        seen = counts > 0
        if not seen.any():
            return row
        out = np.array(row, np.float64)
        if self.rep is not None and self.rep != 1.0:
            out[seen] = out[seen] ** self.rep
        if self.presence or self.freq:
            out *= np.exp(-(self.presence * seen + self.freq * counts))
        return out

    def allow_row(self) -> Optional[np.ndarray]:
        """The EXACT host-side mask for the next sampled token (None =
        unconstrained). Applied by `sample_logits` as ``-inf`` logits —
        forbidden tokens get probability exactly 0, whatever the device
        mask did (the device's additive row is the perf assist; this is
        the correctness guarantee)."""
        if self.grammar is None:
            return None
        return self.grammar.allow[self.gstate]

    def advance(self, tok: int) -> None:
        if self._counts is not None:
            self._counts[tok] += 1
        if self.grammar is not None:
            ns = int(self.grammar.next_state[self.gstate, tok])
            if ns >= 0:
                self.gstate = ns

    def exhausted(self) -> bool:
        """True when the grammar admits nothing from the current state:
        the structured output is complete — the engine finishes the
        request with ``finish_reason="grammar"``."""
        return self.grammar is not None and not self.grammar.live(self.gstate)

    def stop_feed(self, tok: int) -> int:
        return self.stop.feed(tok) if self.stop is not None else 0

    @property
    def stop_pending(self) -> int:
        return self.stop.pending if self.stop is not None else 0


# ---------------------------------------------------------------------------
# device mask-row bookkeeping
# ---------------------------------------------------------------------------

class _MaskEntry:
    __slots__ = ("start", "rows", "n_states", "refs", "last_used")

    def __init__(self, start: int, rows: int, n_states: int):
        self.start = start
        self.rows = rows
        self.n_states = n_states
        self.refs = 0
        self.last_used = 0


class MaskPool:
    """Host bookkeeping for the engine's device mask table rows.

    Row 0 is RESERVED all-zeros (the admit-all row every unconstrained
    slot's state index points at). Grammars allocate ``bucket_for(S)``
    rows (pow2 buckets — the upload program family stays fixed, and a
    bucket's zero-padded tail rows are admit-all rows inside the
    grammar's own allocation, never another grammar's). Entries are
    refcounted and cached across requests by grammar content hash;
    zero-ref entries LRU-evict under pressure. ``acquire`` returning
    None means the grammar cannot fit even after eviction — the caller
    falls back to host-only masking (correct, slower).

    Scheduler-thread-only past engine start (attach at admission,
    release on slot free) — the same single-writer protocol as the KV
    pool's metadata."""

    def __init__(self, rows: int, buckets: Sequence[int]):
        self.rows = int(rows)
        self.buckets = list(buckets)
        self._free: List[Tuple[int, int]] = [(1, self.rows - 1)] \
            if self.rows > 1 else []
        self._resident: Dict[str, _MaskEntry] = {}
        self._tick = 0

    def _alloc(self, n: int) -> Optional[int]:
        for i, (start, size) in enumerate(self._free):
            if size >= n:
                if size == n:
                    self._free.pop(i)
                else:
                    self._free[i] = (start + n, size - n)
                return start
        return None

    def _free_extent(self, start: int, n: int) -> None:
        self._free.append((start, n))
        self._free.sort()
        merged: List[Tuple[int, int]] = []
        for s, sz in self._free:
            if merged and merged[-1][0] + merged[-1][1] == s:
                merged[-1] = (merged[-1][0], merged[-1][1] + sz)
            else:
                merged.append((s, sz))
        self._free = merged

    def lookup(self, key: str) -> Optional[int]:
        e = self._resident.get(key)
        return e.start if e is not None else None

    def acquire(self, grammar: CompiledGrammar) -> Tuple[Optional[int], bool]:
        """(first device row, needs_upload) — or (None, False) when the
        grammar cannot fit. ``needs_upload=True`` means the caller must
        upload the mask table into rows [start, start + n_states)."""
        self._tick += 1
        e = self._resident.get(grammar.key)
        if e is not None:
            e.refs += 1
            e.last_used = self._tick
            return e.start, False
        n = grammar.n_states
        if not self.buckets or n > self.buckets[-1]:
            return None, False
        need = next(b for b in self.buckets if b >= n)
        start = self._alloc(need)
        while start is None:
            victims = [k for k, v in self._resident.items() if v.refs == 0]
            if not victims:
                return None, False
            k = min(victims, key=lambda k: self._resident[k].last_used)
            v = self._resident.pop(k)
            self._free_extent(v.start, v.rows)
            start = self._alloc(need)
        e = _MaskEntry(start, need, n)
        e.refs = 1
        e.last_used = self._tick
        self._resident[grammar.key] = e
        return start, True

    def release(self, key: str) -> None:
        e = self._resident.get(key)
        if e is not None and e.refs > 0:
            e.refs -= 1

    def resident_rows(self) -> int:
        return sum(e.rows for e in self._resident.values())

    def stats(self) -> dict:
        return {"rows": self.rows,
                "resident": len(self._resident),
                "resident_rows": self.resident_rows(),
                "free_rows": sum(sz for _s, sz in self._free)}


# ---------------------------------------------------------------------------
# token streaming
# ---------------------------------------------------------------------------

class TokenStream:
    """Thread-safe per-request token event queue — the backing store of
    one SSE response.

    Producer side (the scheduler thread, via `DecodeHandle`): ``push``
    one event per RELEASED token (stop-sequence hold-back happens before
    the push — a live partial match is withheld until it dies or
    completes), ``close`` once with the terminal event. Pushes are
    deduplicated by token INDEX: a supervisor crash-recovery re-decode
    (token-identical by construction) re-emits from index 0, and the
    already-streamed prefix is silently skipped — the client sees each
    token exactly once, across engine restarts.

    Consumer side (the HTTP handler thread): iterate :meth:`events`
    until the terminal event (``{"done": true, ...}`` carrying the final
    token list, ``finish_reason``, ``request_id``, and the per-phase
    ``timings`` breakdown)."""

    def __init__(self):
        self._q: "queue.SimpleQueue[dict]" = queue.SimpleQueue()
        self._sent = 0      # next unstreamed token index (producer only)
        self._closed = False

    @property
    def sent(self) -> int:
        return self._sent

    def push(self, index: int, tok: int) -> None:
        if index < self._sent or self._closed:
            return  # crash-recovery re-emission of an already-sent token
        self._sent = index + 1
        self._q.put({"token": int(tok), "index": int(index)})

    def close(self, handle, error: Optional[BaseException] = None) -> None:
        """Terminal event (exactly once): flush any tokens the hold-back
        withheld (truncation already happened — `handle.tokens` is
        final), then the done record."""
        if self._closed:
            return
        tokens = list(handle.tokens)
        for i in range(self._sent, len(tokens)):
            self._sent = i + 1
            self._q.put({"token": int(tokens[i]), "index": i})
        evt = {"done": True, "request_id": handle.request_id,
               "tokens": tokens,
               "finish_reason": getattr(handle, "finish_reason", None),
               "timings": handle.timings()}
        if error is not None:
            evt["error"] = str(error)
        self._closed = True
        self._q.put(evt)

    def events(self, deadline: Optional[float] = None):
        """Yield events until the terminal one. ``deadline``: absolute
        `time.monotonic` cutoff — expiry raises TimeoutError (the SSE
        writer cancels the request and answers in-band)."""
        while True:
            if deadline is None:
                evt = self._q.get()
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("stream deadline exceeded")
                try:
                    evt = self._q.get(timeout=remaining)
                except queue.Empty:
                    raise TimeoutError("stream deadline exceeded")
            yield evt
            if evt.get("done"):
                return
