"""Engine supervisor: watchdog, crash recovery, degradation, draining.

Before this module, the serving tier's fault model was "hope": one
uncaught exception in the scheduler loop (or one hung XLA dispatch)
killed every in-flight request silently — the daemon thread evaporated,
the HTTP tier kept admitting traffic into a dead engine, and each
blocked caller discovered the outage only by timing out. DeepSpark
(arXiv 1602.08191) and TensorFlow (arXiv 1605.08695) both treat worker
failure as a first-class design input; this is that treatment for the
decode engine.

The supervisor OWNS the engine (it is built from a ``factory`` so a
dead one can be rebuilt from scratch) and layers four mechanisms on top:

**Watchdog.** The scheduler loop stamps ``engine.heartbeat`` once per
iteration (idle passes included, so staleness means *stuck*, not
*quiet*). The watchdog thread polls it; a heartbeat older than
``hang_timeout_s``, or a recorded ``engine.crashed`` exception (the
loop's new try/except reports instead of evaporating), triggers
recovery.

**Crash recovery.** The dead engine is *fenced* (a hung thread that
later wakes sees the fence and exits rather than double-finishing
requests), a replacement is built by the factory — re-jitting the same
program families, so CompileCounter budgets are unchanged — and every
tracked in-flight request is resubmitted FRONT-of-queue onto it with
its ORIGINAL (reset) handle: the caller blocked in ``result()`` never
observes the restart. Decode is deterministic per request (the seed
reseeds, the prompt re-prefills), so the re-run reproduces exactly the
token sequence the crashed attempt was producing — the same primitive
preempt-and-swap (PR 6) already proved. Consecutive restarts back off
exponentially with seeded jitter; each request carries a retry budget,
and exhaustion fails it with :class:`RetryBudgetExceededError` (the
serving layer's structured 503 carrying the ``request_id``).

**Graceful degradation.** Sustained pressure walks a ladder:
level 1 sheds the lowest-priority queued load (``LoadSheddedError`` →
retryable 503), level 2 additionally halves the prefill chunk cap
(shorter device holds; the smaller pow2 buckets are already compiled),
level 3 rejects new admissions with :class:`AdmissionRejectedError`
(503 + ``Retry-After``). TWO escalation inputs (ISSUE 11): queue depth
against the shed watermark, and — with ``slo=`` a
`profiler.SLOMonitor` — the latency-budget burn rate, so a fleet whose
queue is short but whose p99 is burning the SLO still degrades before
it melts. Easing on BOTH inputs walks back down. The current rung is
the ``degradation_level`` gauge.

**Draining restart** (``/admin/drain``): stop admitting, let in-flight
work finish, swap in a fresh engine, resume — a zero-dropped-request
restart for weight pushes or leak hygiene.

Readiness (`/readyz`) is ``not draining AND not recovering AND
heartbeat fresh``; liveness (`/healthz`) is just "the process answers".
Every transition is traced (``engine_crash`` / ``engine_restart`` /
``degrade`` instants, plus a per-request ``recovered`` span bridging
the crash gap on the request waterfall) and counted
(``engine_restarts_total``, ``requests_recovered_total``,
``serving_ready`` / ``degradation_level`` gauges).

The chaos proof lives in ``tests/test_chaos.py``: every
`inference/failpoints.py` seam armed in turn under concurrent load,
asserting no request lost, none answered twice, and every completion
token-identical to the no-fault run.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .batcher import QueueFullError
from .engine import DecodeHandle, DecodeScheduler
from .metrics import MetricsRegistry, default_registry
from .trace import FlightRecorder, default_recorder

__all__ = ["EngineSupervisor", "RetryBudgetExceededError",
           "ShuttingDownError", "AdmissionRejectedError"]


class RetryBudgetExceededError(RuntimeError):
    """The request's retry budget ran out across engine restarts: every
    attempt saw the engine die. Carries the ``request_id`` so the
    serving layer's 503 body is actionable, not silent."""

    def __init__(self, request_id: str, attempts: int):
        self.request_id = request_id
        self.attempts = attempts
        super().__init__(
            f"request {request_id} abandoned after {attempts} engine "
            "crash(es): retry budget exhausted")


class ShuttingDownError(RuntimeError):
    """The server is tearing down; in-flight requests are failed FAST
    with this (structured 503) instead of being left to hang against a
    stopped engine."""

    def __init__(self, request_id: Optional[str] = None):
        self.request_id = request_id
        super().__init__("server is shutting down")


class AdmissionRejectedError(RuntimeError):
    """Admission refused by the degradation ladder (level 3) or a drain
    in progress. ``retry_after_s`` feeds the HTTP ``Retry-After``
    header — the client should back off, not hammer."""

    def __init__(self, reason: str, retry_after_s: float):
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        super().__init__(f"not admitting requests ({reason}); retry "
                         f"after {retry_after_s:g}s")


class _Tracked:
    """One supervised in-flight request: everything needed to replay it
    from scratch on a rebuilt engine."""

    __slots__ = ("prompt", "max_new_tokens", "kwargs", "handle", "attempts",
                 "span_open")

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 kwargs: dict, handle: DecodeHandle):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.kwargs = kwargs
        self.handle = handle
        self.attempts = 1  # submissions so far (first one included)
        # a `recovered` span is open on this request's trace track: a
        # recovery pass that fails mid-way (factory error) and reruns
        # must not open a second unmatched begin per victim
        self.span_open = False


class EngineSupervisor:
    """Wraps a :class:`DecodeScheduler` with watchdog + crash recovery +
    a graceful-degradation ladder + draining restarts.

    ``factory``: zero-arg callable building a CONFIGURED (not started)
    DecodeScheduler — called once at construction and once per
    restart/drain swap. ``hang_timeout_s``: heartbeat staleness that
    declares the loop hung. ``retry_budget``: total submissions allowed
    per request (1 original + budget-1 recoveries... precisely: a
    request is abandoned once its attempt count EXCEEDS the budget).
    ``clock``/``sleep_fn``: injectable time (tests drive the watchdog
    with a frozen clock and zero real sleeps via ``check()``).
    ``watchdog=False`` skips the background thread — tests then call
    :meth:`check` explicitly.
    """

    def __init__(self, factory: Callable[[], DecodeScheduler], *,
                 hang_timeout_s: float = 5.0,
                 warmup_timeout_s: float = 60.0,
                 poll_interval_s: float = 0.05,
                 retry_budget: int = 3,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 backoff_jitter: float = 0.25,
                 backoff_seed: int = 0,
                 backoff_reset_s: float = 30.0,
                 shed_watermark: float = 0.75,
                 calm_watermark: float = 0.25,
                 ladder_patience: int = 3,
                 retry_after_s: float = 1.0,
                 slo=None,
                 metrics: Optional[MetricsRegistry] = None,
                 tracer: Optional[FlightRecorder] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 watchdog: bool = True, warm_on_build: bool = True):
        self._factory = factory
        self.hang_timeout_s = float(hang_timeout_s)
        # a FRESH engine's first iteration legitimately stalls the
        # heartbeat for however long XLA takes to compile its program
        # families (a rebuilt engine's jit caches start empty) — judging
        # it by hang_timeout_s would declare a false hang, fence the
        # compiling engine, rebuild, recompile, and churn until every
        # request's retry budget died. Until the engine completes its
        # first iteration (iterations == 0), staleness is judged against
        # this much larger bound instead.
        self.warmup_timeout_s = max(float(warmup_timeout_s),
                                    float(hang_timeout_s))
        self.poll_interval_s = float(poll_interval_s)
        self.retry_budget = int(retry_budget)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_jitter = float(backoff_jitter)
        self.backoff_reset_s = float(backoff_reset_s)
        self.shed_watermark = float(shed_watermark)
        self.calm_watermark = float(calm_watermark)
        self.ladder_patience = int(ladder_patience)
        self.retry_after_s = float(retry_after_s)
        # latency-SLO escalation input (profiler.SLOMonitor, ISSUE 11):
        # the ladder walks up on sustained queue pressure OR a sustained
        # latency-budget burn, and walks down only when BOTH are calm —
        # two independent inputs, one rung, no flapping when one input
        # oscillates around its watermark while the other holds it up
        self._slo = slo
        self.metrics = metrics if metrics is not None else default_registry()
        self.tracer = tracer if tracer is not None else default_recorder()
        self._clock = clock
        self._sleep = sleep_fn
        # seeded jitter: two replicas restarting off the same crash must
        # not retry in lockstep, but a chaos replay must be exact
        self._backoff_rng = np.random.default_rng(backoff_seed)
        self._lock = threading.RLock()  # engine identity + tracked set
        self._tracked: Dict[str, _Tracked] = {}
        self._stopping = False
        self._draining = False
        self._recovering = False
        self._restart_streak = 0
        self._last_restart: Optional[float] = None
        self._pressure_hits = 0
        self._calm_hits = 0
        self.degradation_level = 0
        self.restarts = 0
        m = self.metrics
        self._m_restarts = m.counter("engine_restarts_total")
        self._m_recovered = m.counter("requests_recovered_total")
        self._m_abandoned = m.counter("requests_abandoned_total")
        self._m_shed = m.counter("requests_shed_total")
        self._g_level = m.gauge("degradation_level")
        self._g_ready = m.gauge("serving_ready")
        self._warm_on_build = bool(warm_on_build)
        self._kick = threading.Event()  # crash callback -> prompt poll
        # under the lock like every other _spawn_engine call site: the
        # watchdog starts below and the degradation/engine state it reads
        # is lock-guarded from the first instant
        with self._lock:
            self.engine = self._spawn_engine()
        self._g_ready.set(1)
        self._watchdog: Optional[threading.Thread] = None
        if watchdog:
            self._watchdog = threading.Thread(
                target=self._watch, daemon=True, name="engine-supervisor")
            self._watchdog.start()

    # -- engine lifecycle --------------------------------------------------
    def _spawn_engine(self) -> DecodeScheduler:
        """Build, hook, start, and WARM a fresh engine. Warming runs one
        synthetic request whose prompt touches every prefill chunk
        bucket plus the decode/admit programs, so the XLA compiles land
        HERE — inside the recovery/drain window the supervisor already
        owns — instead of stalling the heartbeat under live traffic
        right after a swap (a tight watchdog would read that stall as a
        fresh hang and churn restarts until the retry budgets died)."""
        eng = self._factory()
        eng._on_crash = self._note_crash
        self._apply_degradation(eng, self.degradation_level)
        eng.start()
        if self._warm_on_build:
            self._warm(eng)
        return eng

    def _warm(self, eng: DecodeScheduler) -> None:
        """Best-effort program-family warm-up (engine.warmup compiles
        every bucket's program with pure discarded calls — no metrics,
        trace, or pool side effects). A failure is traced, never
        swallowed, and never fatal: an unwarmed engine still serves,
        it just compiles under traffic.

        When any TRACKED in-flight request carries a grammar, the
        masked program families are warmed too (``warmup(masks=True)``)
        — a recovery swap is about to resubmit that constrained
        request, and its masked-decode compile landing mid-iteration on
        the fresh engine would stall the very heartbeat the watchdog
        judges (the false-hang churn warmup exists to prevent).
        Unconstrained rebuilds keep skipping the ~2x masked warm-up."""
        warmup = getattr(eng, "warmup", None)  # stub engines: no-op
        if warmup is None:
            return
        with self._lock:
            masks = any(t.kwargs.get("grammar") is not None
                        for t in self._tracked.values())
        try:
            # the masks kwarg only when needed: stub/legacy engines in
            # the chaos drills expose a zero-arg warmup()
            warmup(masks=True) if masks else warmup()
        except Exception as e:
            self.tracer.instant("warmup_skipped", track="supervisor",
                                args={"error": type(e).__name__,
                                      "detail": str(e)[:200]})

    def _note_crash(self, exc: BaseException) -> None:
        # runs on the DYING scheduler thread: record nothing here (the
        # engine already stamped .crashed); just wake the watchdog so
        # recovery starts within one poll, not one poll interval
        self._kick.set()

    def _watch(self) -> None:
        while not self._stopping:
            self._kick.wait(timeout=self.poll_interval_s)
            self._kick.clear()
            if self._stopping:
                return
            try:
                self.check()
            except Exception as e:
                # the supervisor is the last line of defense — its own
                # loop must survive anything recovery throws (e.g. a
                # factory failure while the process is dying)
                self.tracer.instant(
                    "supervisor_error", track="supervisor",
                    args={"error": type(e).__name__,
                          "detail": str(e)[:200]})

    def check(self) -> None:
        """One watchdog evaluation: crash/hang detection + the
        degradation ladder. Normally driven by the background thread;
        tests call it directly with an injected frozen clock.

        The whole evaluation holds ``self._lock`` (reentrant — recovery
        re-acquires it): the ladder counters and the engine identity are
        otherwise written by this watchdog thread while ``submit()``
        reads them under the lock, the lockset-empty cross-thread access
        graftlint CC005 flagged."""
        with self._lock:
            if self._stopping or self._draining:
                return
            eng = self.engine
            if eng.crashed is not None:
                self._recover("crash", eng)
                return
            limit = (self.hang_timeout_s if eng.iterations > 0
                     else self.warmup_timeout_s)
            if self._clock() - eng.heartbeat > limit:
                self._recover("hang", eng)
                return
            self._evaluate_ladder(eng)
            self._prune_done()

    # -- crash recovery ----------------------------------------------------
    def _recover(self, reason: str, dead: DecodeScheduler) -> None:
        with self._lock:
            if self.engine is not dead or self._stopping:
                return  # someone else already swapped it
            self._recovering = True
            self._g_ready.set(0)
            try:
                self._recover_locked(reason, dead)
                self._g_ready.set(1)
            finally:
                # a factory/rebuild failure must not leave _recovering
                # latched True (readiness stuck 503 forever on whatever
                # engine a LATER pass does manage to build); the next
                # watchdog poll re-enters and retries
                self._recovering = False

    def _recover_locked(self, reason: str, dead: DecodeScheduler) -> None:
        tr = self.tracer
        tr.instant("engine_crash" if reason == "crash"
                   else "engine_hang", track="supervisor",
                   args={"reason": reason,
                         "error": type(dead.crashed).__name__
                         if dead.crashed else "heartbeat_stale",
                         "iterations": dead.iterations,
                         "inflight": len(self._tracked)})
        # fence FIRST: from here the dead engine's thread (hung, may
        # wake later) can no longer touch any handle; then give it a
        # join grace so the common case (crashed = thread already
        # exiting) is fully quiesced before handles are reused
        dead.fence()
        if dead._thread is not None:
            dead._thread.join(timeout=self.poll_interval_s)
        # sweep the tracked set: done/cancelled requests leave it,
        # survivors get a `recovered` span bridging the outage on
        # their waterfall track
        victims: List[_Tracked] = []
        for rid, t in list(self._tracked.items()):
            h = t.handle
            if h.done():
                del self._tracked[rid]
            elif h.cancelled():
                h._finish()  # caller already gave up; partial tokens
                del self._tracked[rid]
            else:
                victims.append(t)
        victims.sort(key=lambda t: t.handle.t_submit)
        for t in victims:
            if not t.span_open:  # a retried recovery pass must not
                t.span_open = True  # stack a second unmatched begin
                tr.begin("recovered", req=t.handle.request_id,
                         args={"reason": reason,
                               "attempt": t.attempts})
        # bounded exponential backoff + seeded jitter between
        # CONSECUTIVE restarts (a crash loop must not spin-rebuild);
        # the streak resets after a healthy stretch
        now = self._clock()
        if self._last_restart is not None and \
                now - self._last_restart > self.backoff_reset_s:
            self._restart_streak = 0
        delay = min(self.backoff_max_s,
                    self.backoff_base_s * (2 ** self._restart_streak))
        jitter = self._backoff_rng.random()  # host RNG, not a sync
        delay *= 1.0 + self.backoff_jitter * jitter
        self._restart_streak += 1
        self._last_restart = now
        if delay > 0:
            self._sleep(delay)
        # rebuild + warm: the factory re-jits the same program
        # families (same shapes, same buckets — CompileCounter
        # budgets are unchanged), and the degradation rung carries
        # over
        self.engine = self._spawn_engine()
        self.restarts += 1
        self._m_restarts.inc()
        tr.instant("engine_restart", track="supervisor",
                   args={"restart": self.restarts, "reason": reason,
                         "backoff_s": round(delay, 4),
                         "recovering": len(victims)})
        # resubmit FRONT-of-queue, newest first, so the final queue
        # order is oldest-submit-first — recovered work does not
        # wait behind requests that arrived after the crash
        recovered = 0
        for t in reversed(victims):
            h = t.handle
            rid = h.request_id
            if t.attempts >= self.retry_budget:
                self._m_abandoned.inc()
                t.span_open = False
                tr.end("recovered", req=rid,
                       args={"outcome": "retry_budget_exhausted"})
                h._finish(RetryBudgetExceededError(rid, t.attempts))
                del self._tracked[rid]
                continue
            t.attempts += 1
            h._reset_for_retry()
            t.span_open = False
            tr.end("recovered", req=rid)
            try:
                self.engine.submit(t.prompt, t.max_new_tokens,
                                   _handle=h, _front=True, **t.kwargs)
            except QueueFullError as e:
                # a full-queue-and-full-slots crash can leave more
                # victims than the rebuilt queue holds: the
                # overflow must FAIL (retryable 503 via the
                # handle), never hang — and must not abort the
                # remaining resubmissions
                h._finish(e)
                del self._tracked[rid]
                continue
            except RuntimeError:
                # the replacement died before this resubmission
                # landed (a crash-looping engine): leave the
                # request TRACKED — the next recovery pass retries
                # it, and its attempts counter keeps marching
                # toward the budget's structured 503
                continue
            recovered += 1
        if recovered:
            self._m_recovered.inc(recovered)
        self._recovering = False
        self._g_ready.set(1)

# -- degradation ladder ------------------------------------------------
    def _evaluate_ladder(self, eng: DecodeScheduler) -> None:
        """One ladder evaluation over BOTH escalation inputs: queue
        pressure (the fraction of max_queue waiting) and — when an
        `profiler.SLOMonitor` is attached — the latency-budget burn
        rate. Either input hot counts a pressure hit; de-escalation
        needs every input calm (queue at-or-under the calm watermark
        AND latency back inside budget), so a rung held up by latency
        cannot flap just because the queue drained, and vice versa.
        The patience counters debounce both directions unchanged."""
        frac = eng.queue_depth() / max(1, eng.max_queue)
        burning, latency_calm = (
            self._slo.pressure(self._clock())
            if self._slo is not None else (False, True))
        if frac >= self.shed_watermark or burning:
            self._pressure_hits += 1
            self._calm_hits = 0
        elif frac <= self.calm_watermark and latency_calm:
            self._calm_hits += 1
            self._pressure_hits = 0
        else:
            self._pressure_hits = 0
            self._calm_hits = 0
        if self._pressure_hits >= self.ladder_patience \
                and self.degradation_level < 3:
            self._set_level(self.degradation_level + 1,
                            source="latency" if burning
                            and frac < self.shed_watermark else "queue")
            self._pressure_hits = 0
        elif self._calm_hits >= self.ladder_patience \
                and self.degradation_level > 0:
            self._set_level(self.degradation_level - 1)
            self._calm_hits = 0
        if self.degradation_level >= 1:
            shed = eng.shed_queued(eng.max_queue // 2)
            if shed:
                self._m_shed.inc(shed)

    def _set_level(self, level: int, source: str = "queue") -> None:
        self.degradation_level = level
        self._g_level.set(level)
        self._apply_degradation(self.engine, level)
        self.tracer.instant("degrade", track="supervisor",
                            args={"level": level, "input": source})

    @staticmethod
    def _apply_degradation(eng: DecodeScheduler, level: int) -> None:
        """Project a degradation rung onto an engine (also called on
        every rebuild, so a restart under pressure comes up degraded,
        not amnesiac). Takes the rung as a parameter — callers read
        ``degradation_level`` under whatever lock they already hold —
        instead of re-reading shared state lock-free here."""
        eng.chunk_cap = (max(1, eng.prefill_chunk // 2)
                         if level >= 2 else None)

    # -- admission / client side -------------------------------------------
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int,
               **kw) -> DecodeHandle:
        """Supervised submit: tracked for crash recovery. Raises
        :class:`AdmissionRejectedError` at degradation level 3 or while
        draining (the HTTP tier turns it into 503 + Retry-After)."""
        # the not-running retry window must span at least one full
        # recovery (rebuild + warm-up compiles), or a submit landing
        # mid-restart would error out just before the engine came back
        deadline = self._clock() + max(5.0, 2 * self.backoff_max_s)
        while True:
            with self._lock:
                # admission checks live under the same lock that guards
                # engine swaps / drain transitions, so a request can
                # never slip past a flag mid-flip into a dying engine
                if self._stopping:
                    raise ShuttingDownError()
                if self._draining:
                    raise AdmissionRejectedError(
                        "draining restart in progress",
                        self.retry_after_s)
                if self.degradation_level >= 3:
                    raise AdmissionRejectedError(
                        "degradation ladder level 3 (sustained "
                        "overload)", self.retry_after_s)
                try:
                    handle = self.engine.submit(prompt_ids,
                                                max_new_tokens, **kw)
                except QueueFullError:
                    raise
                except RuntimeError:
                    # engine died between checks (not running): recovery
                    # will swap it — bounded retry, and on expiry a
                    # RETRYABLE 503 with a back-off hint, never a raw
                    # lifecycle error surfaced as a client fault
                    if self._clock() >= deadline:
                        raise AdmissionRejectedError(
                            "engine recovering (crash loop?)",
                            self.retry_after_s)
                    handle = None
                if handle is not None:
                    self._tracked[handle.request_id] = _Tracked(
                        [int(t) for t in prompt_ids], int(max_new_tokens),
                        dict(kw), handle)
                    return handle
            self._kick.set()  # nudge the watchdog at the dead engine
            self._sleep(self.poll_interval_s)

    def generate_handle(self, prompt_ids: Sequence[int],
                        max_new_tokens: int,
                        timeout: Optional[float] = 120.0,
                        **kw) -> DecodeHandle:
        """Blocking supervised generate — the `/generate` entry point.
        Same contract as the engine's: a timed-out wait CANCELS the
        request. The handle leaves the recovery-tracking set on exit
        either way (a caller that got its answer — or gave up — must
        not have its request replayed by a later restart)."""
        handle = self.submit(prompt_ids, max_new_tokens, **kw)
        try:
            handle.result(timeout)
        except TimeoutError:
            handle.cancel()
            raise
        finally:
            self._untrack(handle.request_id)
        return handle

    def generate_many(self, prompt_ids: Sequence[int], n: int,
                      max_new_tokens: int,
                      timeout: Optional[float] = 120.0, *, seed: int = 0,
                      **kw) -> List:
        """Supervised best-of-n (`/generate` with ``n > 1``): the shared
        `speculative.submit_fork_group` protocol over this supervisor's
        tracked submit — every candidate is tracked for crash recovery
        individually (the fork group rides the resubmission kwargs, so
        recovered candidates keep sharing blocks when the rebuilt
        engine re-publishes, and degrade to independent prefills when
        it cannot: correctness never depends on the fork). A partial-
        submit failure or timeout cancels the submitted candidates;
        cancelled handles finish at the engine's next sweep and leave
        the tracking set via `_prune_done`."""
        from .speculative import await_fork_group, submit_fork_group
        handles = submit_fork_group(self.submit, prompt_ids, n,
                                    max_new_tokens, seed=seed, **kw)
        try:
            await_fork_group(handles, timeout, clock=self._clock)
        finally:
            for h in handles:
                self._untrack(h.request_id)
        return handles

    def _untrack(self, request_id: str) -> None:
        with self._lock:
            self._tracked.pop(request_id, None)

    def untrack(self, request_id: str) -> None:
        """Public untrack for callers that drive a `submit()` handle
        themselves instead of blocking in `generate_handle` — the SSE
        streaming path: the HTTP tier drains the handle's TokenStream
        and must drop the recovery-tracking entry when the stream ends
        (completed or client-disconnected), exactly like
        `generate_handle`'s finally does. Until then the request IS
        tracked: an engine crash mid-stream resubmits it and the
        token-identical re-decode resumes the stream seamlessly."""
        self._untrack(request_id)

    def _prune_done(self) -> None:
        """Drop finished requests nobody untracked (fire-and-forget
        `submit()` users) so the tracked set cannot grow unbounded."""
        with self._lock:
            for rid in [rid for rid, t in self._tracked.items()
                        if t.handle.done()]:
                del self._tracked[rid]

    # -- readiness / draining ----------------------------------------------
    @property
    def ready(self) -> bool:
        """`/readyz`: able to take traffic NOW — not stopping, not
        draining, not mid-recovery, engine loop alive and beating.

        Deliberately LOCK-FREE: ``self._lock`` is held for the whole of
        a recovery (backoff sleep + rebuild + warm-up compiles, seconds)
        and a readiness probe must answer "not ready" DURING that
        window, not block until it ends. Every read here is one
        GIL-atomic bool/ref load; a probe racing a flag flip returns the
        verdict from one instant earlier — exactly as correct for a
        poller."""
        if self._stopping or self._draining or self._recovering:  # graftlint: disable=CC005
            return False
        eng = self.engine  # graftlint: disable=CC005 — atomic ref read, see above
        if eng.crashed is not None:
            return False
        limit = (self.hang_timeout_s if eng.iterations > 0
                 else self.warmup_timeout_s)
        return (self._clock() - eng.heartbeat) <= limit

    def status(self) -> dict:
        """The `/readyz` body (and the UI's robustness line). Lock-free
        for the same reason as :attr:`ready` — each field is one
        GIL-atomic scalar/ref read, and a diagnostics snapshot one flag
        flip stale is fine; blocking /readyz on the seconds-long
        recovery lock hold is not."""
        eng = self.engine
        out = {
            "ready": self.ready,
            "draining": self._draining,
            "recovering": self._recovering,
            "degradation_level": self.degradation_level,  # graftlint: disable=CC005
            "restarts": self.restarts,  # graftlint: disable=CC005 — atomic int read, see docstring
            "heartbeat_age_s": round(self._clock() - eng.heartbeat, 3),
            "inflight": len(self._tracked),  # graftlint: disable=CC005 — atomic len(), see docstring
        }
        if self._slo is not None:
            # the BRIEF form: /readyz is polled constantly, and the
            # full snapshot sorts every route's window per call — the
            # per-route percentiles live on /info and /debug/engine
            out["slo"] = self._slo.brief()
        return out

    def drain(self, timeout: Optional[float] = None,
              poll_s: float = 0.02) -> bool:
        """Draining restart: stop admitting (readiness flips false),
        let in-flight work finish, swap in a fresh engine, resume.
        Returns False if ``timeout`` expired with work still in flight
        (admission resumes on the OLD engine — nothing was dropped)."""
        with self._lock:
            if self._draining or self._stopping:
                return False
            self._draining = True
            inflight0 = self.engine.inflight()
        self._g_ready.set(0)
        self.tracer.instant("drain_begin", track="supervisor",
                            args={"inflight": inflight0})
        t0 = self._clock()
        try:
            while True:
                with self._lock:
                    # the swap decision and the swap itself share one
                    # lock hold: no submit can slip into the old engine
                    # between "empty" and stop()
                    if self.engine.inflight() == 0 \
                            and not self.engine.crashed:
                        old = self.engine
                        old.stop()
                        self.engine = self._spawn_engine()
                        self.tracer.instant(
                            "drain_swap", track="supervisor",
                            args={"elapsed_s":
                                  round(self._clock() - t0, 3)})
                        return True
                    if self.engine.crashed:
                        # crashed mid-drain: fall back to crash recovery
                        # (it requeues the stragglers), then finish the
                        # drain pass on the fresh engine
                        self._draining = False
                        self._recover("crash", self.engine)
                        self._draining = True
                if timeout is not None and self._clock() - t0 > timeout:
                    return False
                self._sleep(poll_s)
        finally:
            with self._lock:
                self._draining = False
            if not self._stopping:
                self._g_ready.set(1)

    def drain_async(self) -> threading.Thread:
        """`POST /admin/drain`: kick a drain and return immediately
        (clients watch `/readyz` flip)."""
        th = threading.Thread(target=self.drain, daemon=True,
                              name="engine-drain")
        th.start()
        return th

    # -- teardown ----------------------------------------------------------
    def stop(self) -> None:
        """Fail-fast teardown: every tracked in-flight request gets a
        structured :class:`ShuttingDownError` (503 with its request_id)
        instead of hanging against a stopped engine, then the engine
        and watchdog go down."""
        self._stopping = True
        self._kick.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)
            self._watchdog = None
        with self._lock:
            for rid, t in list(self._tracked.items()):
                if not t.handle.done():
                    t.handle._finish(ShuttingDownError(rid))
            self._tracked.clear()
            self._g_ready.set(0)
            self.engine.stop()
