"""Model zoo: the reference benchmark configurations (BASELINE.md).

These are the workloads the reference is measured on: LeNet-MNIST, MLP-Iris,
AlexNet-CIFAR10, GravesLSTM char-RNN. Built through the same public config
DSL a user would use.
"""
from __future__ import annotations

from ..nn.conf.config import MultiLayerConfiguration, NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (BatchNormalization, ConvolutionLayer, DenseLayer,
                              GravesLSTM, LocalResponseNormalization,
                              OutputLayer, RnnOutputLayer, SubsamplingLayer)
from ..nn.updater.updaters import Adam, Nesterovs, Sgd


def lenet_mnist(seed: int = 123, lr: float = 0.01, dtype: str = "float32",
                height: int = 28, width: int = 28, channels: int = 1,
                n_classes: int = 10) -> MultiLayerConfiguration:
    """LeNet (BASELINE.md 'LeNet MNIST': Conv/Subsampling/Dense/Output, SGD)."""
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(Nesterovs(momentum=0.9))
            .regularization(True).l2(5e-4).dtype(dtype)
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                    activation="identity", weight_init="xavier"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="negativeloglikelihood"))
            .set_input_type(InputType.convolutional(height, width, channels))
            .build())


def mlp_iris(seed: int = 12345, lr: float = 0.1) -> MultiLayerConfiguration:
    """BASELINE.md 'MLP Iris': DenseLayer + OutputLayer, SGD."""
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(Sgd())
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())


def alexnet_cifar10(seed: int = 42, lr: float = 1e-3, dtype: str = "float32",
                    n_classes: int = 10) -> MultiLayerConfiguration:
    """Scaled-down AlexNet for 32x32 CIFAR-10
    (BASELINE.md 'AlexNet CIFAR-10': Conv + BatchNormalization, Adam)."""
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(Adam())
            .regularization(True).l2(1e-4).dtype(dtype)
            .list()
            .layer(ConvolutionLayer(n_out=64, kernel_size=(3, 3), stride=(1, 1),
                                    padding=(1, 1), activation="identity"))
            .layer(BatchNormalization(activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=128, kernel_size=(3, 3), padding=(1, 1),
                                    activation="identity"))
            .layer(BatchNormalization(activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3), padding=(1, 1),
                                    activation="identity"))
            .layer(BatchNormalization(activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=512, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="negativeloglikelihood"))
            .set_input_type(InputType.convolutional(32, 32, 3))
            .build())


def char_rnn_lstm(vocab_size: int = 77, hidden: int = 256, seed: int = 12345,
                  lr: float = 0.1, tbptt: int = 50,
                  dtype: str = "float32") -> MultiLayerConfiguration:
    """GravesLSTM char-RNN with truncated BPTT
    (BASELINE.md 'GravesLSTM char-RNN', Nesterovs updater)."""
    from ..nn.conf.config import BACKPROP_TBPTT
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(Nesterovs(momentum=0.9))
            .dtype(dtype)
            .list()
            .layer(GravesLSTM(n_in=vocab_size, n_out=hidden, activation="tanh"))
            .layer(GravesLSTM(n_in=hidden, n_out=hidden, activation="tanh"))
            .layer(RnnOutputLayer(n_in=hidden, n_out=vocab_size,
                                  activation="softmax", loss="mcxent"))
            .backprop_type(BACKPROP_TBPTT)
            .t_bptt_forward_length(tbptt).t_bptt_backward_length(tbptt)
            .build())
