"""Model zoo: the reference benchmark configurations (BASELINE.md).

These are the workloads the reference is measured on: LeNet-MNIST, MLP-Iris,
AlexNet-CIFAR10, GravesLSTM char-RNN. Built through the same public config
DSL a user would use.
"""
from __future__ import annotations

from typing import Optional

from ..nn.conf.config import MultiLayerConfiguration, NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (BatchNormalization, ConvolutionLayer, DenseLayer,
                              GravesLSTM, LocalResponseNormalization,
                              OutputLayer, RnnOutputLayer, SubsamplingLayer)
from ..nn.updater.updaters import Adam, Nesterovs, Sgd


def lenet_mnist(seed: int = 123, lr: float = 0.01, dtype: str = "float32",
                height: int = 28, width: int = 28, channels: int = 1,
                n_classes: int = 10) -> MultiLayerConfiguration:
    """LeNet (BASELINE.md 'LeNet MNIST': Conv/Subsampling/Dense/Output, SGD)."""
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(Nesterovs(momentum=0.9))
            .regularization(True).l2(5e-4).dtype(dtype)
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                    activation="identity", weight_init="xavier"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="negativeloglikelihood"))
            .set_input_type(InputType.convolutional(height, width, channels))
            .build())


def mlp_iris(seed: int = 12345, lr: float = 0.1) -> MultiLayerConfiguration:
    """BASELINE.md 'MLP Iris': DenseLayer + OutputLayer, SGD."""
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(Sgd())
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())


def alexnet_cifar10(seed: int = 42, lr: float = 1e-3, dtype: str = "float32",
                    n_classes: int = 10) -> MultiLayerConfiguration:
    """Scaled-down AlexNet for 32x32 CIFAR-10
    (BASELINE.md 'AlexNet CIFAR-10': Conv + BatchNormalization, Adam)."""
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(Adam())
            .regularization(True).l2(1e-4).dtype(dtype)
            .list()
            .layer(ConvolutionLayer(n_out=64, kernel_size=(3, 3), stride=(1, 1),
                                    padding=(1, 1), activation="identity"))
            .layer(BatchNormalization(activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=128, kernel_size=(3, 3), padding=(1, 1),
                                    activation="identity"))
            .layer(BatchNormalization(activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3), padding=(1, 1),
                                    activation="identity"))
            .layer(BatchNormalization(activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=512, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_out=n_classes, activation="softmax",
                               loss="negativeloglikelihood"))
            .set_input_type(InputType.convolutional(32, 32, 3))
            .build())


def char_rnn_lstm(vocab_size: int = 77, hidden: int = 256, seed: int = 12345,
                  lr: float = 0.1, tbptt: int = 50,
                  dtype: str = "float32") -> MultiLayerConfiguration:
    """GravesLSTM char-RNN with truncated BPTT
    (BASELINE.md 'GravesLSTM char-RNN', Nesterovs updater)."""
    from ..nn.conf.config import BACKPROP_TBPTT
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(Nesterovs(momentum=0.9))
            .dtype(dtype)
            .list()
            .layer(GravesLSTM(n_in=vocab_size, n_out=hidden, activation="tanh"))
            .layer(GravesLSTM(n_in=hidden, n_out=hidden, activation="tanh"))
            .layer(RnnOutputLayer(n_in=hidden, n_out=vocab_size,
                                  activation="softmax", loss="mcxent"))
            .backprop_type(BACKPROP_TBPTT)
            .t_bptt_forward_length(tbptt).t_bptt_backward_length(tbptt)
            .build())


def dbn_mnist(seed: int = 123, lr: float = 0.1, n_in: int = 784,
              n_classes: int = 10,
              hidden: tuple = (500, 250, 200)) -> MultiLayerConfiguration:
    """Deep Belief Network: stacked RBMs + softmax output.

    The reference's signature pretraining workload (stacked
    nn/conf/layers/RBM.java hidden layers trained with CD-k via
    nn/layers/feedforward/rbm/RBM.java:101 `contrastiveDivergence`, then
    supervised finetuning through MultiLayerNetwork.pretrain:165 /
    finetune:1331). ``net.fit(it)`` alone runs pretrain + finetune (the
    config sets ``pretrain(True)``); to drive the phases separately use
    ``net.pretrain(it)`` once then ``net.finetune(it)`` per epoch.
    """
    from ..nn.conf.layers import RBM
    b = (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(lr).updater(Sgd())
         .list().pretrain(True))
    prev = n_in
    for h in hidden:
        b.layer(RBM(n_in=prev, n_out=h, hidden_unit="binary",
                    visible_unit="binary", k=1, activation="sigmoid"))
        prev = h
    b.layer(OutputLayer(n_in=prev, n_out=n_classes, activation="softmax",
                        loss="negativeloglikelihood"))
    return b.build()


def deep_autoencoder_mnist(seed: int = 123, lr: float = 0.05,
                           n_in: int = 784, bottleneck: int = 30,
                           hidden: Optional[tuple] = None) -> MultiLayerConfiguration:
    """Hinton-style deep autoencoder: RBM encoder stack to a small code,
    mirrored decoder, sigmoid reconstruction with MSE.

    Mirrors the reference's deep-autoencoder configuration (stacked RBM
    layers pretrained layerwise, then end-to-end reconstruction finetuning;
    reference nn/layers/feedforward/autoencoder + RBM stack). The decoder
    half uses AutoEncoder layers so the whole net remains layerwise
    pretrainable.
    """
    from ..nn.conf.layers import RBM, AutoEncoder
    if hidden is None:
        # geometric taper n_in -> bottleneck over two hidden widths
        h1 = max(bottleneck, int(round((n_in ** 2 * bottleneck) ** (1 / 3))))
        h2 = max(bottleneck, int(round((n_in * bottleneck ** 2) ** (1 / 3))))
        hidden = (h1, h2)
    dims = [n_in, *hidden, bottleneck]
    b = (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(lr).updater(Sgd())
         .list().pretrain(True))
    for a, c in zip(dims[:-1], dims[1:]):
        b.layer(RBM(n_in=a, n_out=c, activation="sigmoid"))
    rev = list(reversed(dims))
    for a, c in zip(rev[:-1], rev[1:-1]):
        b.layer(AutoEncoder(n_in=a, n_out=c, activation="sigmoid"))
    b.layer(OutputLayer(n_in=dims[1], n_out=n_in, activation="sigmoid",
                        loss="mse"))
    return b.build()


def transformer_lm(vocab_size: int = 77, d_model: int = 128, n_heads: int = 4,
                   n_blocks: int = 2, ff_mult: int = 4, seed: int = 7,
                   lr: float = 3e-4, dtype: str = "float32",
                   rope: bool = False, n_kv_heads=None):
    """Decoder-only transformer language model as a ComputationGraph.

    No 0.4-era reference counterpart (pre-transformer codebase) — built from
    this framework's long-context pieces (SelfAttentionLayer + ring/Ulysses
    sequence parallelism in parallel/ring.py, LayerNormalization, residual
    ElementWise vertices). Input: one-hot [B, T, vocab]; output: next-token
    distribution per timestep. Pre-LN residual blocks:
        x = x + Attn(LN(x));  x = x + FFN(LN(x))
    """
    from ..nn.conf.graph import ElementWiseVertex
    from ..nn.conf.layers import LayerNormalization, SelfAttentionLayer
    gb = (NeuralNetConfiguration.builder()
          .seed(seed).learning_rate(lr).updater(Adam())
          .dtype(dtype)
          .graph_builder()
          .add_inputs("in")
          .add_layer("embed", DenseLayer(n_in=vocab_size, n_out=d_model,
                                         activation="identity"), "in"))
    prev = "embed"
    for i in range(n_blocks):
        gb.add_layer(f"ln{i}a", LayerNormalization(n_in=d_model, n_out=d_model,
                                                   activation="identity"),
                     prev)
        gb.add_layer(f"attn{i}",
                     SelfAttentionLayer(n_in=d_model, n_out=d_model,
                                        n_heads=n_heads, causal=True,
                                        rope=rope, n_kv_heads=n_kv_heads,
                                        activation="identity"), f"ln{i}a")
        gb.add_vertex(f"res{i}a", ElementWiseVertex(op="add"),
                      prev, f"attn{i}")
        gb.add_layer(f"ln{i}b", LayerNormalization(n_in=d_model, n_out=d_model,
                                                   activation="identity"),
                     f"res{i}a")
        gb.add_layer(f"ff{i}", DenseLayer(n_in=d_model,
                                          n_out=ff_mult * d_model,
                                          activation="gelu"), f"ln{i}b")
        gb.add_layer(f"ff{i}o", DenseLayer(n_in=ff_mult * d_model,
                                           n_out=d_model,
                                           activation="identity"), f"ff{i}")
        gb.add_vertex(f"res{i}b", ElementWiseVertex(op="add"),
                      f"res{i}a", f"ff{i}o")
        prev = f"res{i}b"
    gb.add_layer("ln_f", LayerNormalization(n_in=d_model, n_out=d_model,
                                            activation="identity"), prev)
    gb.add_layer("out", RnnOutputLayer(n_in=d_model, n_out=vocab_size,
                                       activation="softmax", loss="mcxent"),
                 "ln_f")
    gb.set_outputs("out")
    return gb.build()
