"""Autoregressive generation utilities for the language models.

No reference counterpart (the 0.4-era codebase predates LM sampling); the
char-RNN example's greedy loop (reference-era GravesLSTM demos sample this
way) generalized to temperature / top-k sampling for both the stateful
recurrent nets (`rnn_time_step`) and the transformer ComputationGraph
(full-context re-forward per token).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def _sample_logits(probs: np.ndarray, temperature: float, top_k: Optional[int],
                   rng: np.random.Generator,
                   top_p: Optional[float] = None,
                   allow: Optional[np.ndarray] = None) -> int:
    """Pick a token id from one probability row [V]. ``top_p`` (nucleus
    sampling) keeps the smallest set of tokens whose cumulative probability
    reaches p; composes with top_k (both filters apply).

    ``allow`` (bool [V], grammar-constrained decoding —
    `inference/logitproc.py`): forbidden tokens get ``-inf`` logits, so
    their sampling probability is EXACTLY zero (``exp(-inf) == 0``; one
    `rng.choice` draw either way, so the RNG stream stays in lockstep
    with unconstrained decode). An all-True mask leaves every value
    untouched — an admit-everything grammar is token-identical to
    ``allow=None`` by construction. The caller guarantees at least one
    allowed token (the engine finishes a grammar-exhausted request
    before sampling)."""
    if temperature <= 0.0:  # greedy
        if allow is not None:
            # probs are softmax outputs (>= 0): -1 can never win argmax
            return int(np.where(allow, probs, -1.0).argmax())
        return int(probs.argmax())
    logits = np.log(np.maximum(probs, 1e-30)) / temperature
    if allow is not None:
        logits = np.where(allow, logits, -np.inf)
    if top_k is not None and top_k > 0 and top_k < logits.shape[-1]:
        cutoff = np.partition(logits, -top_k)[-top_k]
        logits = np.where(logits >= cutoff, logits, -np.inf)
    if top_p is not None and 0.0 < top_p < 1.0:
        order = np.argsort(logits)[::-1]
        lmax = logits[order[0]]
        ps = np.exp(logits[order] - lmax)
        ps /= ps.sum()
        keep_n = int(np.searchsorted(np.cumsum(ps), top_p) + 1)
        drop = order[keep_n:]
        logits[drop] = -np.inf
    logits = logits - logits.max()
    p = np.exp(logits)
    p /= p.sum()
    return int(rng.choice(p.shape[-1], p=p))


# public SPI: the serving decode scheduler (inference/engine.py) selects
# tokens through the SAME function the solo generators use, which is what
# makes engine output token-identical to generate_transformer/generate_rnn
# for a given seed — one sampling definition, two decode loops
sample_logits = _sample_logits


def generate_transformer(net, prompt_ids: Sequence[int], n_tokens: int,
                         vocab_size: int, *, temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None, seed: int = 0,
                         max_context: Optional[int] = None,
                         use_cache: bool = False) -> list:
    """Continue `prompt_ids` by `n_tokens` using a transformer_lm
    ComputationGraph (one-hot input, next-token distribution per step).

    use_cache=False re-forwards the full (optionally truncated) context per
    token; use_cache=True streams through the attention KV cache
    (`rnn_time_step`: prefill the prompt once, then O(cache) per token —
    requires causal attention and prompt+tokens <= max_cache_len)."""
    if not len(prompt_ids):
        raise ValueError("prompt_ids must be non-empty (the model needs at "
                         "least one token of context)")
    if use_cache and max_context is not None:
        raise ValueError("max_context (sliding window) is not supported "
                         "with use_cache=True: the KV cache never evicts; "
                         "use the re-forward path for windowed generation")
    rng = np.random.default_rng(seed)

    def onehot(ctx):
        ctx = np.asarray(ctx, dtype=np.int64)
        x = np.zeros((1, len(ctx), vocab_size), np.float32)  # O(T*V), not
        x[0, np.arange(len(ctx)), ctx] = 1.0                 # an eye(V)
        return x

    out = []
    if use_cache:
        # NOTE: this resets (and on exit clears) the net's streaming KV
        # state — callers interleaving their own rnn_time_step streaming
        # must not share `net` with cached generation (ADVICE r3).
        needed = len(prompt_ids) + max(n_tokens - 1, 0)
        layer_confs = list(getattr(net.conf, "layers", []) or [])
        for v in getattr(net.conf, "vertices", {}).values():  # graph nets
            if getattr(v, "layer", None) is not None:
                layer_confs.append(v.layer)
        for conf in layer_confs:
            cap = getattr(conf, "max_cache_len", None)
            if (type(conf).__name__ == "SelfAttentionLayer"
                    and cap is not None and needed > int(cap)):
                raise ValueError(
                    f"prompt ({len(prompt_ids)}) + n_tokens ({n_tokens}) "
                    f"needs a KV cache of {needed} but max_cache_len="
                    f"{int(cap)}; raise max_cache_len or generate fewer "
                    f"tokens (checked upfront so no tokens are consumed "
                    f"before the failure)")
        net.rnn_clear_previous_state()
        try:
            probs = np.asarray(
                net.rnn_time_step(onehot(prompt_ids))[0])[0, -1]
            for i in range(n_tokens):
                nxt = _sample_logits(probs, temperature, top_k, rng, top_p)
                out.append(nxt)
                if i + 1 < n_tokens:  # the final token needs no forward pass
                    probs = np.asarray(
                        net.rnn_time_step(onehot([nxt]))[0])[0, -1]
        finally:
            net.rnn_clear_previous_state()
        return out
    ids = list(int(i) for i in prompt_ids)
    for _ in range(n_tokens):
        ctx = np.asarray(ids if max_context is None else ids[-max_context:])
        probs = np.asarray(net.output(onehot(ctx))[0])[0, -1]
        nxt = _sample_logits(probs, temperature, top_k, rng, top_p)
        ids.append(nxt)
        out.append(nxt)
    return out


def generate_rnn(net, prompt_ids: Sequence[int], n_tokens: int,
                 vocab_size: int, *, temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None, seed: int = 0) -> list:
    """Continue `prompt_ids` by `n_tokens` with a recurrent
    MultiLayerNetwork via stateful O(1)-memory `rnn_time_step`
    (reference rnnTimeStep:1460 streaming inference)."""
    if not len(prompt_ids):
        raise ValueError("prompt_ids must be non-empty (the model needs at "
                         "least one token of context)")
    rng = np.random.default_rng(seed)
    net.rnn_clear_previous_state()

    def step(tok):
        x = np.zeros((1, 1, vocab_size), np.float32)
        x[0, 0, int(tok)] = 1.0
        return np.asarray(net.rnn_time_step(x))

    for tok in prompt_ids:  # prime the state one step at a time
        probs = step(tok)
    out = []
    for _ in range(n_tokens):
        row = probs[0, -1] if probs.ndim == 3 else probs[0]
        nxt = _sample_logits(row, temperature, top_k, rng, top_p)
        out.append(nxt)
        probs = step(nxt)
    return out
