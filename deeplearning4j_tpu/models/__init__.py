"""Model zoo: the reference benchmark configurations plus the long-context
transformer this framework adds (see ``models/zoo.py``)."""
from .sampling import generate_rnn, generate_transformer
from .zoo import (alexnet_cifar10, char_rnn_lstm, dbn_mnist,
                  deep_autoencoder_mnist, lenet_mnist, mlp_iris,
                  transformer_lm)

__all__ = [
    "alexnet_cifar10", "char_rnn_lstm", "dbn_mnist",
    "deep_autoencoder_mnist", "lenet_mnist", "mlp_iris", "transformer_lm",
    "generate_rnn", "generate_transformer",
]
