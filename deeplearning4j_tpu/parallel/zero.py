"""Cross-replica weight-update (optimizer-state) sharding — ZeRO stage 1.

Beyond the reference (whose distributed story is parameter averaging), after
the technique in "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv:2004.13336, the PAPERS.md pointer; the same
dataflow ZeRO-1 popularized): in data-parallel training every replica holds
a full copy of the optimizer state and performs the identical weight
update. Sharding the optimizer state across the data axis makes each
replica update only its shard — optimizer memory drops ~n-fold (for Adam
that is 2/3 of training-state bytes beyond the params) and the update
compute parallelizes, at the cost of collecting updated params.

TPU-native mechanics: this is PURE SHARDING ANNOTATION. The updater-state
pytree is placed with each tensor sharded along the data axis on its
largest divisible dimension; `IciDataParallelTrainingMaster` keeps
pre-annotated shardings (trainer.py `keep_or_repl`), and GSPMD partitions
the update math to match — the gradient psum, per-shard update, and the
gather of updated params all fall out of XLA's propagation, no hand-written
collectives. Golden-equal to unsharded training (tests).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, default_mesh


def shard_updater_state(net, mesh: Optional[Mesh] = None,
                        axis: str = DATA_AXIS):
    """Annotate `net.updater_state` for cross-replica update sharding.

    Each state tensor is sharded along `axis` on its LARGEST dimension
    divisible by the axis size; tensors with no divisible dimension (small
    biases, scalars) stay replicated — a partial shard is still most of the
    memory win, since the big tensors are exactly the divisible ones.

    Call after `net.init()` (or after `resume()`), before training with
    `IciDataParallelTrainingMaster`. Returns (sharded_leaves, total_leaves).
    """
    mesh = mesh or default_mesh()
    n = mesh.shape[axis]
    stats = [0, 0]

    def place(a):
        a = jnp.asarray(a)
        stats[1] += 1
        if n > 1 and a.ndim:
            dims = sorted(range(a.ndim), key=lambda d: -a.shape[d])
            for d in dims:
                if a.shape[d] >= n and a.shape[d] % n == 0:
                    spec = [None] * a.ndim
                    spec[d] = axis
                    stats[0] += 1
                    return jax.device_put(a, NamedSharding(mesh, P(*spec)))
        return jax.device_put(a, NamedSharding(mesh, P()))

    net.updater_state = jax.tree_util.tree_map(place, net.updater_state)
    return stats[0], stats[1]


def updater_state_bytes_per_device(net) -> int:
    """Optimizer-state bytes resident on ONE device — the number the
    sharding shrinks (addressable shard sizes, not logical sizes)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(net.updater_state):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            d = shards[0].data
            total += d.size * d.dtype.itemsize
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total
