"""Distributed data-parallel training: TrainingMaster SPI + ICI-collective impls.

Capability parity with the reference's distributed stack (SURVEY.md §2.4):
  - `TrainingMaster`/`TrainingWorker` SPI
    (spark/dl4j-spark/.../spark/api/TrainingMaster.java, TrainingWorker.java)
  - `ParameterAveragingTrainingMaster.java:50` — the synchronous
    parameter-averaging algorithm (executeTraining:159 / doIteration:183 /
    processResults:352: sum params across workers, divide, set on driver)
  - `parallelism/ParallelWrapper.java` — in-process multi-device DP with
    per-thread model clones and periodic averaging (:95, :232-237)

TPU-first redesign (per SURVEY.md §3.2 'TPU mapping'): the Spark
mapPartitions -> aggregate round trip becomes collectives over ICI inside ONE
jit-compiled program:
  - `IciDataParallelTrainingMaster` — gradient all-reduce EVERY step. The
    batch is sharded over the mesh's "data" axis; parameters stay replicated;
    XLA's GSPMD partitioner inserts the psum. This is the fast path (no
    param broadcast round trips, no host hops — pure ICI).
  - `ParameterAveragingTrainingMaster` — keeps the reference's
    `averagingFrequency` semantics exactly: each device runs N independent
    local updates (shard_map), then parameters AND updater state are pmean'd
    (reference aggregates updater state via UpdaterAggregator). Used for the
    golden distributed-vs-single-machine equivalence test
    (TestCompareParameterAveragingSparkVsSingleMachine.java:35).
Multi-host: the same code runs under jax.distributed with a global mesh —
ICI within a slice, DCN across slices — no NCCL/MPI analog needed.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, default_mesh
from .stats import SparkTrainingStats, phase_timer
from ..datasets.dataset import DataSet


class TrainingMaster:
    """SPI (reference spark/api/TrainingMaster.java)."""

    def execute_training(self, net, iterator) -> None:
        raise NotImplementedError

    def get_training_stats(self) -> Optional[SparkTrainingStats]:
        return None


def _tree_put(tree, sharding):
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), tree)


def _require_multilayer(net):
    from ..nn.multilayer import MultiLayerNetwork
    if not isinstance(net, MultiLayerNetwork):
        raise TypeError(
            f"TrainingMaster implementations currently support MultiLayerNetwork "
            f"only (got {type(net).__name__}); ComputationGraph distributed "
            f"training is not yet wired")


class IciDataParallelTrainingMaster(TrainingMaster):
    """Per-step gradient all-reduce over ICI (the TPU-native fast path).

    Parameters are replicated over the mesh, each global batch is sharded on
    the data axis, and the batch-mean loss makes GSPMD insert a single psum
    per step — the reference's params.divi(aggCount) driver round trip
    (ParameterAveragingTrainingMaster.java:358-380) collapses into it.
    """

    def __init__(self, mesh: Optional[Mesh] = None, collect_stats: bool = False):
        self.mesh = mesh or default_mesh()
        self.stats = SparkTrainingStats() if collect_stats else None

    def execute_training(self, net, iterator) -> None:
        _require_multilayer(net)
        net._check_init()
        repl = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P(DATA_AXIS))
        net.params = _tree_put(net.params, repl)
        net.variables = _tree_put(net.variables, repl)
        net.updater_state = _tree_put(net.updater_state, repl)
        n_dev = self.mesh.size
        for ds in iterator:
            with phase_timer(self.stats, "data_fetch"):
                x = np.asarray(ds.features)
                y = np.asarray(ds.labels)
                fm = getattr(ds, "features_mask", None)
                lm = getattr(ds, "labels_mask", None)
                if x.shape[0] % n_dev:
                    # Pad to a divisible batch with cyclic duplicates (keeps
                    # BatchNorm batch statistics on-distribution) but give the
                    # padded rows ZERO loss weight via the labels mask, so the
                    # per-example mean is unbiased — the reference's
                    # balancedRandomSplit never double-counts an example.
                    orig = x.shape[0]
                    need = -(-orig // n_dev) * n_dev
                    idx = np.arange(need) % orig
                    x = x[idx]
                    y = y[idx]
                    fm = fm[idx] if fm is not None else None
                    if lm is None:
                        lm_shape = (need,) if y.ndim == 2 else (need, y.shape[1])
                        lm = np.ones(lm_shape, np.float32)
                    else:
                        lm = np.asarray(lm)[idx].astype(np.float32, copy=True)
                    lm[orig:] = 0.0
                xs = jax.device_put(jnp.asarray(x), shard)
                ys = jax.device_put(jnp.asarray(y), shard)
                fms = jax.device_put(jnp.asarray(fm), shard) if fm is not None else None
                lms = jax.device_put(jnp.asarray(lm), shard) if lm is not None else None
            with phase_timer(self.stats, "process_minibatch"):
                step_fn = net._get_train_step((fms is not None, lms is not None, False))
                net._key, sub = jax.random.split(net._key)
                (net.params, net.variables, net.updater_state, loss,
                 _) = step_fn(net.params, net.variables, net.updater_state,
                              jnp.asarray(net.step), sub, xs, ys, fms, lms, None)
                net.score_ = float(loss)
                net.step += 1
            for listener in net.listeners:
                listener.iteration_done(net, net.step)

    def get_training_stats(self):
        return self.stats


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Reference-semantics parameter averaging (ParameterAveragingTrainingMaster.java:50).

    Each of the mesh's `data`-axis devices is a "worker" holding its own
    parameter copy; every `averaging_frequency` minibatches, params + updater
    state are pmean'd over ICI. averaging_frequency=1 with n workers is
    mathematically the reference's synchronous averaging; higher frequencies
    reproduce the exact drift-and-average behavior (and its convergence
    characteristics) the reference exposes.
    """

    def __init__(self, batch_size_per_worker: int = 16, averaging_frequency: int = 1,
                 mesh: Optional[Mesh] = None, collect_stats: bool = False):
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = max(1, averaging_frequency)
        self.mesh = mesh or default_mesh()
        self.stats = SparkTrainingStats() if collect_stats else None

    # -- the shard_map'd worker round ------------------------------------------
    def _get_round_fn(self, net):
        _require_multilayer(net)
        # cache on the net itself so the compiled round's lifetime (and its
        # closure over the net's layers) is tied to that net
        key = ("pa_round", self.averaging_frequency, self.mesh.shape_tuple)
        if key in net._jit_cache:
            return net._jit_cache[key]
        raw_step = net._build_train_step((False, False, False))
        mesh = self.mesh

        def worker_round(params, variables, ustates, step, rng, xs, ys, ls):
            # local views: [1, N, b, ...] -> scan over N minibatches; ls is the
            # per-example loss weight (zero on rows tiled in to fill the round)
            xs_l = xs[0]
            ys_l = ys[0]
            ls_l = ls[0]
            widx = jax.lax.axis_index(DATA_AXIS)
            wrng = jax.random.fold_in(rng, widx)

            def body(carry, batch):
                p, v, u, s = carry
                x, y, m, i = batch
                srng = jax.random.fold_in(wrng, i)  # fresh dropout per local step
                np_, nv, nu, loss, _ = raw_step(p, v, u, s, srng, x, y, None, m, None)
                # a minibatch that is 100% zero-weight fill must be a true
                # no-op: stateful updaters (momentum/Adam) would otherwise
                # move params and advance schedules on padding-only data
                wsum = jnp.sum(m)
                active = wsum > 0
                sel = lambda a, b: jnp.where(active, a, b)  # noqa: E731
                p = jax.tree_util.tree_map(sel, np_, p)
                v = jax.tree_util.tree_map(sel, nv, v)
                u = jax.tree_util.tree_map(sel, nu, u)
                s = s + active.astype(s.dtype)
                return (p, v, u, s), (loss, wsum)

            n_local = xs_l.shape[0]
            (p, v, u, s), (losses, wsums) = jax.lax.scan(
                body, (params, variables, ustates, step),
                (xs_l, ys_l, ls_l, jnp.arange(n_local)))
            # parameter + updater-state averaging over the data axis
            # (reference processResults:352 aggregate-sum + divi, plus
            #  UpdaterAggregator for updater state)
            p = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, DATA_AXIS), p)
            v = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, DATA_AXIS), v)
            u = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, DATA_AXIS), u)
            # example-weighted round loss: fill minibatches carry zero weight
            loss_sum = jax.lax.psum(jnp.sum(losses * wsums), DATA_AXIS)
            w_sum = jax.lax.psum(jnp.sum(wsums), DATA_AXIS)
            loss = loss_sum / jnp.maximum(w_sum, 1.0)
            return p, v, u, loss

        pspec = jax.tree_util.tree_map(lambda _: P(), net.params)
        vspec = jax.tree_util.tree_map(lambda _: P(), net.variables)
        uspec = jax.tree_util.tree_map(lambda _: P(), net.updater_state)
        fn = jax.jit(jax.shard_map(
            worker_round, mesh=mesh,
            in_specs=(pspec, vspec, uspec, P(), P(), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=(pspec, vspec, uspec, P()),
            check_vma=False,
        ))
        net._jit_cache[key] = fn
        return fn

    def execute_training(self, net, iterator) -> None:
        net._check_init()
        n_dev = self.mesh.size
        b = self.batch_size_per_worker
        n = self.averaging_frequency
        round_fn = self._get_round_fn(net)
        buf_x: List[np.ndarray] = []
        buf_y: List[np.ndarray] = []

        def flush():
            if not buf_x:
                return
            x = np.concatenate(buf_x)
            y = np.concatenate(buf_y)
            buf_x.clear()
            buf_y.clear()
            need = n_dev * n * b
            orig = x.shape[0]
            if orig < need:
                # Partial round: mirror the reference's balancedRandomSplit —
                # spread the real rows EVENLY over the workers (round-robin)
                # so no worker idles, and zero-weight the fill rows so they
                # contribute no gradient. Static shapes are preserved.
                reps = int(np.ceil(need / orig))
                x = np.tile(x, (reps,) + (1,) * (x.ndim - 1))[:need]
                y = np.tile(y, (reps,) + (1,) * (y.ndim - 1))[:need]
            elif orig > need:  # carry the remainder into the next round
                buf_x.append(x[need:])
                buf_y.append(y[need:])
                x = x[:need]
                y = y[:need]
            lmask = np.ones((need,) if y.ndim == 2 else (need, y.shape[1]),
                            np.float32)
            lmask[min(orig, need):] = 0.0
            if orig < need:
                # row i -> worker i % n_dev: real rows land on every worker
                perm = (np.arange(need).reshape(n * b, n_dev).T.reshape(-1))
                x, y, lmask = x[perm], y[perm], lmask[perm]
            xs = x.reshape((n_dev, n, b) + x.shape[1:])
            ys = y.reshape((n_dev, n, b) + y.shape[1:])
            ls = lmask.reshape((n_dev, n, b) + lmask.shape[1:])
            with phase_timer(self.stats, "aggregate_round"):
                net._key, sub = jax.random.split(net._key)
                with self.mesh:
                    (net.params, net.variables, net.updater_state,
                     loss) = round_fn(net.params, net.variables, net.updater_state,
                                      jnp.asarray(net.step), sub,
                                      jnp.asarray(xs), jnp.asarray(ys),
                                      jnp.asarray(ls))
                net.score_ = float(loss)
                net.step += n
            for listener in net.listeners:
                listener.iteration_done(net, net.step)

        with phase_timer(self.stats, "total_training"):
            for ds in iterator:
                with phase_timer(self.stats, "data_fetch"):
                    buf_x.append(np.asarray(ds.features))
                    buf_y.append(np.asarray(ds.labels))
                have = sum(a.shape[0] for a in buf_x)
                if have >= n_dev * n * b:
                    flush()
            while buf_x:
                flush()

    def get_training_stats(self):
        return self.stats


class ParallelWrapper:
    """In-process multi-device data parallelism
    (reference parallelism/ParallelWrapper.java: N trainer threads with
    clone()d models, round-robin dispatch, averaging every
    `averagingFrequency` iterations :95). Here the "threads" are mesh
    devices and the dispatch/averaging is one shard_map program.
    """

    def __init__(self, net, workers: Optional[int] = None,
                 averaging_frequency: int = 1, batch_size_per_worker: int = 32,
                 prefetch_buffer: int = 2):
        self.net = net
        n = workers or len(jax.devices())
        self.master = ParameterAveragingTrainingMaster(
            batch_size_per_worker=batch_size_per_worker,
            averaging_frequency=averaging_frequency,
            mesh=default_mesh(n))
        self.prefetch_buffer = prefetch_buffer

    def fit(self, iterator):
        from ..datasets.iterators import AsyncDataSetIterator
        if self.prefetch_buffer > 0:
            iterator = AsyncDataSetIterator(iterator, self.prefetch_buffer)
        self.master.execute_training(self.net, iterator)
        return self.net
