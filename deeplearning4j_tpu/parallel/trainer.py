"""Distributed data-parallel training: TrainingMaster SPI + ICI-collective impls.

Capability parity with the reference's distributed stack (SURVEY.md §2.4):
  - `TrainingMaster`/`TrainingWorker` SPI
    (spark/dl4j-spark/.../spark/api/TrainingMaster.java, TrainingWorker.java)
  - `ParameterAveragingTrainingMaster.java:50` — the synchronous
    parameter-averaging algorithm (executeTraining:159 / doIteration:183 /
    processResults:352: sum params across workers, divide, set on driver)
  - `parallelism/ParallelWrapper.java` — in-process multi-device DP with
    per-thread model clones and periodic averaging (:95, :232-237)

TPU-first redesign (per SURVEY.md §3.2 'TPU mapping'): the Spark
mapPartitions -> aggregate round trip becomes collectives over ICI inside ONE
jit-compiled program:
  - `IciDataParallelTrainingMaster` — gradient all-reduce EVERY step. The
    batch is sharded over the mesh's "data" axis; parameters stay replicated;
    XLA's GSPMD partitioner inserts the psum. This is the fast path (no
    param broadcast round trips, no host hops — pure ICI).
  - `ParameterAveragingTrainingMaster` — keeps the reference's
    `averagingFrequency` semantics exactly: each device runs N independent
    local updates (shard_map), then parameters AND updater state are pmean'd
    (reference aggregates updater state via UpdaterAggregator). Used for the
    golden distributed-vs-single-machine equivalence test
    (TestCompareParameterAveragingSparkVsSingleMachine.java:35).
Multi-host: the same code runs under jax.distributed with a global mesh —
ICI within a slice, DCN across slices — no NCCL/MPI analog needed.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, default_mesh
from .stats import SparkTrainingStats, phase_timer


class TrainingMaster:
    """SPI (reference spark/api/TrainingMaster.java)."""

    def execute_training(self, net, iterator) -> None:
        raise NotImplementedError

    def get_training_stats(self) -> Optional[SparkTrainingStats]:
        return None


def _tree_put(tree, sharding):
    if jax.process_count() > 1:
        # multi-process (multi-host): route through host memory — a
        # process-local jax.Array source is not addressable everywhere,
        # but every process can contribute shards from the same numpy value
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), sharding), tree)
    # single-process: direct device-to-device resharding (often a no-op)
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), tree)


def _is_graph(net) -> bool:
    from ..nn.graph import ComputationGraph
    return isinstance(net, ComputationGraph)


def _as_lists(ds):
    """Normalize a DataSet/MultiDataSet to (inputs, labels, fmasks, lmasks)
    lists — one entry per network input/output (reference MultiDataSet)."""
    if hasattr(ds, "features_masks"):  # MultiDataSet
        return (list(ds.features), list(ds.labels),
                list(ds.features_masks) if ds.features_masks else None,
                list(ds.labels_masks) if ds.labels_masks else None)
    fm = getattr(ds, "features_mask", None)
    lm = getattr(ds, "labels_mask", None)
    return ([ds.features], [ds.labels],
            [fm] if fm is not None else None,
            [lm] if lm is not None else None)


def _ones_lmask(y, need: int, orig: int) -> np.ndarray:
    """Per-example loss weights: 1 for real rows, 0 for fill rows beyond
    orig. Shape [need] for 2-D labels, [need, T] for time series."""
    m = np.ones((need,) if y.ndim == 2 else (need, y.shape[1]), np.float32)
    m[min(orig, need):] = 0.0
    return m


def _unified_step(net, has_fm: bool, has_lm: bool, in_scan: bool = False):
    """A facade-independent pure train step
    (params, variables, ustates, step, rng, inputs, labels, fmasks, lmasks)
    -> (params, variables, ustates, loss) with list-typed inputs/labels/masks
    — lets both masters drive MultiLayerNetwork AND ComputationGraph
    (reference SparkDl4jMultiLayer + SparkComputationGraph.java:63,133).
    ``in_scan``: the caller traces this step inside a lax.scan body (remat
    drops its CSE barriers there; see nn/layers/base.remat_forward)."""
    if _is_graph(net):
        raw = net._build_train_step(in_scan=in_scan)
        in_names = net.conf.network_inputs

        def step(p, v, u, s, rng, inputs, labels, fmasks, lmasks):
            fmd = dict(zip(in_names, fmasks)) if fmasks is not None else None
            return raw(p, v, u, s, rng, inputs, labels, fmd, lmasks)
        return step

    raw = net._build_train_step((has_fm, has_lm, False), in_scan=in_scan)

    def step(p, v, u, s, rng, inputs, labels, fmasks, lmasks):
        np_, nv, nu, loss, _ = raw(
            p, v, u, s, rng, inputs[0], labels[0],
            fmasks[0] if fmasks is not None else None,
            lmasks[0] if lmasks is not None else None, None)
        return np_, nv, nu, loss
    return step


def _pad_ragged(inputs, labels, fmasks, lmasks, n_dev):
    """Pad batch axis to a multiple of n_dev with cyclic duplicates carrying
    ZERO loss weight (see IciDataParallelTrainingMaster)."""
    orig = inputs[0].shape[0]
    if orig % n_dev == 0:
        return inputs, labels, fmasks, lmasks
    need = -(-orig // n_dev) * n_dev
    idx = np.arange(need) % orig
    inputs = [a[idx] for a in inputs]
    labels = [a[idx] for a in labels]
    if fmasks is not None:
        fmasks = [np.asarray(m)[idx] if m is not None else None for m in fmasks]
    if lmasks is None:
        lmasks = [None] * len(labels)
    out_lm = []
    for y, m in zip(labels, lmasks):
        if m is None:
            m = _ones_lmask(y, need, orig)
        else:
            m = np.asarray(m)[idx].astype(np.float32, copy=True)
            m[orig:] = 0.0
        out_lm.append(m)
    return inputs, labels, fmasks, out_lm


class IciDataParallelTrainingMaster(TrainingMaster):
    """Per-step gradient all-reduce over ICI (the TPU-native fast path).

    Parameters are replicated over the mesh, each global batch is sharded on
    the data axis, and the batch-mean loss makes GSPMD insert a single psum
    per step — the reference's params.divi(aggCount) driver round trip
    (ParameterAveragingTrainingMaster.java:358-380) collapses into it.
    """

    def __init__(self, mesh: Optional[Mesh] = None, collect_stats: bool = False,
                 state_tracker=None):
        self.mesh = mesh or default_mesh()
        self.stats = SparkTrainingStats() if collect_stats else None
        # fault tolerance: periodic atomic checkpoints (statetracker.py)
        self.state_tracker = state_tracker
        self._batches_done = 0
        self._skip = 0

    def _get_step(self, net, has_fm: bool, has_lm: bool):
        key = ("ici_step", has_fm, has_lm)
        if key not in net._jit_cache:
            net._jit_cache[key] = jax.jit(_unified_step(net, has_fm, has_lm),
                                          donate_argnums=(0, 2))
        return net._jit_cache[key]

    def resume(self, net) -> int:
        """Restore the newest checkpoint into `net`; returns how many
        leading batches of the SAME data sequence execute_training should
        skip (the redelivery semantics of StateTracker.java:122-129)."""
        if self.state_tracker is None:
            return 0
        cursor = self.state_tracker.restore(net) or {}
        skip = int(cursor.get("master_batches", 0))
        self._batches_done = skip
        self._skip = skip
        return skip

    def execute_training(self, net, iterator) -> None:
        net._check_init()
        repl = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P(DATA_AXIS))

        def keep_or_repl(a):
            # DP x TP composition: arrays already annotated on THIS mesh
            # (e.g. by parallel.tensor_parallel.shard_transformer_tp) keep
            # their sharding; everything else replicates. Blanket
            # replication here used to silently strip TP annotations.
            s = getattr(a, "sharding", None)
            if isinstance(s, NamedSharding) and s.mesh == self.mesh:
                return a
            return jax.device_put(np.asarray(a), repl) \
                if jax.process_count() > 1 else jax.device_put(a, repl)

        net.params = jax.tree_util.tree_map(keep_or_repl, net.params)
        net.variables = _tree_put(net.variables, repl)
        net.updater_state = jax.tree_util.tree_map(keep_or_repl,
                                                   net.updater_state)
        n_dev = self.mesh.size
        # resumed run: skip the batches already trained before the restored
        # checkpoint (call resume(net) first; the iterator must replay the
        # same sequence)
        skip = self._skip
        self._skip = 0
        for ds in iterator:
            if skip > 0:
                skip -= 1
                continue
            with phase_timer(self.stats, "data_fetch"):
                inputs, labels, fms, lms = _as_lists(ds)
                inputs = [np.asarray(a) for a in inputs]
                labels = [np.asarray(a) for a in labels]
                # Ragged batches: pad with cyclic duplicates (keeps BatchNorm
                # batch statistics on-distribution) carrying ZERO loss weight,
                # so the per-example mean is unbiased — the reference's
                # balancedRandomSplit never double-counts an example.
                inputs, labels, fms, lms = _pad_ragged(inputs, labels,
                                                       fms, lms, n_dev)

                def put(a):
                    # numpy source: valid for global shardings multi-process
                    return (jax.device_put(np.asarray(a), shard)
                            if a is not None else None)
                xs = [put(a) for a in inputs]
                ys = [put(a) for a in labels]
                fmss = [put(m) for m in fms] if fms is not None else None
                lmss = [put(m) for m in lms] if lms is not None else None
            with phase_timer(self.stats, "process_minibatch"):
                step_fn = self._get_step(net, fmss is not None, lmss is not None)
                net._key, sub = jax.random.split(net._key)
                (net.params, net.variables, net.updater_state,
                 loss) = step_fn(net.params, net.variables, net.updater_state,
                                 jnp.asarray(net.step), sub, xs, ys, fmss, lmss)
                net.score_ = loss  # lazily fetched (see MultiLayerNetwork.score_)
                net.step += 1
            for listener in net.listeners:
                listener.iteration_done(net, net.step)
            self._batches_done += 1
            if self.state_tracker is not None:
                self.state_tracker.batch_done(
                    net, {"master_batches": self._batches_done})
        if self.state_tracker is not None:
            # async trackers: the last checkpoint must be durable (and any
            # background write error must surface) before fit returns
            self.state_tracker.wait()

    def get_training_stats(self):
        return self.stats


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Reference-semantics parameter averaging (ParameterAveragingTrainingMaster.java:50).

    Each of the mesh's `data`-axis devices is a "worker" holding its own
    parameter copy; every `averaging_frequency` minibatches, params + updater
    state are pmean'd over ICI. averaging_frequency=1 with n workers is
    mathematically the reference's synchronous averaging; higher frequencies
    reproduce the exact drift-and-average behavior (and its convergence
    characteristics) the reference exposes.
    """

    def __init__(self, batch_size_per_worker: int = 16, averaging_frequency: int = 1,
                 mesh: Optional[Mesh] = None, collect_stats: bool = False,
                 state_tracker=None):
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = max(1, averaging_frequency)
        self.mesh = mesh or default_mesh()
        self.stats = SparkTrainingStats() if collect_stats else None
        # fault tolerance: checkpoint at averaging-round boundaries — the
        # consistent cut where params/updater state are globally agreed.
        # NOTE: these checkpoints restore MODEL state (params/updater/step);
        # data-cursor replay for this master is driver-level — use
        # statetracker.fit_with_recovery, which owns the cursor (and
        # disables this master-side hook while driving)
        self.state_tracker = state_tracker
        self._rounds_done = 0

    # -- the shard_map'd worker round ------------------------------------------
    def _get_round_fn(self, net, has_fm: bool):
        # cache on the net itself so the compiled round's lifetime (and its
        # closure over the net's layers) is tied to that net
        key = ("pa_round", self.averaging_frequency, self.mesh.shape_tuple,
               has_fm)
        if key in net._jit_cache:
            return net._jit_cache[key]
        raw_step = _unified_step(net, has_fm, True, in_scan=True)
        mesh = self.mesh

        def worker_round(params, variables, ustates, step, rng, xs, ys, fs, ls):
            # local views: lists of [1, N, b, ...] -> scan over N minibatches;
            # fs carries feature masks (or None), ls the per-example loss
            # weights (zero on rows tiled in to fill the round)
            xs_l = [a[0] for a in xs]
            ys_l = [a[0] for a in ys]
            fs_l = ([f[0] if f is not None else None for f in fs]
                    if fs is not None else None)
            ls_l = [m[0] for m in ls]
            widx = jax.lax.axis_index(DATA_AXIS)
            wrng = jax.random.fold_in(rng, widx)

            def body(carry, batch):
                p, v, u, s = carry
                x, y, f, m, i = batch
                srng = jax.random.fold_in(wrng, i)  # fresh dropout per local step
                np_, nv, nu, loss = raw_step(p, v, u, s, srng, x, y, f, m)
                # a minibatch that is 100% zero-weight fill must be a true
                # no-op: stateful updaters (momentum/Adam) would otherwise
                # move params and advance schedules on padding-only data
                wsum = sum(jnp.sum(mm) for mm in m)
                active = wsum > 0
                sel = lambda a, b: jnp.where(active, a, b)  # noqa: E731
                p = jax.tree_util.tree_map(sel, np_, p)
                v = jax.tree_util.tree_map(sel, nv, v)
                u = jax.tree_util.tree_map(sel, nu, u)
                s = s + active.astype(s.dtype)
                return (p, v, u, s), (loss, wsum)

            n_local = xs_l[0].shape[0]
            (p, v, u, s), (losses, wsums) = jax.lax.scan(
                body, (params, variables, ustates, step),
                (xs_l, ys_l, fs_l, ls_l, jnp.arange(n_local)))
            # parameter + updater-state averaging over the data axis
            # (reference processResults:352 aggregate-sum + divi, plus
            #  UpdaterAggregator for updater state)
            p = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, DATA_AXIS), p)
            v = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, DATA_AXIS), v)
            u = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, DATA_AXIS), u)
            # example-weighted round loss: fill minibatches carry zero weight
            loss_sum = jax.lax.psum(jnp.sum(losses * wsums), DATA_AXIS)
            w_sum = jax.lax.psum(jnp.sum(wsums), DATA_AXIS)
            loss = loss_sum / jnp.maximum(w_sum, 1.0)
            return p, v, u, loss

        fn = jax.jit(jax.shard_map(
            worker_round, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        ))
        net._jit_cache[key] = fn
        return fn

    def execute_training(self, net, iterator) -> None:
        net._check_init()
        n_dev = self.mesh.size
        b = self.batch_size_per_worker
        n = self.averaging_frequency
        # (inputs, labels, fmasks-or-None, lmasks-or-None) per fetched batch
        buf: List[tuple] = []

        def have():
            return sum(t[0][0].shape[0] for t in buf)

        def _concat_masks(pos: int, batches, ref_col):
            """Concatenate per-batch masks for one input/output position,
            substituting ones for batches that carry no mask. Returns None if
            NO batch carries a mask at this position."""
            present = [t[pos][ref_col] for t in batches
                       if t[pos] is not None and t[pos][ref_col] is not None]
            if not present:
                return None
            template = np.asarray(present[0])
            out = []
            for t in batches:
                m = t[pos][ref_col] if t[pos] is not None else None
                nrows = t[0][0].shape[0]
                if m is None:
                    m = np.ones((nrows,) + template.shape[1:], np.float32)
                out.append(np.asarray(m, np.float32))
            return np.concatenate(out)

        def flush():
            if not buf:
                return
            n_in = len(buf[0][0])
            n_out = len(buf[0][1])
            batches = list(buf)
            buf.clear()
            inputs = [np.concatenate([t[0][k] for t in batches])
                      for k in range(n_in)]
            labels = [np.concatenate([t[1][k] for t in batches])
                      for k in range(n_out)]
            fms = [_concat_masks(2, batches, k) for k in range(n_in)]
            has_fm = any(m is not None for m in fms)
            lms = [_concat_masks(3, batches, k) for k in range(n_out)]
            need = n_dev * n * b
            orig = inputs[0].shape[0]

            def fill(a):
                # Partial round: mirror the reference's balancedRandomSplit —
                # fill rows are cyclic duplicates, later zero-weighted and
                # spread round-robin so no worker idles.
                reps = int(np.ceil(need / orig))
                return np.tile(a, (reps,) + (1,) * (a.ndim - 1))[:need]

            if orig < need:
                inputs = [fill(a) for a in inputs]
                labels = [fill(a) for a in labels]
                fms = [fill(m) if m is not None else None for m in fms]
                lms = [fill(m) if m is not None else None for m in lms]
            elif orig > need:  # carry the remainder into the next round
                buf.append(([a[need:] for a in inputs],
                            [a[need:] for a in labels],
                            [m[need:] if m is not None else None for m in fms]
                            if has_fm else None,
                            [m[need:] if m is not None else None for m in lms]
                            if any(m is not None for m in lms) else None))
                inputs = [a[:need] for a in inputs]
                labels = [a[:need] for a in labels]
                fms = [m[:need] if m is not None else None for m in fms]
                lms = [m[:need] if m is not None else None for m in lms]
            # loss weights: real labels mask (or ones) with zero fill rows
            lmasks = []
            for y, m in zip(labels, lms):
                w = _ones_lmask(y, need, orig)
                if m is not None:
                    w = w * np.asarray(m, np.float32).reshape(w.shape)
                lmasks.append(w)
            if orig < need:
                # row i -> worker i % n_dev: real rows land on every worker
                perm = (np.arange(need).reshape(n * b, n_dev).T.reshape(-1))
                inputs = [a[perm] for a in inputs]
                labels = [a[perm] for a in labels]
                lmasks = [m[perm] for m in lmasks]
                fms = [m[perm] if m is not None else None for m in fms]

            def stack(a):
                return jnp.asarray(a.reshape((n_dev, n, b) + a.shape[1:]))
            xs = [stack(a) for a in inputs]
            ys = [stack(a) for a in labels]
            ls = [stack(m) for m in lmasks]
            fs = ([stack(m) if m is not None else None for m in fms]
                  if has_fm else None)
            round_fn = self._get_round_fn(net, has_fm)
            with phase_timer(self.stats, "aggregate_round"):
                net._key, sub = jax.random.split(net._key)
                with self.mesh:
                    (net.params, net.variables, net.updater_state,
                     loss) = round_fn(net.params, net.variables,
                                      net.updater_state,
                                      jnp.asarray(net.step), sub,
                                      xs, ys, fs, ls)
                net.score_ = loss  # lazily fetched
                net.step += n
            for listener in net.listeners:
                listener.iteration_done(net, net.step)
            self._rounds_done += 1
            if self.state_tracker is not None:
                self.state_tracker.batch_done(net,
                                              {"round": self._rounds_done})

        with phase_timer(self.stats, "total_training"):
            for ds in iterator:
                with phase_timer(self.stats, "data_fetch"):
                    inputs, labels, bfm, blm = _as_lists(ds)
                    buf.append(([np.asarray(a) for a in inputs],
                                [np.asarray(a) for a in labels],
                                bfm, blm))
                if have() >= n_dev * n * b:
                    flush()
            while buf:
                flush()
        if self.state_tracker is not None:
            # async trackers: final checkpoint durable before fit returns
            self.state_tracker.wait()

    def get_training_stats(self):
        return self.stats


class ParallelWrapper:
    """In-process multi-device data parallelism
    (reference parallelism/ParallelWrapper.java: N trainer threads with
    clone()d models, round-robin dispatch, averaging every
    `averagingFrequency` iterations :95). Here the "threads" are mesh
    devices and the dispatch/averaging is one shard_map program.
    """

    def __init__(self, net, workers: Optional[int] = None,
                 averaging_frequency: int = 1, batch_size_per_worker: int = 32,
                 prefetch_buffer: int = 2):
        self.net = net
        n = workers or len(jax.devices())
        self.master = ParameterAveragingTrainingMaster(
            batch_size_per_worker=batch_size_per_worker,
            averaging_frequency=averaging_frequency,
            mesh=default_mesh(n))
        self.prefetch_buffer = prefetch_buffer

    def fit(self, iterator):
        from ..datasets.iterators import AsyncDataSetIterator
        if self.prefetch_buffer > 0:
            iterator = AsyncDataSetIterator(iterator, self.prefetch_buffer)
        self.master.execute_training(self.net, iterator)
        return self.net
