"""Distributed configuration registry (ZooKeeper analog).

Capability parity with `deeplearning4j-scaleout-zookeeper`
(ZooKeeperConfigurationRegister.java / ZooKeeperConfigurationRetriever.java:
serialize a configuration under a known key so every worker in the cluster
retrieves the identical bytes).

TPU-native substrate: a TPU pod's hosts share storage (NFS/GCS fuse) rather
than a ZK ensemble, so the registry is a directory of atomically-written
JSON entries — same contract (last write wins, readers never observe torn
values, keys enumerable), no coordination service to operate. Values are
either raw JSON strings or objects exposing to_json() (the config classes).
"""
from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Dict, List, Optional, Union


class ConfigurationRegistry:
    def __init__(self, root: Union[str, Path]):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        if "/" in key or key.startswith("."):
            raise ValueError(f"invalid registry key {key!r}")
        return self.root / f"{key}.json"

    def register(self, key: str, conf) -> None:
        """Store a configuration under `key` (reference
        ZooKeeperConfigurationRegister.register()). Atomic: readers see the
        old or the new value, never a torn write."""
        if hasattr(conf, "to_json"):
            payload = {"type": type(conf).__name__, "json": conf.to_json()}
        else:
            payload = {"type": "raw", "json": json.dumps(conf)}
        payload["registered_at"] = time.time()
        path = self._path(key)
        tmp = path.with_name(f".{path.name}.tmp")
        tmp.write_text(json.dumps(payload))
        with open(tmp, "rb") as fh:
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def retrieve_json(self, key: str) -> Optional[str]:
        """Raw serialized form (reference retriever returns the bytes)."""
        path = self._path(key)
        if not path.exists():
            return None
        return json.loads(path.read_text())["json"]

    def retrieve(self, key: str):
        """Deserialize through the config serde registry when the stored
        type is a known configuration class; raw JSON values decode to
        Python objects."""
        path = self._path(key)
        if not path.exists():
            return None
        payload = json.loads(path.read_text())
        tname, blob = payload["type"], payload["json"]
        if tname == "raw":
            return json.loads(blob)
        from ..nn.conf.config import (MultiLayerConfiguration,
                                      NeuralNetConfiguration)
        from ..nn.conf.graph import ComputationGraphConfiguration
        for cls in (MultiLayerConfiguration, ComputationGraphConfiguration,
                    NeuralNetConfiguration):
            if cls.__name__ == tname:
                return cls.from_json(blob)
        return json.loads(blob)

    def keys(self) -> List[str]:
        return sorted(p.stem for p in self.root.glob("*.json")
                      if not p.name.startswith("."))

    def delete(self, key: str) -> bool:
        path = self._path(key)
        try:
            path.unlink()
            return True
        except FileNotFoundError:
            return False
