"""Long-context attention: ring attention + Ulysses-style sequence parallelism.

No reference counterpart (SURVEY.md §5 'Long-context: Absent' — the reference
predates attention; its only length-scaling tool is truncated BPTT). These are
the TPU-native long-context mechanisms required of this framework:

  - `ring_attention(...)`: the sequence axis is sharded over the mesh's "seq"
    devices; K/V blocks rotate around the ring via `lax.ppermute` while each
    device keeps a streaming-softmax accumulator (running max / denominator /
    weighted sum), so attention over a sequence of length L runs with O(L/n)
    memory per device and compute overlapping the ICI transfers.
    (Blockwise formulation per Liu et al., "Ring Attention with Blockwise
    Transformers" — see PAPERS.md retrieval notes.)
  - `ulysses_attention(...)`: all-to-all switches the sharding from sequence
    to heads, runs ordinary full attention on H/n heads locally, and
    all-to-alls back (DeepSpeed-Ulysses style sequence parallelism).

Both are numerically equivalent to single-device full attention (tested on
the 8-device CPU mesh against the dense reference implementation).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import SEQ_AXIS

Array = jax.Array


def full_attention(q: Array, k: Array, v: Array, causal: bool = False,
                   scale: Optional[float] = None) -> Array:
    """Dense reference attention. q,k,v: [B, L, H, D] -> [B, L, H, D]."""
    D = q.shape[-1]
    scale = scale or (1.0 / jnp.sqrt(D).astype(q.dtype))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Lq, Lk), bool))
        s = jnp.where(mask[None, None], s, jnp.finfo(s.dtype).min)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block_attend(q, k, v, m, l, o, scale, q_off, k_off, causal):
    """One streaming-softmax accumulation step.
    q: [B, Lq, H, D]; k,v: [B, Lk, H, D]; m,l: [B, H, Lq]; o: [B, Lq, H, D]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B, H, Lq, Lk]
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(Lq)
        kpos = k_off + jnp.arange(Lk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, jnp.finfo(s.dtype).min)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows (m_new could be -inf-like)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    corr = jnp.exp(m - m_safe)
    p = jnp.exp(s - m_safe[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    o_new = o * jnp.moveaxis(corr, 1, 2)[..., None] + pv
    return m_safe, l_new, o_new


def ring_attention(q: Array, k: Array, v: Array, mesh: Mesh,
                   axis: str = SEQ_AXIS, causal: bool = False) -> Array:
    """Sequence-parallel attention over `mesh[axis]`.

    q,k,v: GLOBAL [B, L, H, D] arrays (sharded or not — they are device_put
    onto the sequence sharding); returns the global output with the same
    sharding. L must be divisible by the axis size.
    """
    n = mesh.shape[axis]
    B, L, H, D = q.shape
    if L % n:
        raise ValueError(f"sequence length {L} not divisible by {axis}={n}")
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
    chunk = L // n

    def local_fn(ql, kl, vl):
        # ql/kl/vl: local [B, Lc, H, D]
        idx = lax.axis_index(axis)
        m = jnp.full((B, H, chunk), jnp.finfo(ql.dtype).min, ql.dtype)
        l = jnp.zeros((B, H, chunk), ql.dtype)
        o = jnp.zeros_like(ql)
        perm = [(i, (i + 1) % n) for i in range(n)]  # send to next; recv from prev

        def body(step, carry):
            kc, vc, m, l, o = carry
            # after `step` rotations this device holds the chunk that started
            # on device (idx - step) mod n
            src = jnp.mod(idx - step, n)
            m, l, o = _block_attend(ql, kc, vc, m, l, o, scale,
                                    idx * chunk, src * chunk, causal)
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return kc, vc, m, l, o

        _, _, m, l, o = lax.fori_loop(0, n, body, (kl, vl, m, l, o))
        denom = jnp.moveaxis(jnp.maximum(l, 1e-20), 1, 2)[..., None]
        return o / denom

    spec = P(None, axis, None, None)
    sharded = jax.jit(jax.shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))
    sh = NamedSharding(mesh, spec)
    with mesh:
        return sharded(jax.device_put(q, sh), jax.device_put(k, sh),
                       jax.device_put(v, sh))


def ulysses_attention(q: Array, k: Array, v: Array, mesh: Mesh,
                      axis: str = SEQ_AXIS, causal: bool = False) -> Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): trade the
    sequence sharding for a head sharding, attend fully per head, trade back.
    Requires H divisible by the axis size."""
    n = mesh.shape[axis]
    B, L, H, D = q.shape
    if H % n or L % n:
        raise ValueError(f"heads {H} and length {L} must divide {axis}={n}")

    def local_fn(ql, kl, vl):
        def seq_to_head(x):
            # [B, L/n, H, D] --all-to-all--> [B, L, H/n, D]
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

        def head_to_seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_head(ql), seq_to_head(kl), seq_to_head(vl)
        # through the helper seam: a registered flash kernel accelerates
        # the per-device full-L local attention too
        from ..ops import helpers as ophelpers
        oh = ophelpers.attention(qh, kh, vh, causal=causal)
        return head_to_seq(oh)

    spec = P(None, axis, None, None)
    sharded = jax.jit(jax.shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))
    sh = NamedSharding(mesh, spec)
    with mesh:
        return sharded(jax.device_put(q, sh), jax.device_put(k, sh),
                       jax.device_put(v, sh))
