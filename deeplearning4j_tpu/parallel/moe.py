"""Expert parallelism: Mixture-of-Experts with all_to_all dispatch.

No reference counterpart (pre-MoE codebase); completes this framework's
sharding alphabet (dp/tp in trainer.py, sp in ring.py, pp in pipeline.py,
ep here) per the TPU-native north star.

The standard dense-dispatch TPU formulation (Mesh-TensorFlow / Switch
Transformer): top-1 gating builds a [tokens, experts, capacity] dispatch
tensor with einsums (no scatter — MXU-friendly), tokens travel to their
expert's device with `lax.all_to_all`, the expert FFN runs, and a second
all_to_all brings results home where the gate probabilities combine them.
Everything is pure collectives inside shard_map, so jax.grad trains
straight through (router + experts) with no custom backward.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


def moe_spmd_fn(expert_fn: Callable, n_experts: int, capacity: int,
                axis: str = "expert"):
    """Per-device SPMD MoE body (wrap in shard_map over `axis`).

    Per-device view:
      expert_params: [1, ...] pytree — this device's expert
      gate_w:        [D, E] router weights (replicated)
      x:             [n_local, D] this device's token shard
    Returns [n_local, D] combined outputs for the local tokens.
    """
    E, C = n_experts, capacity

    def body(expert_params, gate_w, x):
        my_params = jax.tree_util.tree_map(lambda a: a[0], expert_params)
        probs = jax.nn.softmax(x @ gate_w)             # [n, E]
        gate = jnp.max(probs, -1)                      # top-1 weight
        onehot = jax.nn.one_hot(jnp.argmax(probs, -1), E,
                                dtype=x.dtype)         # [n, E]
        # position of each token in its expert's capacity buffer
        pos = jnp.cumsum(onehot, axis=0) * onehot - onehot  # [n, E]
        keep = onehot * (pos < C).astype(x.dtype)
        dispatch = keep[..., None] * jax.nn.one_hot(
            pos.astype(jnp.int32), C, dtype=x.dtype)   # [n, E, C]
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)   # [E, C, D]
        # tokens to their expert's device: dim0 chunk e -> device e; after
        # the exchange dim0 indexes SOURCE device, content is my expert's
        recv = jax.lax.all_to_all(expert_in, axis, split_axis=0,
                                  concat_axis=0, tiled=True)  # [E, C, D]
        out = expert_fn(my_params, recv.reshape(E * C, -1))
        out = out.reshape(E, C, -1)
        # route results back to the tokens' home devices
        back = jax.lax.all_to_all(out, axis, split_axis=0,
                                  concat_axis=0, tiled=True)  # [E, C, D]
        combine = dispatch * gate[:, None, None]
        return jnp.einsum("nec,ecd->nd", combine, back)

    return body


class MoEExecutor:
    """Expert-parallel MoE layer over a mesh `expert` axis: one expert per
    device, batch sharded over the same axis (the canonical ep layout)."""

    def __init__(self, expert_fn: Callable, n_experts: int, mesh: Mesh,
                 capacity_factor: float = 1.0, axis: str = "expert"):
        if mesh.shape[axis] != n_experts:
            raise ValueError(f"mesh axis {axis!r} has {mesh.shape[axis]} "
                             f"devices, need n_experts={n_experts}")
        self.expert_fn = expert_fn
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.mesh = mesh
        self.axis = axis
        self._jit_cache = {}

    def _get_apply(self, n_local: int):
        capacity = max(1, int(np.ceil(
            self.capacity_factor * n_local / self.n_experts)))
        key = (n_local, capacity)
        if key not in self._jit_cache:
            body = moe_spmd_fn(self.expert_fn, self.n_experts, capacity,
                               self.axis)
            self._jit_cache[key] = jax.jit(jax.shard_map(
                body, mesh=self.mesh,
                in_specs=(P(self.axis), P(), P(self.axis)),
                out_specs=P(self.axis),
                check_vma=False,
            ))
        return self._jit_cache[key]

    def shard_params(self, stacked_expert_params):
        sh = NamedSharding(self.mesh, P(self.axis))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), sh),
            stacked_expert_params)

    def apply(self, stacked_expert_params, gate_w, x) -> Array:
        """x: [B, D] global batch (sharded over the expert axis)."""
        if x.shape[0] % self.n_experts:
            raise ValueError(f"batch {x.shape[0]} not divisible by "
                             f"n_experts={self.n_experts}")
        n_local = x.shape[0] // self.n_experts
        return self._get_apply(n_local)(stacked_expert_params, gate_w, x)

    def grad_fn(self, loss_fn: Callable):
        """d(loss)/d(experts, router) through dispatch + all_to_all."""

        def objective(stacked_expert_params, gate_w, x, target):
            y = self.apply(stacked_expert_params, gate_w, x)
            return loss_fn(y, target)

        return jax.jit(jax.value_and_grad(objective, argnums=(0, 1)))
