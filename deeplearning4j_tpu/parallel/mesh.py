"""Device mesh construction helpers.

The TPU-native replacement for the reference's cluster topology plumbing
(Spark executor placement / Akka cluster membership, SURVEY.md §2.4): a
`jax.sharding.Mesh` over ICI-connected devices with named axes. Axis naming
convention used across the framework:
  - "data"  : data parallelism (batch sharding; the ParameterAveraging axis)
  - "model" : tensor parallelism (weight sharding)
  - "seq"   : sequence/context parallelism (ring attention)
  - "pipe"  : pipeline stages
  - "expert": expert parallelism
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def default_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    """1-D mesh over the first n local devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


def mesh_2d(data: int, model: int,
            axes: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS)) -> Mesh:
    devs = jax.devices()
    if data * model > len(devs):
        raise ValueError(f"mesh {data}x{model} needs {data*model} devices, "
                         f"have {len(devs)}")
    grid = np.asarray(devs[:data * model]).reshape(data, model)
    return Mesh(grid, axes)


def make_mesh(shape: dict) -> Mesh:
    """Build a mesh from {axis_name: size}; sizes must multiply to <= #devices."""
    sizes = [int(s) for s in shape.values()]
    total = int(np.prod(sizes))
    devs = jax.devices()
    if total > len(devs):
        raise ValueError(f"mesh {shape} needs {total} devices, have {len(devs)}")
    grid = np.asarray(devs[:total]).reshape(sizes)
    return Mesh(grid, tuple(shape.keys()))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    return NamedSharding(mesh, P(axis))
