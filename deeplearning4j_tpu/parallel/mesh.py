"""Device mesh construction helpers.

The TPU-native replacement for the reference's cluster topology plumbing
(Spark executor placement / Akka cluster membership, SURVEY.md §2.4): a
`jax.sharding.Mesh` over ICI-connected devices with named axes. Axis naming
convention used across the framework:
  - "data"  : data parallelism (batch sharding; the ParameterAveraging axis)
  - "model" : tensor parallelism (weight sharding)
  - "seq"   : sequence/context parallelism (ring attention)
  - "pipe"  : pipeline stages
  - "expert": expert parallelism
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def default_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS) -> Mesh:
    """1-D mesh over the first n local devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (axis,))


def mesh_2d(data: int, model: int,
            axes: Tuple[str, str] = (DATA_AXIS, MODEL_AXIS)) -> Mesh:
    devs = jax.devices()
    if data * model > len(devs):
        raise ValueError(f"mesh {data}x{model} needs {data*model} devices, "
                         f"have {len(devs)}")
    grid = np.asarray(devs[:data * model]).reshape(data, model)
    return Mesh(grid, axes)


def make_mesh(shape: dict) -> Mesh:
    """Build a mesh from {axis_name: size}; sizes must multiply to <= #devices."""
    sizes = [int(s) for s in shape.values()]
    total = int(np.prod(sizes))
    devs = jax.devices()
    if total > len(devs):
        raise ValueError(f"mesh {shape} needs {total} devices, have {len(devs)}")
    grid = np.asarray(devs[:total]).reshape(sizes)
    return Mesh(grid, tuple(shape.keys()))


def hybrid_mesh(dcn_shape: dict, ici_shape: dict) -> Mesh:
    """Multi-slice mesh: DCN axes outermost (across slices), ICI axes within.

    The multi-pod topology the reference reaches with Spark executor
    placement across hosts (SURVEY.md §2.4: driver -> executors over TCP) is
    expressed here as mesh geometry: axes in ``dcn_shape`` vary across TPU
    slices (collectives on them ride the data-center network) and axes in
    ``ici_shape`` vary within a slice (collectives ride ICI). Shard weights
    over ICI axes and batch over DCN axes so the per-step all-reduce volume
    crossing DCN is the small gradient-sum, never activations — the
    scaling-book recipe.

    On hardware, devices carry ``slice_index``; devices of one slice form one
    row-block. On single-slice (or CPU test) topologies, contiguous blocks of
    ``prod(ici_shape)`` devices stand in for slices so the same code runs
    under `--xla_force_host_platform_device_count`.
    """
    dcn_axes, ici_axes = tuple(dcn_shape), tuple(ici_shape)
    overlap = set(dcn_axes) & set(ici_axes)
    if overlap:
        raise ValueError(f"axis names must be unique across dcn/ici: {overlap}")
    n_slices = int(np.prod([int(s) for s in dcn_shape.values()]))
    per_slice = int(np.prod([int(s) for s in ici_shape.values()]))
    devs = jax.devices()
    if n_slices * per_slice > len(devs):
        raise ValueError(f"hybrid mesh {dcn_shape}x{ici_shape} needs "
                         f"{n_slices * per_slice} devices, have {len(devs)}")
    by_slice: dict = {}
    for d in devs:
        by_slice.setdefault(getattr(d, "slice_index", None) or 0, []).append(d)
    usable = [sorted(v, key=lambda d: d.id)[:per_slice]
              for _, v in sorted(by_slice.items())
              if len(v) >= per_slice][:n_slices]
    if len(usable) < n_slices:
        if len(by_slice) > 1:
            # real multi-slice hardware whose layout can't host this
            # geometry: refuse rather than silently letting an "ICI" axis
            # span slices (its collectives would ride DCN)
            raise ValueError(
                f"hybrid mesh {dcn_shape}x{ici_shape} does not fit the "
                f"slice layout {[len(v) for v in by_slice.values()]} "
                f"(need {n_slices} slices of >= {per_slice} devices)")
        # pseudo-slices: contiguous device blocks (single-slice / CPU test)
        if n_slices > 1:
            import warnings
            warnings.warn(
                f"hybrid_mesh: requested {n_slices} slices but only one "
                f"real slice is present — falling back to pseudo-slice "
                f"contiguous blocks, so the '{'/'.join(dcn_axes)}' DCN "
                f"axis actually rides ICI. Fine for tests; on real "
                f"hardware check the pod topology.", stacklevel=2)
        return make_mesh({**dcn_shape, **ici_shape})
    grid = np.asarray(usable).reshape(
        [int(s) for s in dcn_shape.values()] +
        [int(s) for s in ici_shape.values()])
    return Mesh(grid, dcn_axes + ici_axes)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    return NamedSharding(mesh, P(axis))
