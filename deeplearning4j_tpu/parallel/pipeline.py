"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

No reference counterpart (the reference is data-parallel only — SURVEY.md
§2.4); this is part of the TPU-native distributed design the north star
calls for (dp/tp/sp in parallel/{trainer,ring}.py; pp here).

Design: the classic JAX "collective pipeline" — stages live on the devices
of a `pipe` mesh axis; a `lax.scan` over S+M-1 ticks moves activations
between neighbouring stages with `lax.ppermute`, stage 0 injects a new
microbatch each tick, the last stage emits results. Because the schedule is
expressed as pure collectives inside `shard_map`, `jax.grad` differentiates
straight through it — the reverse-order backward pipeline (GPipe's backward
schedule) falls out of autodiff, no hand-written bwd pass.

Scope: homogeneous block stacks (every stage runs the same `block_fn` with
the same activation shape) — exactly the transformer-block regime pipeline
parallelism is used for in practice. Params are stacked [S, ...] and
sharded one stage per device along `pipe`.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Array = jax.Array


def stack_block_params(params_list):
    """Stack per-stage param pytrees into one [S, ...] pytree."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def gpipe_spmd_fn(block_fn: Callable, n_stages: int, n_micro: int,
                  axis: str = "pipe"):
    """Build the per-device SPMD pipeline body (to be wrapped in shard_map).

    Inputs (per-device view):
      stage_params: [1, ...] pytree — this device's stage slice
      xs:           [M, B, ...] microbatches (replicated; only stage 0 reads)
    Returns:
      ys:           [M, B, ...] pipeline outputs (valid on every device —
                    the last stage's results are broadcast with a psum so
                    downstream loss code is stage-agnostic)
    """
    S, M = n_stages, n_micro

    def body(stage_params, xs):
        s = jax.lax.axis_index(axis)
        my_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        buf0 = jnp.zeros_like(xs[0])
        outs0 = jnp.zeros_like(xs)
        perm = [(i, (i + 1) % S) for i in range(S)]
        # Bubble ticks still run block_fn; their results are discarded, BUT
        # a degenerate input (e.g. all zeros) can create NaN forward
        # intermediates in normalized blocks (std(0) has a 0/0 gradient),
        # and 0 * NaN = NaN then poisons parameter cotangents. So bubble
        # ticks compute on a GUARANTEED-nondegenerate synthetic input
        # (iota-patterned, nonzero variance), selected with jnp.where —
        # whose VJP routes zero cotangent to the unselected branch.
        flat = jnp.arange(int(np.prod(xs[0].shape)), dtype=jnp.float32)
        safe = ((flat % 7.0) - 3.0).reshape(xs[0].shape).astype(xs.dtype)

        def tick(carry, t):
            buf, outs = carry
            recv = jax.lax.ppermute(buf, axis, perm)
            m_in = jnp.clip(t, 0, M - 1)
            injected = jax.lax.dynamic_index_in_dim(xs, m_in, keepdims=False)
            inp_raw = jnp.where((s == 0) & (t < M), injected, recv)
            # stage s carries real data exactly during ticks [s, s+M)
            live = (t >= s) & (t < s + M)
            inp = jnp.where(live, inp_raw, safe)
            out = block_fn(my_params, inp)
            # the LAST stage finished microbatch m = t - (S-1) at this tick
            m_out = t - (S - 1)
            valid = (s == S - 1) & (m_out >= 0) & (m_out < M)
            slot = jnp.clip(m_out, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, slot, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, out, cur), slot, 0)
            return (out, outs), None

        (_, outs), _ = jax.lax.scan(tick, (buf0, outs0),
                                    jnp.arange(S + M - 1))
        # broadcast the last stage's outputs to every stage (loss code runs
        # replicated); non-last stages contribute zeros
        mine = jnp.where(s == S - 1, 1.0, 0.0).astype(outs.dtype)
        return jax.lax.psum(outs * mine, axis)

    return body


class GPipeExecutor:
    """Pipelined apply/train over a homogeneous block stack.

    block_fn(params, x) -> y must preserve x's shape (transformer-block
    regime). Parameters live stacked [S, ...], sharded one stage per device
    of the mesh's `pipe` axis.
    """

    def __init__(self, block_fn: Callable, n_stages: int, n_micro: int,
                 mesh: Mesh, axis: str = "pipe"):
        if mesh.shape[axis] != n_stages:
            raise ValueError(f"mesh axis {axis!r} has {mesh.shape[axis]} "
                             f"devices, need n_stages={n_stages}")
        self.block_fn = block_fn
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.mesh = mesh
        self.axis = axis
        body = gpipe_spmd_fn(block_fn, n_stages, n_micro, axis)
        self._apply = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axis), P()),  # params stage-sharded, data replicated
            out_specs=P(),
            check_vma=False,
        ))

    def shard_params(self, stacked_params):
        """Place a stacked [S, ...] param pytree one stage per device."""
        sh = NamedSharding(self.mesh, P(self.axis))
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), sh), stacked_params)

    def apply(self, stacked_params, x, *, microbatch: bool = True) -> Array:
        """Run the stack over x ([B, ...] or pre-split [M, b, ...])."""
        if microbatch:
            B = x.shape[0]
            if B % self.n_micro:
                raise ValueError(f"batch {B} not divisible by "
                                 f"n_micro={self.n_micro}")
            xs = x.reshape((self.n_micro, B // self.n_micro) + x.shape[1:])
        else:
            if x.shape[0] != self.n_micro:
                raise ValueError(
                    f"pre-split input has {x.shape[0]} microbatches; "
                    f"executor was built with n_micro={self.n_micro}")
            xs = x
        ys = self._apply(stacked_params, xs)
        return ys.reshape((-1,) + ys.shape[2:]) if microbatch else ys

    def grad_fn(self, loss_fn: Callable):
        """Build d(loss)/d(params) through the pipeline: loss_fn(y, target)
        over the pipelined outputs. Autodiff reverses the schedule (the
        GPipe backward pipeline) automatically."""

        def objective(stacked_params, x, target):
            y = self.apply(stacked_params, x)
            return loss_fn(y, target)

        return jax.jit(jax.value_and_grad(objective))
