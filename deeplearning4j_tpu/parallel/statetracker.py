"""Fault tolerance: checkpoint-based training state tracking + elastic resume.

Capability parity with the reference's legacy distributed runtime
(SURVEY.md §5 "Failure detection / elastic recovery"):
  - `scaleout/api/statetracker/StateTracker.java:45` — per-worker job
    persistence and redelivery (saveWorker/loadForWorker :122-129), worker
    lifecycle (addWorker/enableWorker/disableWorker :184-199)
  - `BaseHazelCastStateTracker.java` — replicated shared state
  - Spark's lineage-based task retry

TPU-first redesign: there is no Hazelcast grid to replicate into — the
durable substrate is the checkpoint file (SURVEY §5: "checkpoint-based
restart + re-sharding a failed host's data"). The tracker periodically
writes an ATOMIC checkpoint (ModelSerializer zip: config + params + updater
state + variables, plus a cursor: epoch, batch index, host rng key) and on
restart `resume()` restores the newest intact checkpoint — a kill at any
instant loses at most `every_n_steps` batches and never corrupts state
(write-to-temp + os.replace; a torn write leaves the previous checkpoint).
"Job redelivery" maps to replaying the batches after the restored cursor;
re-sharding a lost worker's data is the data iterator's responsibility and
falls out of cursor-based replay.
"""
from __future__ import annotations

import json
import os
import time
import zipfile
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

CURSOR_JSON = "cursor.json"


class TrainingStateTracker:
    """Periodic atomic checkpoints + restore (StateTracker.java:45 analog).

    Checkpoints are complete: params, updater state, BN variables, step
    counter, the host PRNG key, and a caller-supplied cursor — so a resumed
    run continues bit-identically to an uninterrupted one (given the same
    data order), which the kill-mid-training test asserts.
    """

    def __init__(self, directory: Union[str, Path], every_n_batches: int = 10,
                 keep_last: int = 2):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.every_n_batches = max(1, every_n_batches)
        self.keep_last = max(1, keep_last)
        self._since_save = 0
        # worker lifecycle registry (reference addWorker/disableWorker
        # :184-199). PERSISTED to the shared checkpoint directory (the
        # reference keeps it in ZooKeeper-backed shared state): a job
        # restarted after a host failure must see the same roster so it
        # can disable the dead worker and re-shard (elastic-recovery test
        # in tests/test_multihost.py).
        self._workers: Dict[str, bool] = self._load_workers()

    # -- worker lifecycle (reference :184-199) ---------------------------------
    # One FILE PER WORKER, merged on read. The roster lives on a shared
    # checkpoint substrate (NFS / GCS-fuse) where flock is unreliable
    # (gcsfuse: silent no-op; NFS: mount-dependent), so any cross-host
    # read-merge-write of a single roster file can lose registrations.
    # Per-worker files need no cross-host mutual exclusion at all: distinct
    # workers touch distinct files, and same-worker mutations are owned by
    # that worker (or the master that declared it dead) with atomic
    # last-writer-wins via os.replace. (Advisor r4, severity medium.)
    def _workers_dir(self) -> Path:
        return self.dir / "workers"

    @staticmethod
    def _worker_file_stem(worker_id: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in worker_id)
        if safe != worker_id:  # collision-proof the sanitized name
            import hashlib
            safe += "-" + hashlib.sha1(worker_id.encode()).hexdigest()[:8]
        return safe

    def _load_workers(self) -> Dict[str, bool]:
        merged: Dict[str, bool] = {}
        try:  # legacy pre-r5 single-file roster, lowest precedence
            with open(self.dir / "workers.json") as fh:
                merged.update({str(k): bool(v)
                               for k, v in json.load(fh).items()})
        except (OSError, ValueError):
            pass
        wd = self._workers_dir()
        if wd.is_dir():
            for f in sorted(wd.glob("*.json")):
                try:
                    with open(f) as fh:
                        rec = json.load(fh)
                    merged[str(rec["id"])] = bool(rec["enabled"])
                except (OSError, ValueError, KeyError):
                    continue  # torn write: skip, the owner will rewrite
        return merged

    def _mutate_workers(self, worker_id: str, value, *,
                        keep_existing: bool) -> None:
        wd = self._workers_dir()
        wd.mkdir(parents=True, exist_ok=True)
        path = wd / f"{self._worker_file_stem(worker_id)}.json"
        payload = json.dumps({"id": worker_id, "enabled": bool(value)})
        if keep_existing:
            # add_worker must never OVERWRITE concurrent state: a master
            # disabling this worker races the worker re-registering. Respect
            # the merged roster (covers the legacy single-file format), then
            # create with O_EXCL — if the file exists (or appears between
            # check and create), the existing record wins; if we win the
            # create, a concurrent disable's os.replace lands after and
            # wins. Both orders converge to the disable — the guarantee the
            # old flock'd read-merge-write gave on substrates where flock
            # actually works, now without needing it.
            if worker_id not in self._load_workers():
                # write the FULL record to a unique tmp first, then claim
                # the name with os.link (fails if present, like O_EXCL, but
                # the visible file always has complete content): a crash
                # between a direct O_EXCL create and its write would leave
                # a permanent empty poison file this worker could never
                # re-register past
                tmp = path.with_suffix(f".add.{os.getpid()}.{id(self):x}")
                with open(tmp, "w") as fh:
                    fh.write(payload)
                    fh.flush()
                    os.fsync(fh.fileno())
                try:
                    os.link(tmp, path)
                except FileExistsError:
                    # a record exists: it wins — unless it is an EMPTY/torn
                    # leftover of a crashed add (a poison file nothing would
                    # ever rewrite): heal it with our complete record
                    try:
                        if os.path.getsize(path) == 0:
                            os.replace(tmp, path)
                            tmp = None
                    except OSError:
                        pass
                except OSError:
                    # hard links unsupported (gcsfuse): fall back to the
                    # atomic-visibility rename. The lost property is only
                    # create-if-absent firstness for simultaneous adds of
                    # the SAME new worker with different values — add
                    # always writes enabled=True, so both writers agree
                    os.replace(tmp, path)
                    tmp = None
                finally:
                    if tmp is not None:
                        try:
                            os.unlink(tmp)
                        except OSError:
                            pass
        else:
            # enable/disable: atomic last-writer-wins overwrite; unique tmp
            # name so two hosts mutating the same worker cannot clobber
            # each other's in-flight tmp before the rename
            tmp = path.with_suffix(f".tmp.{os.getpid()}.{id(self):x}")
            with open(tmp, "w") as fh:
                fh.write(payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        self._workers = self._load_workers()

    def add_worker(self, worker_id: str) -> None:
        self._mutate_workers(worker_id, True, keep_existing=True)

    def enable_worker(self, worker_id: str) -> None:
        self._mutate_workers(worker_id, True, keep_existing=False)

    def disable_worker(self, worker_id: str) -> None:
        self._mutate_workers(worker_id, False, keep_existing=False)

    def workers(self) -> List[str]:
        return sorted(self._workers)

    def enabled_workers(self) -> List[str]:
        return sorted(w for w, ok in self._workers.items() if ok)

    # -- checkpoint write ------------------------------------------------------
    def _checkpoint_paths(self) -> List[Path]:
        return sorted(self.dir.glob("ckpt-*.zip"),
                      key=lambda p: int(p.stem.split("-")[1]))

    def save(self, net, cursor: Optional[dict] = None) -> Path:
        """Write one atomic checkpoint. `cursor` is arbitrary JSON state the
        training driver needs to resume (epoch, batch index, ...)."""
        path = self._write(net, cursor)
        self._since_save = 0
        return path

    def _write(self, net, cursor: Optional[dict] = None) -> Path:
        """The serialization itself — does NOT touch the batch counter (the
        async tracker runs this on its writer thread, where resetting
        `_since_save` would wipe batch_done counts accumulated during a
        slow write and stretch the loss bound past every_n_batches)."""
        from ..util.model_serializer import write_model
        seq_prev = [int(p.stem.split("-")[1]) for p in self._checkpoint_paths()]
        seq = (max(seq_prev) + 1) if seq_prev else 0
        final = self.dir / f"ckpt-{seq:08d}.zip"
        tmp = self.dir / f".ckpt-{seq:08d}.zip.tmp"
        write_model(net, tmp, save_updater=True)
        # append the cursor (+ host rng key) into the same zip
        cur = dict(cursor or {})
        cur["rng_key"] = np.asarray(net._key).tolist()
        cur["step"] = int(net.step)
        cur["wall_time"] = time.time()
        with zipfile.ZipFile(tmp, "a", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(CURSOR_JSON, json.dumps(cur))
        with open(tmp, "rb") as fh:  # durability before the atomic rename
            os.fsync(fh.fileno())
        os.replace(tmp, final)
        for old in self._checkpoint_paths()[:-self.keep_last]:
            try:
                old.unlink()
            except OSError:
                pass
        return final

    def batch_done(self, net, cursor: Optional[dict] = None) -> Optional[Path]:
        """Call once per trained batch; saves every `every_n_batches`."""
        self._since_save += 1
        if self._since_save >= self.every_n_batches:
            return self.save(net, cursor)
        return None

    def wait(self) -> Optional[Path]:
        """Synchronous tracker: every save is already durable; no-op.
        (AsyncTrainingStateTracker overrides this to join its writer.)"""
        return None

    # -- restore ---------------------------------------------------------------
    def latest(self) -> Optional[Path]:
        paths = self._checkpoint_paths()
        return paths[-1] if paths else None

    def restore(self, net) -> Optional[dict]:
        """Restore the newest INTACT checkpoint into `net` (a kill during
        save leaves a .tmp which is ignored; a torn final file falls back to
        the previous checkpoint). Returns the cursor or None."""
        import zlib
        for path in reversed(self._checkpoint_paths()):
            try:
                return self._restore_one(net, path)
            except (zipfile.BadZipFile, KeyError, OSError, ValueError,
                    zlib.error):  # torn OR bit-corrupted file -> fall back
                continue
        return None

    def _restore_one(self, net, path: Path) -> dict:
        from ..util.model_serializer import _restore_state
        with zipfile.ZipFile(path) as zf:
            cursor = json.loads(zf.read(CURSOR_JSON).decode())
            net._check_init()
            _restore_state(net, zf, load_updater=True)
        net._key = jnp.asarray(np.asarray(cursor.pop("rng_key"), np.uint32))
        net.step = int(cursor.get("step", net.step))
        return cursor


def _snapshot(net):
    """Asynchronous point-in-time snapshot of a net's training state.

    Each leaf is snapshotted with a DEVICE-side copy: the copy op is only
    *enqueued* here (jax dispatch is async), runs at HBM bandwidth, and is
    ordered before any later donating train step — so the snapshot is
    consistent as-of-now and `save()` returns without waiting for device
    work, let alone device->host transfer. A plain reference capture is NOT
    enough: the jitted train steps donate their input buffers, which
    deletes the captured arrays on the very next step. (The reference has
    the same problem for a different reason — its params are one mutable
    flat INDArray, Model.java:95-108 — and would need a locked host copy.)
    """
    import jax

    def leaf(a):
        return a.copy() if isinstance(a, jax.Array) else a

    snap = object.__new__(type(net))
    snap.conf = net.conf
    snap.params = jax.tree_util.tree_map(leaf, net.params)
    snap.updater_state = jax.tree_util.tree_map(leaf, net.updater_state)
    snap.variables = jax.tree_util.tree_map(leaf, net.variables)
    snap.step = int(net.step)
    snap._key = leaf(net._key)
    snap._initialized = True
    return snap


class AsyncTrainingStateTracker(TrainingStateTracker):
    """Async (orbax-style) checkpointing: `save()` enqueues device-side
    copies of the state (dispatch-only — see `_snapshot`) and returns
    immediately; one background writer thread does the device->host fetch,
    zip serialization, fsync and atomic rename. The training loop never
    stalls on checkpoint IO — on a TPU that means the step pipeline stays
    full through a save.

    At most one save is in flight (a new `save()` first waits for the
    previous one, surfacing any writer error there); `wait()` blocks until
    the pending checkpoint is durable; `restore()`/`close()` imply `wait()`.
    Kill-safety is inherited: the writer goes through the same
    write-tmp -> fsync -> os.replace protocol, so dying mid-save leaves the
    previous checkpoint intact.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        import concurrent.futures
        self._writer = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-writer")
        self._pending = None

    def save(self, net, cursor: Optional[dict] = None):
        """Snapshot now, write in the background. Returns a Future[Path]."""
        self.wait()  # bound in-flight saves to 1; surface earlier failures
        snap = _snapshot(net)
        cur = dict(cursor or {})
        self._pending = self._writer.submit(self._write, snap, cur)
        self._since_save = 0
        return self._pending

    def wait(self) -> Optional[Path]:
        """Block until the in-flight checkpoint (if any) is durable."""
        pending, self._pending = self._pending, None
        return pending.result() if pending is not None else None

    def restore(self, net) -> Optional[dict]:
        self.wait()
        return super().restore(net)

    def close(self) -> None:
        """Make the in-flight save durable and release the writer thread.
        The shutdown happens even when the pending write failed (the error
        still propagates)."""
        try:
            self.wait()
        finally:
            self._writer.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # the with-body's exception wins; still release the writer and
            # don't let a failed background save replace it
            try:
                self.close()
            except Exception:
                pass
            return False
        self.close()
        return False


def fit_with_recovery(net, make_iterator: Callable[[int], object],
                      epochs: int, tracker: TrainingStateTracker,
                      master=None) -> dict:
    """Resumable multi-epoch training — the `resume()` entry point.

    `make_iterator(epoch)` must return the SAME batch sequence for a given
    epoch on every invocation (deterministic data order is what makes
    recovery exact — the reference redelivers the same persisted job,
    StateTracker.java:122-129). If `master` is given, each batch is trained
    through `master.execute_training` (distributed path); otherwise through
    the net's own single-batch fit.

    On entry, restores the newest checkpoint (if any) and replays forward
    from its cursor. A process kill at ANY point (including mid-save) loses
    at most `tracker.every_n_batches` batches of progress and resumes to the
    same final state an uninterrupted run reaches.
    """
    cursor = tracker.restore(net) or {}
    start_epoch = int(cursor.get("epoch", 0))
    start_batch = int(cursor.get("batch", 0))
    # this driver owns the cursor: suspend any master-side checkpoint hook
    # so each batch is recorded exactly once, in THIS epoch/batch vocabulary
    master_tracker = getattr(master, "state_tracker", None)
    if master is not None and master_tracker is not None:
        master.state_tracker = None
    try:
        _fit_with_recovery_loop(net, make_iterator, epochs, tracker, master,
                                start_epoch, start_batch)
    finally:
        if master is not None and master_tracker is not None:
            master.state_tracker = master_tracker
    tracker.save(net, {"epoch": epochs, "batch": 0, "done": True})
    tracker.wait()  # async trackers: the final checkpoint must be durable
    return {"epochs": epochs, "final_step": net.step}


def _fit_with_recovery_loop(net, make_iterator, epochs, tracker, master,
                            start_epoch, start_batch):
    for epoch in range(start_epoch, epochs):
        it = make_iterator(epoch)
        if hasattr(it, "reset"):
            it.reset()
        pull = (it.next_batch if hasattr(it, "next_batch")
                else iter(it).__next__)
        bi = 0
        while True:
            try:
                ds = pull()
            except StopIteration:
                ds = None
            if ds is None:
                break
            if epoch == start_epoch and bi < start_batch:
                bi += 1
                continue  # already trained before the checkpoint
            if master is not None:
                master.execute_training(net, [ds])
            elif hasattr(net, "fit_batch"):  # MultiLayerNetwork
                net.fit_batch(ds.features, ds.labels,
                              getattr(ds, "features_mask", None),
                              getattr(ds, "labels_mask", None))
            else:  # ComputationGraph: one (Multi)DataSet through fit
                net.fit(ds)
            bi += 1
            tracker.batch_done(net, {"epoch": epoch, "batch": bi})
        start_batch = 0
