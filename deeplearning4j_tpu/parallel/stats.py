"""Training-phase profiling stats.

Parity with the reference's SparkTrainingStats SPI + StatsCalculationHelper
(spark/api/stats/, spark/stats/ — per-phase wall-time events around
broadcast-fetch, data-fetch, minibatch processing; SURVEY.md §5 'Tracing'),
and the TimeSource SPI (spark/time/NTPTimeSource.java vs SystemClockTimeSource)
for cross-node timestamps.
"""
from __future__ import annotations

import contextlib
import json
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional


# -- TimeSource SPI ------------------------------------------------------------

class TimeSource:
    """Reference spark/time/TimeSource.java."""

    def current_time_millis(self) -> float:
        raise NotImplementedError


class SystemClockTimeSource(TimeSource):
    def current_time_millis(self) -> float:
        return time.time() * 1000.0


class NTPTimeSource(TimeSource):
    """Clock-skew-corrected timestamps (reference NTPTimeSource.java). With
    zero egress we estimate skew once against the monotonic clock; on a real
    deployment, plug an NTP offset in via `set_offset_millis`."""

    def __init__(self):
        self._offset = 0.0

    def set_offset_millis(self, offset: float):
        self._offset = offset

    def current_time_millis(self) -> float:
        return time.time() * 1000.0 + self._offset


@dataclass
class EventStats:
    """One timed phase event (reference spark/stats/EventStats)."""

    name: str
    start_millis: float
    duration_millis: float


class SparkTrainingStats:
    """Accumulates per-phase timing events (reference CommonSparkTrainingStats)."""

    def __init__(self, time_source: Optional[TimeSource] = None):
        self.time_source = time_source or SystemClockTimeSource()
        self.events: Dict[str, List[EventStats]] = defaultdict(list)

    def add_event(self, name: str, start_millis: float, duration_millis: float):
        self.events[name].append(EventStats(name, start_millis, duration_millis))

    def keys(self):
        return list(self.events.keys())

    def total_millis(self, name: str) -> float:
        return sum(e.duration_millis for e in self.events.get(name, []))

    def mean_millis(self, name: str) -> float:
        evs = self.events.get(name, [])
        return sum(e.duration_millis for e in evs) / len(evs) if evs else 0.0

    def count(self, name: str) -> int:
        return len(self.events.get(name, []))

    def stats_as_string(self) -> str:
        lines = ["phase                     count   total_ms    mean_ms"]
        for name in sorted(self.events):
            lines.append(f"{name:25s} {self.count(name):5d} {self.total_millis(name):10.1f} "
                         f"{self.mean_millis(name):10.2f}")
        return "\n".join(lines)

    def export_json(self) -> str:
        """StatsUtils-style export (reference spark/stats/StatsUtils)."""
        return json.dumps({
            name: [{"start": e.start_millis, "duration": e.duration_millis}
                   for e in evs]
            for name, evs in self.events.items()
        })


@contextlib.contextmanager
def phase_timer(stats: Optional[SparkTrainingStats], name: str):
    """Time a phase (reference StatsCalculationHelper start/stop pairs)."""
    if stats is None:
        yield
        return
    start = stats.time_source.current_time_millis()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        stats.add_event(name, start, (time.perf_counter() - t0) * 1000.0)


@contextlib.contextmanager
def device_trace(log_dir: str, host_stats: Optional[SparkTrainingStats] = None,
                 phase: str = "device_trace"):
    """Capture a device-level profiler trace around a training region —
    the TPU analog of the reference's per-phase Spark instrumentation
    (SURVEY §5: "jax profiler traces + per-phase host metrics; keep the
    stats SPI"). Writes a TensorBoard/XPlane trace under `log_dir`
    (inspect with tensorboard or xprof) while also recording the wall time
    as a phase event in the host-side stats, so one context manager gives
    both views. Degrades to host timing only if the profiler is
    unavailable on the backend."""
    import jax
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:
        pass
    try:
        with phase_timer(host_stats, phase):
            yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
