"""Distributed training: masters, meshes, sequence/pipeline/expert
parallelism, fault tolerance, driver facades (SURVEY.md §2.4 analog).

The names below are the public surface a driver program uses. Importing
this package initializes jax (the submodules need it at import time).
"""
from .mesh import DATA_AXIS, default_mesh, hybrid_mesh, make_mesh
from .trainer import (IciDataParallelTrainingMaster, ParallelWrapper,
                      ParameterAveragingTrainingMaster, TrainingMaster)
from .statetracker import (AsyncTrainingStateTracker,
                           TrainingStateTracker, fit_with_recovery)
from .registry import ConfigurationRegistry
from .pipeline import GPipeExecutor, stack_block_params
from .moe import MoEExecutor
from .spark_api import SparkComputationGraph, SparkDl4jMultiLayer
from .tensor_parallel import shard_transformer_tp
from .zero import shard_updater_state, updater_state_bytes_per_device
from .evaluation import (DistributedDataSetLossCalculator,
                         DistributedEarlyStoppingTrainer,
                         distributed_evaluate, distributed_score)
from .ring import full_attention, ring_attention, ulysses_attention
from .stats import (NTPTimeSource, SparkTrainingStats, SystemClockTimeSource,
                    TimeSource, device_trace, phase_timer)

__all__ = [
    "DATA_AXIS", "default_mesh", "hybrid_mesh", "make_mesh",
    "TrainingMaster", "IciDataParallelTrainingMaster",
    "ParameterAveragingTrainingMaster", "ParallelWrapper",
    "TrainingStateTracker", "AsyncTrainingStateTracker", "fit_with_recovery", "ConfigurationRegistry",
    "GPipeExecutor", "stack_block_params", "MoEExecutor",
    "SparkDl4jMultiLayer", "SparkComputationGraph", "shard_transformer_tp",
    "shard_updater_state", "updater_state_bytes_per_device",
    "distributed_evaluate", "distributed_score",
    "DistributedDataSetLossCalculator", "DistributedEarlyStoppingTrainer",
    "full_attention", "ring_attention", "ulysses_attention",
    "SparkTrainingStats", "TimeSource", "SystemClockTimeSource",
    "NTPTimeSource", "phase_timer", "device_trace",
]
