"""Megatron-style tensor parallelism for ComputationGraph networks.

No reference counterpart (SURVEY.md §2.4: the reference has data parallelism
only); this is the TPU-native capability that shards the weights themselves
over a mesh axis. The sharding is pure annotation — `jax.device_put` with
NamedShardings — and GSPMD inserts the all-gather/reduce-scatter pairs when
the normal jitted train step runs under the mesh. No model code changes.

Scheme (Megatron pairing):
  - SelfAttentionLayer: Wq/Wk/Wv column-parallel (head dim split over the
    axis), Wo row-parallel, bias replicated — one collective per attention
    block instead of one per projection.
  - DenseLayer directly consuming a column-parallel DenseLayer:
    row-parallel (the FFN down-projection).
  - Other DenseLayers with a nonlinearity: column-parallel (the FFN
    up-projection). Identity-activation projections (embeddings, output
    heads) and LayerNorm/Output layers stay replicated.

Updater state shards exactly like its parameters (momentum follows weights).
"""
from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS_DEFAULT = "model"


def _tp_specs_for_graph(conf, axis: str) -> Dict[str, Dict[str, P]]:
    """Per-vertex, per-param PartitionSpecs for the Megatron scheme."""
    from ..nn.conf.graph import LayerVertex
    from ..nn.conf.layers import DenseLayer, SelfAttentionLayer

    specs: Dict[str, Dict[str, P]] = {}
    col_vertices = set()
    for name in conf.topological_order():
        vertex = conf.vertices[name]
        if not isinstance(vertex, LayerVertex):
            continue
        layer = vertex.layer
        srcs = conf.vertex_inputs[name]
        if isinstance(layer, SelfAttentionLayer):
            specs[name] = {"Wq": P(None, axis), "Wk": P(None, axis),
                           "Wv": P(None, axis), "Wo": P(axis, None),
                           "b": P()}
        elif isinstance(layer, DenseLayer):
            if len(srcs) == 1 and srcs[0] in col_vertices:
                specs[name] = {"W": P(axis, None), "b": P()}
            elif (layer.activation or "identity") != "identity":
                specs[name] = {"W": P(None, axis), "b": P(axis)}
                col_vertices.add(name)
            else:
                specs[name] = {}
        else:
            specs[name] = {}
    return specs


def shard_transformer_tp(net, mesh: Mesh,
                         axis: str = MODEL_AXIS_DEFAULT) -> None:
    """Annotate `net`'s params + updater state with tensor-parallel
    shardings in place. Afterwards run the normal train step under
    `with mesh:`, or — for DP x TP — hand the net to
    `IciDataParallelTrainingMaster(mesh=make_mesh({"data": d, "model": t}))`,
    which preserves existing annotations on its mesh. Numerics are
    unchanged (tested equal to the replicated baseline on a virtual
    mesh)."""
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no axis '{axis}' "
                         f"(axes: {mesh.axis_names})")
    specs = _tp_specs_for_graph(net.conf, axis)
    repl = NamedSharding(mesh, P())

    def put(arr, spec, pname=""):
        # a dim that the mesh axis does not evenly divide (e.g. a GQA
        # layer's shrunken Wk/Wv) falls back to replication rather than
        # crashing device_put — loudly, so a misconfigured mesh is not a
        # silent no-op
        for d, ax in enumerate(spec):
            if ax is not None and arr.shape[d] % mesh.shape[ax]:
                import warnings
                warnings.warn(
                    f"shard_transformer_tp: {pname} dim {d} (size "
                    f"{arr.shape[d]}) is not divisible by mesh axis "
                    f"'{ax}' ({mesh.shape[ax]}); replicating this param",
                    stacklevel=3)
                spec = P()
                break
        return jax.device_put(arr, NamedSharding(mesh, spec))

    for name, lp in net.params.items():
        vspec = specs.get(name, {})
        net.params[name] = {
            pname: put(arr, vspec.get(pname, P()), f"{name}/{pname}")
            for pname, arr in lp.items()}
        net.updater_state[name] = {
            pname: {k: put(v, vspec.get(pname, P()), f"{name}/{pname}")
                    for k, v in state.items()}
            for pname, state in net.updater_state[name].items()}
    net.variables = jax.tree_util.tree_map(
        lambda a: jax.device_put(a, repl), net.variables)
