"""Distributed evaluation, scoring, and early stopping over the mesh.

Capability parity with the reference's Spark evaluation stack:
  - spark/dl4j-spark/.../impl/multilayer/evaluation/EvaluateFlatMapFunction.java
    + EvaluationReduceFunction.java — per-partition Evaluation objects merged
    at the driver
  - spark/dl4j-spark/.../earlystopping/SparkEarlyStoppingTrainer.java:37 +
    SparkDataSetLossCalculator — distributed loss driving early stopping.

TPU-first redesign: instead of shipping Evaluation objects, the confusion
matrix is computed ON DEVICE as one matmul — one_hot(actual)^T(weighted) @
one_hot(predicted) — with the batch sharded over the mesh's data axis, so
GSPMD reduces the per-shard counts with a single psum over ICI. Scoring
likewise runs the jitted masked loss on sharded batches. Works for both
MultiLayerNetwork and ComputationGraph.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, default_mesh
from .trainer import TrainingMaster, _as_lists, _is_graph, _pad_ragged, _tree_put
from ..earlystopping.earlystopping import (EarlyStoppingResult,
                                           EarlyStoppingTrainer,
                                           ScoreCalculator)
from ..evaluation.evaluation import Evaluation


def _eval_forward_fn(net):
    """(params, variables, inputs-list, fmasks-list-or-None)
    -> first-output activations, mask-aware."""
    if _is_graph(net):
        out_name = net.conf.network_outputs[0]
        in_names = net.conf.network_inputs

        def fwd(params, variables, inputs, fmasks):
            fmd = dict(zip(in_names, fmasks)) if fmasks is not None else None
            acts, _, _ = net._forward_impl(params, variables, inputs,
                                           train=False, rng=None, fmasks=fmd)
            return acts[out_name]
        return fwd

    def fwd(params, variables, inputs, fmasks):
        acts, _, _ = net._forward_impl(
            params, variables, inputs[0], train=False, rng=None,
            fmask=fmasks[0] if fmasks is not None else None)
        return acts[-1]
    return fwd


def _get_counts_fn(net, n_classes: int):
    key = ("dist_eval_counts", n_classes)
    if key in net._jit_cache:
        return net._jit_cache[key]
    fwd = _eval_forward_fn(net)

    def counts(params, variables, inputs, fmasks, y, w):
        out = fwd(params, variables, inputs, fmasks)
        if out.ndim == 3:  # time series: flatten, mask weights per step
            out = out.reshape(-1, out.shape[-1])
            y = y.reshape(-1, y.shape[-1])
        actual = jnp.argmax(y, axis=-1)
        pred = jnp.argmax(out, axis=-1)
        oh_a = jax.nn.one_hot(actual, n_classes, dtype=jnp.float32) * w[:, None]
        # contraction over the sharded batch axis => GSPMD inserts ONE psum
        return oh_a.T @ jax.nn.one_hot(pred, n_classes, dtype=jnp.float32)

    net._jit_cache[key] = jax.jit(counts)
    return net._jit_cache[key]


def _get_score_fn(net):
    key = "dist_eval_score"
    if key in net._jit_cache:
        return net._jit_cache[key]
    if _is_graph(net):
        in_names = net.conf.network_inputs

        def score(params, variables, inputs, fmasks, labels, lmasks):
            fmd = dict(zip(in_names, fmasks)) if fmasks is not None else None
            acts, _, _ = net._forward_impl(params, variables, inputs,
                                           train=False, rng=None, fmasks=fmd)
            return (net._loss(acts, labels, lmasks)
                    + net._reg_loss(params))
    else:
        def score(params, variables, inputs, fmasks, labels, lmasks):
            acts, _, _ = net._forward_impl(
                params, variables, inputs[0], train=False, rng=None,
                fmask=fmasks[0] if fmasks is not None else None)
            lm = lmasks[0] if lmasks is not None else None
            return (net._loss_from_output(acts[-1], labels[0], lm)
                    + net._reg_loss(params))
    net._jit_cache[key] = jax.jit(score)
    return net._jit_cache[key]


def _shard_batch(ds, net, mesh):
    """Normalize, ragged-pad (zero-weight fill), and shard one batch.
    Returns (inputs, labels, fmasks-or-None, lmasks, orig_examples)."""
    shard = NamedSharding(mesh, P(DATA_AXIS))
    inputs, labels, fms, lms = _as_lists(ds)
    inputs = [np.asarray(a) for a in inputs]
    labels = [np.asarray(a) for a in labels]
    orig = inputs[0].shape[0]
    inputs, labels, fms, lms = _pad_ragged(inputs, labels, fms, lms, mesh.size)
    if lms is None:
        lms = [None] * len(labels)
    # unit weights for outputs that carry no mask (incl. None entries of a
    # partially-masked MultiDataSet)
    lms = [np.asarray(m, np.float32) if m is not None
           else np.ones((y.shape[0],) if y.ndim == 2 else y.shape[:2],
                        np.float32)
           for m, y in zip(lms, labels)]

    def put(a):
        return (jax.device_put(jnp.asarray(a), shard)
                if a is not None else None)
    fms_out = ([put(np.asarray(m, np.float32)) if m is not None else None
                for m in fms] if fms is not None else None)
    return ([put(a) for a in inputs], [put(a) for a in labels],
            fms_out, [put(m) for m in lms], orig)


def distributed_evaluate(net, iterator, mesh: Optional[Mesh] = None,
                         n_classes: Optional[int] = None) -> Evaluation:
    """Mesh-sharded classification evaluation; equals local evaluate()
    (EvaluateFlatMapFunction + EvaluationReduceFunction analog)."""
    mesh = mesh or default_mesh()
    net._check_init()
    repl = NamedSharding(mesh, P())
    net.params = _tree_put(net.params, repl)
    net.variables = _tree_put(net.variables, repl)
    ev: Optional[Evaluation] = None
    counts_fn = None
    for ds in iterator:
        inputs, labels, fms, lms, _ = _shard_batch(ds, net, mesh)
        if ev is None:
            n_classes = n_classes or labels[0].shape[-1]
            ev = Evaluation(n_classes)
            ev._ensure(n_classes)
            counts_fn = _get_counts_fn(net, n_classes)
        w = lms[0].reshape(-1)
        counts = counts_fn(net.params, net.variables, inputs, fms,
                           labels[0], w)
        ev.confusion.matrix += np.rint(np.asarray(counts)).astype(np.int64)
    if ev is None:
        ev = Evaluation(n_classes or 2)
        ev._ensure(n_classes or 2)
    return ev


def distributed_score(net, iterator, mesh: Optional[Mesh] = None,
                      average: bool = True) -> float:
    """Mesh-sharded dataset loss; equals local DataSetLossCalculator
    (SparkDataSetLossCalculator analog)."""
    mesh = mesh or default_mesh()
    net._check_init()
    repl = NamedSharding(mesh, P())
    net.params = _tree_put(net.params, repl)
    net.variables = _tree_put(net.variables, repl)
    score_fn = _get_score_fn(net)
    total, n = 0.0, 0
    for ds in iterator:
        inputs, labels, fms, lms, orig = _shard_batch(ds, net, mesh)
        loss = float(score_fn(net.params, net.variables, inputs, fms,
                              labels, lms))
        total += loss * orig
        n += orig
    if n == 0:
        return float("nan")
    return total / n if average else total


class DistributedDataSetLossCalculator(ScoreCalculator):
    """Early-stopping score calculator running on the mesh
    (reference SparkDataSetLossCalculator)."""

    def __init__(self, iterator, mesh: Optional[Mesh] = None,
                 average: bool = True):
        self.iterator = iterator
        self.mesh = mesh or default_mesh()
        self.average = average

    def calculate_score(self, net) -> float:
        self.iterator.reset()
        return distributed_score(net, self.iterator, self.mesh, self.average)


class DistributedEarlyStoppingTrainer(EarlyStoppingTrainer):
    """Early stopping with epochs trained through a TrainingMaster
    (reference SparkEarlyStoppingTrainer.java:37)."""

    def __init__(self, config, net, train_iterator, master: TrainingMaster):
        super().__init__(config, net, train_iterator)
        self.master = master

    def _fit_epoch(self, result: EarlyStoppingResult) -> bool:
        self.master.execute_training(self.net, self.iterator)
        for cond in self.config.iteration_termination_conditions:
            if cond.terminate(self.net.score_):
                result.termination_reason = "IterationTerminationCondition"
                result.termination_details = type(cond).__name__
                return True
        return False
