"""Driver-facing distributed model wrappers (the SparkDl4jMultiLayer /
SparkComputationGraph API surface).

Parity with `spark/dl4j-spark/.../impl/multilayer/SparkDl4jMultiLayer.java:67`
and `impl/graph/SparkComputationGraph.java`: a facade that owns (network
configuration, TrainingMaster) and exposes fit(distributed data) /
evaluate / score / predict — the entry point a reference user's driver
program calls.

TPU-native translation: "the cluster" is the device mesh; the RDD is any
(re-)iterable of DataSets — a list, a DataSetIterator, a generator factory,
or a lazily-loaded shard collection. `fit` hands it to the configured
TrainingMaster (ICI all-reduce or parameter averaging), so the reference's
driver -> executors -> aggregate round trip becomes driver -> one
jit-dispatched collective program. Evaluation/scoring run sharded over the
same mesh (parallel/evaluation.py — the EvaluateFlatMapFunction +
EvaluationReduceFunction analog).
"""
from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from jax.sharding import Mesh

from .evaluation import distributed_evaluate, distributed_score
from .mesh import default_mesh
from .trainer import (IciDataParallelTrainingMaster,
                      ParameterAveragingTrainingMaster, TrainingMaster)


class SparkDl4jMultiLayer:
    """Reference SparkDl4jMultiLayer.java:67 — the driver's handle on a
    distributed MultiLayerNetwork."""

    def __init__(self, conf_or_net, training_master: Optional[TrainingMaster]
                 = None, mesh: Optional[Mesh] = None):
        from ..nn.multilayer import MultiLayerNetwork
        if hasattr(conf_or_net, "params"):
            self.net = conf_or_net
        else:
            self.net = MultiLayerNetwork(conf_or_net)
        self.net._check_init()
        self.mesh = mesh or getattr(training_master, "mesh", None) \
            or default_mesh()
        self.master = training_master or IciDataParallelTrainingMaster(
            mesh=self.mesh)

    # -- training (reference fit(RDD):190,200) ---------------------------------
    def fit(self, data: Iterable) -> "SparkDl4jMultiLayer":
        """data: any iterable of DataSets (the RDD analog)."""
        self.master.execute_training(self.net, data)
        return self

    def fit_paths(self, paths: Iterable[str],
                  loader=None) -> "SparkDl4jMultiLayer":
        """Reference fit(String path): train from serialized DataSet files
        (the pre-vectorized export workflow, StringToDataSetExportFunction).
        `loader(path) -> DataSet` defaults to numpy .npz with features/labels."""
        from ..datasets.dataset import DataSet

        def default_loader(p):
            with np.load(p) as z:
                return DataSet(z["features"], z["labels"],
                               z.get("features_mask"), z.get("labels_mask"))

        load = loader or default_loader
        self.master.execute_training(self.net,
                                     (load(p) for p in paths))
        return self

    # -- inference/metrics -----------------------------------------------------
    def predict(self, x) -> np.ndarray:
        """MLlib-style predict (reference predict(Matrix):169-180)."""
        return np.asarray(self.net.output(np.asarray(x)))

    def evaluate(self, iterator, n_classes: Optional[int] = None):
        """Sharded evaluation over the mesh (reference distributed
        evaluation via EvaluateFlatMapFunction)."""
        return distributed_evaluate(self.net, iterator, mesh=self.mesh,
                                    n_classes=n_classes)

    def score(self, iterator) -> float:
        """Mean loss over a dataset, computed sharded (reference
        SparkDl4jMultiLayer.calculateScore)."""
        return distributed_score(self.net, iterator, mesh=self.mesh)

    def get_network(self):
        """Reference getNetwork(): the driver-side model with the final
        parameters."""
        return self.net

    def get_training_master(self) -> TrainingMaster:
        return self.master

    def get_training_stats(self):
        return self.master.get_training_stats()


class SparkComputationGraph(SparkDl4jMultiLayer):
    """Reference impl/graph/SparkComputationGraph.java — same facade over a
    ComputationGraph (the unified masters already drive both)."""

    def __init__(self, conf_or_net, training_master: Optional[TrainingMaster]
                 = None, mesh: Optional[Mesh] = None):
        from ..nn.graph import ComputationGraph
        if not hasattr(conf_or_net, "params"):
            conf_or_net = ComputationGraph(conf_or_net)
        super().__init__(conf_or_net, training_master, mesh)

    def predict(self, *inputs) -> np.ndarray:
        outs = self.net.output(*[np.asarray(a) for a in inputs])
        return np.asarray(outs[0] if isinstance(outs, (list, tuple)) else outs)
