"""Unsupervised pretrain layers: RBM (CD-k) and denoising AutoEncoder.

Parity: reference nn/layers/feedforward/rbm/RBM.java (contrastiveDivergence
:101, sampleHiddenGivenVisible :225, Gibbs chain) and
nn/layers/feedforward/autoencoder/AutoEncoder.java.

As supervised layers they act like a Dense layer (propup). For layerwise
pretraining (reference MultiLayerNetwork.pretrain:165) they expose:
  - RBM.cd_gradient:       CD-k gradient (positive - negative phase stats)
    computed directly — CD is not a differentiable loss, same as reference;
  - AutoEncoder.pretrain_loss: reconstruction loss, differentiated by jax.grad.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .base import LayerImpl, register_impl
from .feedforward import _LinearLayer
from .. import weights as winit
from ...ops import losses as losses_mod

Array = jax.Array


class _PretrainCore(_LinearLayer):
    def init_params(self, key, dtype=jnp.float32):
        params = super().init_params(key, dtype)
        params["vb"] = jnp.zeros((self.conf.n_in,), dtype)  # visible bias
        return params


@register_impl("RBM")
class RBMImpl(_PretrainCore):
    def _hidden_activation(self, pre: Array, rng=None, sample: bool = False) -> Array:
        kind = self.conf.hidden_unit.lower()
        if kind == "binary":
            p = jax.nn.sigmoid(pre)
            if sample and rng is not None:
                return jax.random.bernoulli(rng, p).astype(pre.dtype)
            return p
        if kind == "rectified":
            if sample and rng is not None:
                noise = jax.random.normal(rng, pre.shape, pre.dtype) * jnp.sqrt(
                    jax.nn.sigmoid(pre))
                return jnp.maximum(0.0, pre + noise)
            return jnp.maximum(0.0, pre)
        if kind == "gaussian":
            if sample and rng is not None:
                return pre + jax.random.normal(rng, pre.shape, pre.dtype)
            return pre
        if kind == "softmax":
            return jax.nn.softmax(pre, axis=-1)
        raise ValueError(f"Unknown hidden unit '{kind}'")

    def _visible_activation(self, pre: Array, rng=None, sample: bool = False) -> Array:
        kind = self.conf.visible_unit.lower()
        if kind == "binary":
            p = jax.nn.sigmoid(pre)
            if sample and rng is not None:
                return jax.random.bernoulli(rng, p).astype(pre.dtype)
            return p
        if kind in ("gaussian", "linear"):
            if sample and rng is not None and kind == "gaussian":
                return pre + jax.random.normal(rng, pre.shape, pre.dtype)
            return pre
        if kind == "softmax":
            return jax.nn.softmax(pre, axis=-1)
        raise ValueError(f"Unknown visible unit '{kind}'")

    def prop_up(self, params, v: Array, rng=None, sample=False) -> Array:
        return self._hidden_activation(v @ params["W"] + params["b"], rng, sample)

    def prop_down(self, params, h: Array, rng=None, sample=False) -> Array:
        return self._visible_activation(h @ params["W"].T + params["vb"], rng, sample)

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        x = self._dropout(x, train, rng)
        return self.prop_up(params, x), variables or {}

    def cd_gradient(self, params, v0: Array, rng: jax.Array,
                    k: int = None) -> Tuple[Dict[str, Array], Array]:
        """CD-k gradients (to MINIMIZE, i.e. negative log-likelihood direction)
        and reconstruction error. Mirrors RBM.contrastiveDivergence:101."""
        k = k or int(self.conf.k)
        B = v0.shape[0]
        h0_prob = self.prop_up(params, v0)
        keys = jax.random.split(rng, 2 * k + 1)
        h = jax.random.bernoulli(keys[0], h0_prob).astype(v0.dtype) \
            if self.conf.hidden_unit == "binary" else h0_prob
        vk = v0
        for i in range(k):
            vk = self.prop_down(params, h, keys[2 * i + 1],
                                sample=self.conf.visible_unit == "binary")
            hk_prob = self.prop_up(params, vk)
            h = jax.random.bernoulli(keys[2 * i + 2], hk_prob).astype(v0.dtype) \
                if self.conf.hidden_unit == "binary" else hk_prob
        hk_prob = self.prop_up(params, vk)
        # positive - negative phase, averaged over batch; negate for descent
        gW = -(v0.T @ h0_prob - vk.T @ hk_prob) / B
        gb = -jnp.mean(h0_prob - hk_prob, axis=0)
        gvb = -jnp.mean(v0 - vk, axis=0)
        recon = losses_mod.mse(v0, self.prop_down(params, h0_prob))
        return {"W": gW, "b": gb, "vb": gvb}, recon


@register_impl("AutoEncoder")
class AutoEncoderImpl(_PretrainCore):
    def encode(self, params, x: Array) -> Array:
        return self.activation_fn()(x @ params["W"] + params["b"])

    def decode(self, params, h: Array) -> Array:
        return self.activation_fn()(h @ params["W"].T + params["vb"])

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        x = self._dropout(x, train, rng)
        return self.encode(params, x), variables or {}

    def pretrain_loss(self, params, x: Array, rng: jax.Array) -> Array:
        """Denoising reconstruction loss (corruption = input dropout noise)."""
        level = float(self.conf.corruption_level or 0.0)
        if level > 0.0:
            keep = jax.random.bernoulli(rng, 1.0 - level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        else:
            corrupted = x
        recon = self.decode(params, self.encode(params, corrupted))
        loss_fn = losses_mod.get(self.conf.loss or "reconstruction_crossentropy")
        return loss_fn(x, recon)
