"""Layer implementation SPI + registry.

Parity with the reference's `nn/api/Layer` + `nn/layers/BaseLayer`
(deeplearning4j-core/.../nn/api/Layer.java:37 — activate/preOutput/
backpropGradient — and BaseLayer.java: dropout :59,230, masking :154,361).

TPU-first redesign: a layer impl is a thin stateless object bound to its
config; params live in an external pytree (dict name->Array), forward is a
pure jax-traceable function, and the backward pass is derived by jax.grad —
there is no handwritten `backpropGradient` (the reference needs one because
ND4J has no autodiff). Non-trainable state (BN running stats) rides in
`variables`; recurrent stepping state (rnnTimeStep) in `state`.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp

Array = jax.Array
Params = Dict[str, Array]
Variables = Dict[str, Array]

LAYER_IMPLS: Dict[str, Type["LayerImpl"]] = {}


def remat_forward(impl, *, train: bool, ckpt: bool, recurrent: bool,
                  in_scan: bool = False):
    """Bind a layer impl's forward into positional-tracer form and, when
    ``ckpt``, wrap it in jax.checkpoint (layer-granularity rematerialization:
    backward recomputes layer internals instead of storing them — the
    HBM<->FLOPs trade behind `NeuralNetConfiguration.remat`).

    Positional signature: recurrent -> f(params, x, state0, rng, mask);
    feed-forward -> f(params, x, variables, rng, mask). Static flags stay
    closed over so Python control flow inside forward still works.

    ``in_scan``: set when tracing inside a lax.scan body (fit_scan). There
    the scan boundary already prevents XLA CSE from undoing the remat, so
    checkpoint's optimization barriers (prevent_cse=True, needed for the
    plain jitted step — measured: barriers off erodes the memory saving
    452->448 MB vs 452->421 MB with them) would only block fusion.
    """
    if recurrent:
        def fwd(p, c, s, r, m):
            return impl.forward_with_state(p, c, s, train=train, rng=r, mask=m)
    else:
        def fwd(p, c, v, r, m):
            return impl.forward(p, c, train=train, rng=r, variables=v, mask=m)
    return jax.checkpoint(fwd, prevent_cse=not in_scan) if ckpt else fwd


def register_impl(conf_cls_name: str):
    def deco(cls):
        LAYER_IMPLS[conf_cls_name] = cls
        return cls
    return deco


def impl_for(conf) -> "LayerImpl":
    name = type(conf).__name__
    if name not in LAYER_IMPLS:
        raise ValueError(f"No layer implementation registered for config {name}")
    return LAYER_IMPLS[name](conf)


class LayerImpl:
    """Stateless functional layer bound to a resolved config."""

    # weight param names regularized by l1/l2 (biases excluded, matching the
    # reference's weight-only regularization in BaseLayer.calcL2)
    WEIGHT_KEYS = ("W",)

    def __init__(self, conf):
        self.conf = conf

    # -- params ----------------------------------------------------------------
    def init_params(self, key: jax.Array, dtype=jnp.float32) -> Params:
        return {}

    def init_variables(self, dtype=jnp.float32) -> Variables:
        return {}

    def has_params(self) -> bool:
        return True

    # -- forward ---------------------------------------------------------------
    def forward(
        self,
        params: Params,
        x: Array,
        *,
        train: bool = False,
        rng: Optional[jax.Array] = None,
        variables: Optional[Variables] = None,
        mask: Optional[Array] = None,
    ) -> Tuple[Array, Variables]:
        """Returns (activations, updated variables)."""
        raise NotImplementedError

    # -- regularization contribution to the score ------------------------------
    def reg_loss(self, params: Params) -> Array:
        l1 = float(getattr(self.conf, "l1", 0.0) or 0.0)
        l2 = float(getattr(self.conf, "l2", 0.0) or 0.0)
        acc_dtype = jnp.float32
        for k in self.WEIGHT_KEYS:
            if k in params:
                acc_dtype = jnp.promote_types(params[k].dtype, jnp.float32)
                break
        total = jnp.asarray(0.0, acc_dtype)
        if l1 == 0.0 and l2 == 0.0:
            return total
        for k in self.WEIGHT_KEYS:
            if k in params:
                w = params[k].astype(acc_dtype)
                if l2:
                    total = total + 0.5 * l2 * jnp.sum(w * w)
                if l1:
                    total = total + l1 * jnp.sum(jnp.abs(w))
        return total

    # -- helpers ---------------------------------------------------------------
    def _dropout(self, x: Array, train: bool, rng: Optional[jax.Array]) -> Array:
        """Input dropout (reference BaseLayer.applyDropOutIfNecessary:59).
        Inverted dropout: scale kept units by 1/(1-p) at train time."""
        p = float(getattr(self.conf, "dropout", 0.0) or 0.0)
        if not train or p <= 0.0:
            return x
        if rng is None:
            raise ValueError("dropout requires an rng key at train time")
        keep = 1.0 - p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    def activation_fn(self):
        from ...ops import activations
        return activations.get(self.conf.activation or "identity")
