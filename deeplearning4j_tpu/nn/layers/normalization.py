"""BatchNormalization + LocalResponseNormalization impls.

Parity: reference nn/layers/normalization/BatchNormalization.java (train vs
global stats preOutput:200, gamma/beta :103,227-231) and
LocalResponseNormalization.java; accelerated via the helper seam
(reference CudnnBatchNormalizationHelper / CudnnLocalResponseNormalizationHelper).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, register_impl
from ...ops import helpers as ophelpers

Array = jax.Array


@register_impl("BatchNormalization")
class BatchNormalizationImpl(LayerImpl):
    WEIGHT_KEYS = ()  # gamma/beta not regularized (matches reference)

    def init_params(self, key, dtype=jnp.float32):
        conf = self.conf
        n = conf.n_out
        if conf.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((n,), float(conf.gamma), dtype),
            "beta": jnp.full((n,), float(conf.beta), dtype),
        }

    def init_variables(self, dtype=jnp.float32):
        n = self.conf.n_out
        return {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        conf = self.conf
        variables = variables or self.init_variables(x.dtype)
        axes = tuple(range(x.ndim - 1))  # all but channel/feature
        if conf.lock_gamma_beta:
            gamma = jnp.asarray(conf.gamma, x.dtype)
            beta = jnp.asarray(conf.beta, x.dtype)
        else:
            gamma, beta = params["gamma"], params["beta"]

        if train and not conf.use_global_stats:
            mean32, var32 = ophelpers.bn_batch_stats(x)
            mean = mean32.astype(x.dtype)
            var = var32.astype(x.dtype)
            vdt = variables["mean"].dtype
            d = jnp.asarray(conf.decay, vdt)
            new_vars = {
                "mean": d * variables["mean"] + (1.0 - d) * mean32.astype(vdt),
                "var": d * variables["var"] + (1.0 - d) * var32.astype(vdt),
            }
        else:
            mean, var = variables["mean"], variables["var"]
            new_vars = variables

        y = ophelpers.batch_norm(x, gamma, beta, mean, var, eps=conf.eps)
        return self.activation_fn()(y) if conf.activation not in (None, "identity", "linear") else y, new_vars


    def forward_fused_pool(self, params, x, *, variables=None):
        """Train-mode BN + activation + the FOLLOWING 2x2/s2 max-pool layer
        as one composite op (ops/helpers.bn_act_pool). Engaged by the
        facades when the layer pair matches (nn/multilayer._forward_impl);
        the Pallas plugin overrides the composite's backward with a 2-pass
        fused kernel (ops/pallas_kernels.py). Semantics are identical to
        running the two layers separately."""
        conf = self.conf
        variables = variables or self.init_variables(x.dtype)
        if conf.lock_gamma_beta:
            gamma = jnp.full((conf.n_out,), float(conf.gamma), x.dtype)
            beta = jnp.full((conf.n_out,), float(conf.beta), x.dtype)
        else:
            gamma, beta = params["gamma"], params["beta"]
        y, mean32, var32 = ophelpers.bn_act_pool(
            x, gamma, beta, eps=conf.eps,
            activation=conf.activation or "identity")
        vdt = variables["mean"].dtype
        d = jnp.asarray(conf.decay, vdt)
        new_vars = {
            "mean": d * variables["mean"] + (1.0 - d) * mean32.astype(vdt),
            "var": d * variables["var"] + (1.0 - d) * var32.astype(vdt),
        }
        return y, new_vars

    @staticmethod
    def can_fuse_pool(bn_conf, pool_conf, x) -> bool:
        """True when [this BN layer -> pool_conf] matches the fused
        composite: train batch stats, 2x2/s2 max pool with no effective
        padding, even spatial dims."""
        return (x.ndim == 4
                and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0
                and not bn_conf.use_global_stats
                and pool_conf.pooling_type == "max"
                and tuple(pool_conf.kernel_size) == (2, 2)
                and tuple(pool_conf.stride) == (2, 2)
                and (pool_conf.convolution_mode == "same"
                     or tuple(pool_conf.padding) == (0, 0)))


@register_impl("LocalResponseNormalization")
class LocalResponseNormalizationImpl(LayerImpl):
    def has_params(self):
        return False

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        c = self.conf
        return ophelpers.lrn(x, k=c.k, n=c.n, alpha=c.alpha, beta=c.beta), variables or {}


@register_impl("LayerNormalization")
class LayerNormalizationImpl(LayerImpl):
    """Per-example normalization over the trailing feature axis with learned
    gain/bias (transformer building block — see conf LayerNormalization)."""

    def init_params(self, key, dtype=jnp.float32):
        n = self.conf.n_out or self.conf.n_in
        return {"gain": jnp.ones((n,), dtype),
                "beta": jnp.zeros((n,), dtype)}

    def forward(self, params, x, *, train=False, rng=None, variables=None,
                mask=None):
        conf = self.conf
        x = self._dropout(x, train, rng)
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + jnp.asarray(conf.eps, x.dtype))
        y = y * params["gain"] + params["beta"]
        if conf.activation not in (None, "identity", "linear"):
            y = self.activation_fn()(y)
        return y, variables or {}
