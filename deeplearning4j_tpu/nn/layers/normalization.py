"""BatchNormalization + LocalResponseNormalization impls.

Parity: reference nn/layers/normalization/BatchNormalization.java (train vs
global stats preOutput:200, gamma/beta :103,227-231) and
LocalResponseNormalization.java; accelerated via the helper seam
(reference CudnnBatchNormalizationHelper / CudnnLocalResponseNormalizationHelper).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, register_impl
from ...ops import helpers as ophelpers

Array = jax.Array


@register_impl("BatchNormalization")
class BatchNormalizationImpl(LayerImpl):
    WEIGHT_KEYS = ()  # gamma/beta not regularized (matches reference)

    def init_params(self, key, dtype=jnp.float32):
        conf = self.conf
        n = conf.n_out
        if conf.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((n,), float(conf.gamma), dtype),
            "beta": jnp.full((n,), float(conf.beta), dtype),
        }

    def init_variables(self, dtype=jnp.float32):
        n = self.conf.n_out
        return {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        conf = self.conf
        variables = variables or self.init_variables(x.dtype)
        axes = tuple(range(x.ndim - 1))  # all but channel/feature
        if conf.lock_gamma_beta:
            gamma = jnp.asarray(conf.gamma, x.dtype)
            beta = jnp.asarray(conf.beta, x.dtype)
        else:
            gamma, beta = params["gamma"], params["beta"]

        if train and not conf.use_global_stats:
            if x.dtype in (jnp.bfloat16, jnp.float16):
                # single-pass E[x^2]-E[x]^2 with f32 accumulation: one fused
                # multi-output reduction over x instead of mean-then-var's
                # two passes (the activations are the big HBM tensors; the
                # device trace showed the two-pass stats as separate
                # convert_reduce fusions). Safe only for sub-f32 inputs,
                # where f32 accumulation has ~16 guard bits over the data's
                # significand; for f32/f64 the cancellation E[x^2]-mean^2
                # would destroy precision, so keep two-pass jnp.var there.
                xf = x.astype(jnp.float32)
                mean32 = jnp.mean(xf, axis=axes)
                var32 = jnp.maximum(
                    jnp.mean(xf * xf, axis=axes) - mean32 * mean32, 0.0)
            else:
                mean32 = jnp.mean(x, axis=axes)
                var32 = jnp.var(x, axis=axes)
            mean = mean32.astype(x.dtype)
            var = var32.astype(x.dtype)
            vdt = variables["mean"].dtype
            d = jnp.asarray(conf.decay, vdt)
            new_vars = {
                "mean": d * variables["mean"] + (1.0 - d) * mean32.astype(vdt),
                "var": d * variables["var"] + (1.0 - d) * var32.astype(vdt),
            }
        else:
            mean, var = variables["mean"], variables["var"]
            new_vars = variables

        y = ophelpers.batch_norm(x, gamma, beta, mean, var, eps=conf.eps)
        return self.activation_fn()(y) if conf.activation not in (None, "identity", "linear") else y, new_vars


@register_impl("LocalResponseNormalization")
class LocalResponseNormalizationImpl(LayerImpl):
    def has_params(self):
        return False

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        c = self.conf
        return ophelpers.lrn(x, k=c.k, n=c.n, alpha=c.alpha, beta=c.beta), variables or {}


@register_impl("LayerNormalization")
class LayerNormalizationImpl(LayerImpl):
    """Per-example normalization over the trailing feature axis with learned
    gain/bias (transformer building block — see conf LayerNormalization)."""

    def init_params(self, key, dtype=jnp.float32):
        n = self.conf.n_out or self.conf.n_in
        return {"gain": jnp.ones((n,), dtype),
                "beta": jnp.zeros((n,), dtype)}

    def forward(self, params, x, *, train=False, rng=None, variables=None,
                mask=None):
        conf = self.conf
        x = self._dropout(x, train, rng)
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + jnp.asarray(conf.eps, x.dtype))
        y = y * params["gain"] + params["beta"]
        if conf.activation not in (None, "identity", "linear"):
            y = self.activation_fn()(y)
        return y, variables or {}
