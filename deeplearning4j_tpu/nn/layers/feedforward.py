"""Dense / output / activation / dropout / embedding layer impls.

Parity: reference nn/layers/DenseLayer, BaseOutputLayer/OutputLayer,
ActivationLayer, DropoutLayer, feedforward/embedding/EmbeddingLayer
(deeplearning4j-core/.../nn/layers/; preOutput = x·W + b per
BaseLayer.preOutput).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .base import LayerImpl, register_impl
from .. import weights as winit

Array = jax.Array


class _LinearLayer(LayerImpl):
    def init_params(self, key, dtype=jnp.float32):
        conf = self.conf
        kw, _ = jax.random.split(key)
        dist = conf.dist.spec() if getattr(conf, "dist", None) is not None else None
        W = winit.init_weights(kw, (conf.n_in, conf.n_out), conf.weight_init or "xavier",
                               dist, dtype)
        b = jnp.full((conf.n_out,), float(conf.bias_init or 0.0), dtype)
        return {"W": W, "b": b}

    def _pre_output(self, params, x):
        return x @ params["W"] + params["b"]

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        y, _, v = self.forward_with_preout(params, x, train=train, rng=rng,
                                           variables=variables, mask=mask)
        return y, v

    def forward_with_preout(self, params, x, *, train=False, rng=None,
                            variables=None, mask=None):
        """forward() that additionally returns the PRE-activation output, so
        the loss path can use the stable from-logits losses
        (ops/losses.fused_from_logits) — reproducing the reference's analytic
        output-layer delta (BaseOutputLayer.java getGradientsAndDelta).
        forward() delegates here: one definition of the layer math."""
        x = self._dropout(x, train, rng)
        z = self._pre_output(params, x)
        return self.activation_fn()(z), z, variables or {}


@register_impl("DenseLayer")
class DenseLayerImpl(_LinearLayer):
    pass


@register_impl("OutputLayer")
class OutputLayerImpl(_LinearLayer):
    """Output layer; the network computes the loss from conf.loss
    (reference BaseOutputLayer computes score via LossCalculation)."""


@register_impl("RnnOutputLayer")
class RnnOutputLayerImpl(_LinearLayer):
    """Per-timestep output: [B, T, F] -> [B, T, n_out]
    (reference nn/layers/recurrent/RnnOutputLayer.java reshapes 3d<->2d)."""

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        y, _, v = self.forward_with_preout(params, x, train=train, rng=rng,
                                           variables=variables, mask=mask)
        return y, v

    def forward_with_preout(self, params, x, *, train=False, rng=None,
                            variables=None, mask=None):
        x = self._dropout(x, train, rng)
        z = jnp.einsum("btf,fo->bto", x, params["W"]) + params["b"]
        y = self.activation_fn()(z)
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, z, variables or {}


@register_impl("LossLayer")
class LossLayerImpl(LayerImpl):
    def has_params(self):
        return False

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        y, _, v = self.forward_with_preout(params, x, train=train, rng=rng,
                                           variables=variables, mask=mask)
        return y, v

    def forward_with_preout(self, params, x, *, train=False, rng=None,
                            variables=None, mask=None):
        """LossLayer's pre-activation IS its input — exposing it keeps the
        stable from-logits loss path (the saturated-softmax wedge fix)
        working for nets that end in LossLayer(softmax, mcxent)."""
        return self.activation_fn()(x), x, variables or {}


@register_impl("ActivationLayer")
class ActivationLayerImpl(LayerImpl):
    def has_params(self):
        return False

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        x = self._dropout(x, train, rng)
        return self.activation_fn()(x), variables or {}


@register_impl("DropoutLayer")
class DropoutLayerImpl(LayerImpl):
    def has_params(self):
        return False

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        return self._dropout(x, train, rng), variables or {}


@register_impl("GlobalPoolingLayer")
class GlobalPoolingLayerImpl(LayerImpl):
    """Pool over time ([B,T,F] -> [B,F]) or space ([B,H,W,C] -> [B,C])."""

    def has_params(self):
        return False

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        pool = self.conf.pooling_type.lower()
        axes = (1,) if x.ndim == 3 else (1, 2)
        if pool == "max":
            if mask is not None and x.ndim == 3:
                neg = jnp.finfo(x.dtype).min
                x = jnp.where(mask[..., None] > 0, x, neg)
            return jnp.max(x, axis=axes), variables or {}
        if pool in ("avg", "mean"):
            if mask is not None and x.ndim == 3:
                m = mask[..., None].astype(x.dtype)
                s = jnp.sum(x * m, axis=axes)
                return s / jnp.maximum(jnp.sum(m, axis=axes), 1.0), variables or {}
            return jnp.mean(x, axis=axes), variables or {}
        if pool == "sum":
            if mask is not None and x.ndim == 3:
                x = x * mask[..., None].astype(x.dtype)
            return jnp.sum(x, axis=axes), variables or {}
        if pool == "pnorm":
            p = float(getattr(self.conf, "pnorm", 2))
            return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axes), 1.0 / p), variables or {}
        raise ValueError(f"Unknown pooling type {pool}")


@register_impl("EmbeddingLayer")
class EmbeddingLayerImpl(LayerImpl):
    """Row lookup (reference nn/layers/feedforward/embedding/EmbeddingLayer.java).
    Accepts integer indices [B] / [B,1] or one-hot [B, n_in]; the lookup is a
    gather, which XLA lowers to a dynamic-slice — no one-hot matmul needed."""

    def init_params(self, key, dtype=jnp.float32):
        conf = self.conf
        dist = conf.dist.spec() if getattr(conf, "dist", None) is not None else None
        W = winit.init_weights(key, (conf.n_in, conf.n_out), conf.weight_init or "xavier",
                               dist, dtype)
        params = {"W": W}
        if getattr(conf, "has_bias", True):
            params["b"] = jnp.full((conf.n_out,), float(conf.bias_init or 0.0), dtype)
        return params

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.ndim == 2 and x.shape[-1] == self.conf.n_in:
            out = x @ params["W"]  # one-hot path
        else:
            idx = x.astype(jnp.int32).reshape(x.shape[0], -1)[:, 0] if x.ndim > 1 else x.astype(jnp.int32)
            out = jnp.take(params["W"], idx, axis=0)
        if "b" in params:
            out = out + params["b"]
        return self.activation_fn()(out), variables or {}
