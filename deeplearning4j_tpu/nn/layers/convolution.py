"""Convolution + subsampling (pooling) layer impls, NHWC.

Parity: reference nn/layers/convolution/ConvolutionLayer.java (im2col path +
cuDNN helper hook at :64,212) and SubsamplingLayer.java (max/avg pooling).

TPU-first: the im2col+gemm formulation and the cuDNN helper seam both
collapse into `jax.lax.conv_general_dilated`, which XLA tiles directly onto
the MXU; pooling is `lax.reduce_window`. The accelerated-helper plugin seam
(SURVEY.md §2.3) is preserved at the op level in ops/helpers.py: layers call
through a registry that Pallas kernels can override.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .base import LayerImpl, register_impl
from .. import weights as winit
from ...ops import helpers as ophelpers

Array = jax.Array


def _padding_config(conf):
    if conf.convolution_mode == "same":
        return "SAME"
    ph, pw = conf.padding
    return ((ph, ph), (pw, pw))


@register_impl("ConvolutionLayer")
class ConvolutionLayerImpl(LayerImpl):
    def init_params(self, key, dtype=jnp.float32):
        conf = self.conf
        kh, kw = conf.kernel_size
        dist = conf.dist.spec() if getattr(conf, "dist", None) is not None else None
        W = winit.init_weights(key, (kh, kw, conf.n_in, conf.n_out),
                               conf.weight_init or "xavier", dist, dtype)
        b = jnp.full((conf.n_out,), float(conf.bias_init or 0.0), dtype)
        return {"W": W, "b": b}

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        x = self._dropout(x, train, rng)
        conf = self.conf
        y = ophelpers.conv2d_bias_act(
            x, params["W"], params["b"],
            stride=conf.stride,
            padding=_padding_config(conf),
            dilation=conf.dilation,
            activation=conf.activation or "identity",
        )
        return y, variables or {}


@register_impl("SubsamplingLayer")
class SubsamplingLayerImpl(LayerImpl):
    def has_params(self):
        return False

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        conf = self.conf
        y = ophelpers.pool2d(
            x,
            kind=conf.pooling_type,
            kernel=conf.kernel_size,
            stride=conf.stride,
            padding=_padding_config(conf),
            pnorm=getattr(conf, "pnorm", 2),
        )
        return y, variables or {}
