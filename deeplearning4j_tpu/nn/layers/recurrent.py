"""Recurrent layer impls: LSTM, GravesLSTM (peepholes), bidirectional, GRU.

Parity: reference nn/layers/recurrent/GravesLSTM.java + LSTMHelpers.java
(shared fwd `activateHelper:55` with hot per-timestep loop `:132-145`, bwd
`:273`), GravesBidirectionalLSTM.java, GRU.java, BaseRecurrentLayer.java
(rnnTimeStep stateful inference + TBPTT state carry).

TPU-first redesign of the :132 timestep loop:
  - the input projection x·W for ALL timesteps is hoisted out of the loop
    into one large [B*T, n_in]x[n_in, 4H] matmul (MXU-friendly), so the
    `lax.scan` body only carries the [B,H]x[H,4H] recurrent matmul;
  - the backward pass is jax.grad through the scan (no handwritten BPTT);
  - masking for variable-length sequences gates both output and state carry
    (reference per-timestep masking, GradientCheckTestsMasking).
Layout: [batch, time, features] (reference uses [b, f, t]).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .base import LayerImpl, register_impl
from .. import weights as winit
from ...ops import helpers as ophelpers

Array = jax.Array
State = Dict[str, Array]


class BaseRecurrentImpl(LayerImpl):
    WEIGHT_KEYS = ("W", "RW")
    # whether TBPTT carries this impl's state across windows (true RNN
    # state; the attention KV cache opts out — it is inference-only)
    TBPTT_STATE = True

    def init_state(self, batch: int, dtype=jnp.float32) -> State:
        raise NotImplementedError

    def step(self, params: Dict[str, Array], x_t: Array, state: State) -> Tuple[Array, State]:
        """One timestep for stateful inference (reference rnnTimeStep)."""
        raise NotImplementedError

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        y, _ = self.forward_with_state(params, x, None, train=train, rng=rng, mask=mask)
        return y, variables or {}

    def forward_with_state(self, params, x, state0: Optional[State], *,
                           train=False, rng=None, mask=None) -> Tuple[Array, State]:
        raise NotImplementedError

    def _mask_carry(self, new_state: State, old_state: State, m_t: Array) -> State:
        """Masked timesteps keep the previous state (variable-length support)."""
        return {k: m_t * new_state[k] + (1.0 - m_t) * old_state[k] for k in new_state}


def _materialize_rnn_states(impl_items, existing, batch, dtype, *,
                            tbptt=False):
    """Initial states for stateful layers: existing entries are kept, the
    rest are init_state'd. ``tbptt`` restricts to impls whose state TBPTT
    carries across windows (excludes the inference-only attention KV cache).
    Shared by both facades' rnn_time_step and _do_truncated_bptt."""
    states = dict(existing or {})
    for key, impl in impl_items:
        if not isinstance(impl, BaseRecurrentImpl):
            continue
        if tbptt and not impl.TBPTT_STATE:
            # no cache allocated, but the key must exist: the step returns
            # new_states for every stateful impl, and a key appearing only
            # after window 1 would change the carried pytree structure and
            # force a second XLA compile of the TBPTT train step
            states.setdefault(key, None)
            continue
        if states.get(key) is None:
            states[key] = impl.init_state(batch, dtype)
    return states


def _init_gate_weights(key, conf, n_gates: int, dtype, forget_slot: Optional[int] = None):
    conf_dist = conf.dist.spec() if getattr(conf, "dist", None) is not None else None
    k1, k2 = jax.random.split(key)
    H = conf.n_out
    W = winit.init_weights(k1, (conf.n_in, n_gates * H), conf.weight_init or "xavier",
                           conf_dist, dtype)
    RW = winit.init_weights(k2, (H, n_gates * H), conf.weight_init or "xavier",
                            conf_dist, dtype)
    b = jnp.full((n_gates * H,), float(conf.bias_init or 0.0), dtype)
    if forget_slot is not None:
        fb = float(getattr(conf, "forget_gate_bias_init", 1.0))
        b = b.at[forget_slot * H:(forget_slot + 1) * H].set(fb)
    return W, RW, b


class _LSTMCore(BaseRecurrentImpl):
    """Shared LSTM machinery; gate packing order [i, f, o, g]."""

    PEEPHOLE = False

    def init_params(self, key, dtype=jnp.float32):
        W, RW, b = _init_gate_weights(key, self.conf, 4, dtype, forget_slot=1)
        params = {"W": W, "RW": RW, "b": b}
        if self.PEEPHOLE:
            H = self.conf.n_out
            params.update({
                "pI": jnp.zeros((H,), dtype),
                "pF": jnp.zeros((H,), dtype),
                "pO": jnp.zeros((H,), dtype),
            })
        return params

    def init_state(self, batch, dtype=jnp.float32):
        H = self.conf.n_out
        return {"h": jnp.zeros((batch, H), dtype), "c": jnp.zeros((batch, H), dtype)}

    def _gates(self, params, xproj_t, state):
        """xproj_t: [B, 4H] (x·W + b precomputed); state: {h, c}.
        Cell math lives in ops/helpers.lstm_cell (single definition shared
        with the lstm_sequence seam)."""
        z = xproj_t + state["h"] @ params["RW"]
        peep = ((params["pI"], params["pF"], params["pO"]) if self.PEEPHOLE
                else (0.0, 0.0, 0.0))
        h, c = ophelpers.lstm_cell(z, state["c"], peep, self.activation_fn())
        return h, {"h": h, "c": c}

    def step(self, params, x_t, state):
        xproj = x_t @ params["W"] + params["b"]
        return self._gates(params, xproj, state)

    def forward_with_state(self, params, x, state0, *, train=False, rng=None,
                           mask=None, reverse=False):
        x = self._dropout(x, train, rng)
        B, T, _ = x.shape
        if state0 is None:
            state0 = self.init_state(B, x.dtype)
        # one big MXU matmul for all timesteps
        xproj = jnp.einsum("btf,fg->btg", x, params["W"]) + params["b"]
        xproj_t = jnp.swapaxes(xproj, 0, 1)  # [T, B, 4H]
        mask_t = (None if mask is None
                  else jnp.swapaxes(mask.astype(x.dtype), 0, 1)[..., None])  # [T, B, 1]

        if mask_t is None:
            # hot path: the whole sequence through the accelerated-helper
            # seam (ops/helpers.lstm_sequence; Pallas override available)
            H = self.conf.n_out
            peep = (jnp.stack([params["pI"], params["pF"], params["pO"]])
                    if self.PEEPHOLE else jnp.zeros((3, H), x.dtype))
            ys, ht, ct = ophelpers.lstm_sequence(
                xproj_t, params["RW"], peep, state0["h"], state0["c"],
                activation=self.conf.activation or "identity", reverse=reverse)
            return jnp.swapaxes(ys, 0, 1), {"h": ht, "c": ct}

        def body(state, inp):
            xp, m = inp
            h, new_state = self._gates(params, xp, state)
            new_state = self._mask_carry(new_state, state, m)
            h = h * m
            return new_state, h

        final, ys = lax.scan(body, state0, (xproj_t, mask_t), reverse=reverse)
        return jnp.swapaxes(ys, 0, 1), final  # [B, T, H]


@register_impl("LSTM")
class LSTMImpl(_LSTMCore):
    PEEPHOLE = False


@register_impl("GravesLSTM")
class GravesLSTMImpl(_LSTMCore):
    PEEPHOLE = True


@register_impl("GravesBidirectionalLSTM")
class GravesBidirectionalLSTMImpl(BaseRecurrentImpl):
    """Forward + backward GravesLSTM; outputs summed (reference
    GravesBidirectionalLSTM combines directional activations additively)."""

    WEIGHT_KEYS = ("fwd_W", "fwd_RW", "bwd_W", "bwd_RW")

    def __init__(self, conf):
        super().__init__(conf)
        self._cell = GravesLSTMImpl(conf)

    def init_params(self, key, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        fwd = self._cell.init_params(kf, dtype)
        bwd = self._cell.init_params(kb, dtype)
        out = {f"fwd_{k}": v for k, v in fwd.items()}
        out.update({f"bwd_{k}": v for k, v in bwd.items()})
        return out

    def init_state(self, batch, dtype=jnp.float32):
        return self._cell.init_state(batch, dtype)

    def forward_with_state(self, params, x, state0, *, train=False, rng=None, mask=None):
        fwd_p = {k[4:]: v for k, v in params.items() if k.startswith("fwd_")}
        bwd_p = {k[4:]: v for k, v in params.items() if k.startswith("bwd_")}
        yf, sf = self._cell.forward_with_state(fwd_p, x, None, train=train, rng=rng,
                                               mask=mask)
        yb, _ = self._cell.forward_with_state(bwd_p, x, None, train=train, rng=rng,
                                              mask=mask, reverse=True)
        return yf + yb, sf

    def step(self, params, x_t, state):
        # stateful stepping only uses the forward direction (bidirectional
        # inference needs the full sequence; matches reference behavior of
        # disallowing rnnTimeStep on bidirectional layers)
        raise NotImplementedError("rnnTimeStep is not supported for bidirectional LSTM")


@register_impl("GRU")
class GRUImpl(BaseRecurrentImpl):
    """Gated recurrent unit (reference nn/layers/recurrent/GRU.java).
    Gate packing [r, z, h~]; h_t = z*h_{t-1} + (1-z)*h~."""

    def init_params(self, key, dtype=jnp.float32):
        W, RW, b = _init_gate_weights(key, self.conf, 3, dtype)
        return {"W": W, "RW": RW, "b": b}

    def init_state(self, batch, dtype=jnp.float32):
        return {"h": jnp.zeros((batch, self.conf.n_out), dtype)}

    def _gates(self, params, xproj_t, state):
        H = self.conf.n_out
        act = self.activation_fn()
        h_prev = state["h"]
        rz = xproj_t[:, :2 * H] + h_prev @ params["RW"][:, :2 * H]
        r = jax.nn.sigmoid(rz[:, :H])
        z = jax.nn.sigmoid(rz[:, H:])
        hc = act(xproj_t[:, 2 * H:] + (r * h_prev) @ params["RW"][:, 2 * H:])
        h = z * h_prev + (1.0 - z) * hc
        return h, {"h": h}

    def step(self, params, x_t, state):
        xproj = x_t @ params["W"] + params["b"]
        return self._gates(params, xproj, state)

    def forward_with_state(self, params, x, state0, *, train=False, rng=None, mask=None):
        x = self._dropout(x, train, rng)
        B, T, _ = x.shape
        if state0 is None:
            state0 = self.init_state(B, x.dtype)
        xproj = jnp.einsum("btf,fg->btg", x, params["W"]) + params["b"]
        xproj_t = jnp.swapaxes(xproj, 0, 1)
        mask_t = (None if mask is None
                  else jnp.swapaxes(mask.astype(x.dtype), 0, 1)[..., None])

        def body(state, inp):
            xp, m = inp
            h, new_state = self._gates(params, xp, state)
            if m is not None:
                new_state = self._mask_carry(new_state, state, m)
                h = h * m
            return new_state, h

        if mask_t is None:
            final, ys = lax.scan(lambda s, xp: body(s, (xp, None)), state0, xproj_t)
        else:
            final, ys = lax.scan(body, state0, (xproj_t, mask_t))
        return jnp.swapaxes(ys, 0, 1), final
