"""Multi-head self-attention layer.

No reference counterpart (pre-transformer codebase — SURVEY.md §5); added as
the long-context-capable layer of this framework. Under a `pjit`/GSPMD mesh
the dense path shards automatically; for explicit sequence parallelism use
`parallel.ring.ring_attention` / `ulysses_attention` (same math, tested equal).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, register_impl
from .. import weights as winit
from ...ops import helpers as ophelpers

Array = jax.Array


@register_impl("SelfAttentionLayer")
class SelfAttentionLayerImpl(LayerImpl):
    WEIGHT_KEYS = ("Wq", "Wk", "Wv", "Wo")

    def init_params(self, key, dtype=jnp.float32):
        conf = self.conf
        dist = conf.dist.spec() if getattr(conf, "dist", None) is not None else None
        kq, kk, kv, ko = jax.random.split(key, 4)
        model = conf.n_out
        mk = lambda k, i, o: winit.init_weights(k, (i, o), conf.weight_init or "xavier",
                                                dist, dtype)
        return {
            "Wq": mk(kq, conf.n_in, model),
            "Wk": mk(kk, conf.n_in, model),
            "Wv": mk(kv, conf.n_in, model),
            "Wo": mk(ko, model, model),
            "b": jnp.full((model,), float(conf.bias_init or 0.0), dtype),
        }

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        conf = self.conf
        x = self._dropout(x, train, rng)
        B, T, _ = x.shape
        H = conf.n_heads
        Dh = conf.n_out // H

        def split(a):
            return a.reshape(B, T, H, Dh)

        q = split(jnp.einsum("btf,fo->bto", x, params["Wq"]))
        k = split(jnp.einsum("btf,fo->bto", x, params["Wk"]))
        v = split(jnp.einsum("btf,fo->bto", x, params["Wv"]))
        o = ophelpers.attention(q, k, v, causal=conf.causal)
        if mask is not None:
            o = o * mask[:, :, None, None].astype(o.dtype)
        out = jnp.einsum("btm,mn->btn", o.reshape(B, T, conf.n_out),
                         params["Wo"]) + params["b"]
        return self.activation_fn()(out), variables or {}
