"""Multi-head self-attention layer.

No reference counterpart (pre-transformer codebase — SURVEY.md §5); added as
the long-context-capable layer of this framework. Under a `pjit`/GSPMD mesh
the dense path shards automatically; for explicit sequence parallelism use
`parallel.ring.ring_attention` / `ulysses_attention` (same math, tested equal).

Streaming inference: the impl extends the recurrent-state protocol
(BaseRecurrentImpl), carrying a fixed-capacity KV cache as its state — so
`rnn_time_step` (reference rnnTimeStep:1460, O(1)-memory streaming) works
for transformers exactly like for LSTMs: O(L_max) per token instead of
re-forwarding the full context. Training always runs the full-sequence
path; the cache exists only on the inference step path.

The cached step is multi-token and per-slot: ``pos`` may be a [B] vector
(each batch row decoding at its own depth — the serving engine's slot
scheduling) and the incoming x may carry T > 1 timesteps (chunked
prefill, inference/engine.py): a chunk's K/V rows land at [pos, pos+T)
via per-row offset `dynamic_update_slice`, RoPE rotates at each row's
absolute positions, and the causal mask covers both the cache depth AND
query order within the chunk (`_grouped_attention` qpos0).

Two cache layouts share that step contract: the original contiguous
per-slot stripe ({"k", "v", "pos"}), and the paged layout
({"k_pages", "v_pages", "pos"} + an injected block ``table`` —
inference/kvpool.py, `_paged_step`) where K/V rows live in pool-wide
fixed-size pages and a slot's capacity is bounded by pool bytes instead
of ``max_cache_len``. Both run the same `_grouped_attention` math, so
they are token-identical.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import LayerImpl, register_impl
from .recurrent import BaseRecurrentImpl
from .. import weights as winit
from ...ops import helpers as ophelpers
from ...ops.kvquant import dequantize_kv_rows, quantize_kv_rows

Array = jax.Array


@register_impl("SelfAttentionLayer")
class SelfAttentionLayerImpl(BaseRecurrentImpl):
    WEIGHT_KEYS = ("Wq", "Wk", "Wv", "Wo")
    TBPTT_STATE = False  # the KV cache is inference-only state; training
    # always runs the full-sequence path (no cross-window carry)

    def _kv_heads(self) -> int:
        """K/V head count: n_kv_heads (grouped-query attention) or n_heads
        (plain multi-head). Must divide n_heads."""
        conf = self.conf
        kv = getattr(conf, "n_kv_heads", None)
        if kv is None:
            return conf.n_heads
        if kv <= 0 or conf.n_heads % kv:
            raise ValueError(f"n_kv_heads={kv} must be a positive divisor "
                             f"of n_heads={conf.n_heads}")
        return kv

    def init_params(self, key, dtype=jnp.float32):
        conf = self.conf
        dist = conf.dist.spec() if getattr(conf, "dist", None) is not None else None
        kq, kk, kv, ko = jax.random.split(key, 4)
        model = conf.n_out
        kv_dim = self._kv_heads() * (model // conf.n_heads)
        mk = lambda k, i, o: winit.init_weights(k, (i, o), conf.weight_init or "xavier",
                                                dist, dtype)
        return {
            "Wq": mk(kq, conf.n_in, model),
            "Wk": mk(kk, conf.n_in, kv_dim),
            "Wv": mk(kv, conf.n_in, kv_dim),
            "Wo": mk(ko, model, model),
            "b": jnp.full((model,), float(conf.bias_init or 0.0), dtype),
        }

    # -- recurrent-state protocol (KV cache) ----------------------------------
    def init_state(self, batch: int, dtype=jnp.float32):
        conf = self.conf
        Dh = conf.n_out // conf.n_heads
        Hkv = self._kv_heads()  # GQA: the cache shrinks with the KV heads
        L = int(getattr(conf, "max_cache_len", 1024))
        return {"k": jnp.zeros((batch, L, Hkv, Dh), dtype),
                "v": jnp.zeros((batch, L, Hkv, Dh), dtype),
                "pos": jnp.zeros((), jnp.int32)}

    def _qkv(self, params, x, pos0=0):
        """Projections as [B, T, heads, Dh]; K/V carry `n_kv_heads` heads
        (NOT yet broadcast to the query heads — the cache stores them
        compact; `_expand_kv` broadcasts at attention time)."""
        conf = self.conf
        B, T, _ = x.shape
        H = conf.n_heads
        Dh = conf.n_out // H

        def proj(w, heads):
            return jnp.einsum("btf,fo->bto", x, params[w]).reshape(
                B, T, heads, Dh)

        Hkv = self._kv_heads()
        q = proj("Wq", H)
        k = proj("Wk", Hkv)
        v = proj("Wv", Hkv)
        if getattr(conf, "rope", False):
            q = self._rope(q, pos0)
            k = self._rope(k, pos0)
        return q, k, v

    def _expand_kv(self, a):
        """Broadcast [B, T, Hkv, Dh] K/V to the n_heads query heads."""
        H = self.conf.n_heads
        Hkv = a.shape[2]
        if Hkv == H:
            return a
        return jnp.repeat(a, H // Hkv, axis=2)

    def _rope(self, a, pos0):
        """Rotary position embedding on [B, T, H, Dh] (Dh even), half-split
        pairing (GPT-NeoX "rotate-half" convention: dim i pairs with
        i + Dh/2 — NOT the paper's interleaved (0,1),(2,3) pairing; weight
        converters must match). The rotation commutes with the KV cache —
        cached keys are stored pre-rotated at their absolute position.
        ``pos0`` may be a scalar (whole batch at one depth) or a [B] vector
        (slot-based decode: each row at its own depth)."""
        B, T, H, Dh = a.shape
        if Dh % 2:
            raise ValueError(f"rope requires an even head dim, got {Dh}")
        half = Dh // 2
        freq = jnp.asarray(self.conf.rope_base, jnp.float32) ** (
            -jnp.arange(half, dtype=jnp.float32) / half)
        pos = jnp.asarray(pos0)
        t = jnp.arange(T, dtype=jnp.float32)
        if pos.ndim:  # per-row positions -> per-row angles [B, T, half]
            ang = (pos.astype(jnp.float32)[:, None]
                   + t[None, :])[:, :, None] * freq[None, None]
            cos = jnp.cos(ang)[:, :, None, :].astype(a.dtype)
            sin = jnp.sin(ang)[:, :, None, :].astype(a.dtype)
            a1, a2 = a[..., :half], a[..., half:]
            return jnp.concatenate([a1 * cos - a2 * sin,
                                    a1 * sin + a2 * cos], axis=-1)
        ang = (pos + t)[:, None] * freq[None]
        cos = jnp.cos(ang)[None, :, None, :].astype(a.dtype)
        sin = jnp.sin(ang)[None, :, None, :].astype(a.dtype)
        a1, a2 = a[..., :half], a[..., half:]
        return jnp.concatenate([a1 * cos - a2 * sin,
                                a1 * sin + a2 * cos], axis=-1)

    def _out(self, params, o, B, T):
        out = jnp.einsum("btm,mn->btn", o.reshape(B, T, self.conf.n_out),
                         params["Wo"]) + params["b"]
        return self.activation_fn()(out)

    def forward(self, params, x, *, train=False, rng=None, variables=None, mask=None):
        conf = self.conf
        x = self._dropout(x, train, rng)
        B, T, _ = x.shape
        q, k, v = self._qkv(params, x)
        if k.shape[2] != q.shape[2] and ophelpers.get_helper("attention") is None:
            # GQA on the default XLA path: grouped contraction against the
            # compact K/V — no H-expanded copies. A registered kernel
            # (flash/splash) requires matching head counts, so the repeat
            # only happens when a kernel is worth it (long context).
            o = self._grouped_attention(q, k, v, causal=conf.causal)
        else:
            o = ophelpers.attention(q, self._expand_kv(k),
                                    self._expand_kv(v), causal=conf.causal)
        if mask is not None:
            o = o * mask[:, :, None, None].astype(o.dtype)
        return self._out(params, o, B, T), variables or {}

    def _grouped_attention(self, q, k, v, *, causal, qpos0=0):
        """Dense attention with q grouped over compact KV heads — THE single
        contraction for both the full forward (qpos0=0, L==T) and the
        KV-cached decode step (qpos0=cache position, L=cache capacity).
        q: [B, T, H, Dh]; k, v: [B, L, Hkv, Dh] -> [B, T, H, Dh].
        ``qpos0`` scalar, or [B] for per-row decode depths (slot scheduling:
        each row's causal horizon is its own cache position)."""
        B, T, H, Dh = q.shape
        L, Hkv = k.shape[1], k.shape[2]
        qg = q.reshape(B, T, Hkv, H // Hkv, Dh)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) / jnp.sqrt(
            jnp.asarray(Dh, q.dtype))
        if causal:
            qp = jnp.asarray(qpos0)
            if qp.ndim:  # [B] -> valid [B, T, L] -> [B, 1, 1, T, L]
                valid = (jnp.arange(L)[None, None, :]
                         <= qp[:, None, None] + jnp.arange(T)[None, :, None])
                valid = valid[:, None, None]
            else:
                valid = (jnp.arange(L)[None, :]
                         <= qp + jnp.arange(T)[:, None])[None, None, None]
            s = jnp.where(valid, s.astype(jnp.float32),
                          jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        return jnp.einsum("bhgqk,bkhd->bqhgd", p, v).reshape(B, T, H, Dh)

    def forward_with_state(self, params, x, state0, *, train=False, rng=None,
                           mask=None):
        """Full-sequence attention when training or uncached (state passes
        through untouched); KV-cached incremental attention when an
        inference step arrives with a cache state. The step takes any T
        (T=1 decode, T=C chunked prefill) at scalar or per-row [B]
        positions; positions beyond `max_cache_len` are unsupported
        (fixed-capacity cache — chunk callers must keep pos+T <= cap,
        padding included: the overflow guard sees the PADDED length)."""
        if train or state0 is None:
            y, _ = self.forward(params, x, train=train, rng=rng, mask=mask)
            return y, state0
        if not self.conf.causal:
            raise NotImplementedError(
                "KV-cached streaming decode requires causal=True: a "
                "non-causal layer's full forward attends to FUTURE "
                "positions the cache cannot know yet (same limitation as "
                "bidirectional LSTM rnnTimeStep)")
        if "k_pages" in state0:
            return self._paged_step(params, x, state0, mask=mask)
        B, T, _ = x.shape
        pos = state0["pos"]
        L_cap = state0["k"].shape[1]
        per_slot = jnp.ndim(pos) > 0  # [B] positions: slot-based decode
        del rng  # no dropout on the inference step path
        if not isinstance(pos, jax.core.Tracer) and \
                int(jnp.max(pos) if per_slot else pos) + T > L_cap:
            raise ValueError(
                f"KV cache overflow: position "
                f"{int(jnp.max(pos) if per_slot else pos)}+{T} exceeds "
                f"max_cache_len={L_cap}; raise SelfAttentionLayer."
                f"max_cache_len or rnn_clear_previous_state()")
        # under a trace pos is abstract and cannot raise; poison the output
        # with NaN instead of silently reading a clamp-corrupted cache
        overflow = (pos + T) > L_cap
        q, k_new, v_new = self._qkv(params, x, pos0=pos)
        if per_slot:
            # per-row write offsets: vmap the slice update over the batch
            upd = jax.vmap(lambda c, n, p: jax.lax.dynamic_update_slice(
                c, n, (p, 0, 0)))
            kc = upd(state0["k"], k_new, pos)
            vc = upd(state0["v"], v_new, pos)
        else:
            kc = jax.lax.dynamic_update_slice(state0["k"], k_new,
                                              (0, pos, 0, 0))
            vc = jax.lax.dynamic_update_slice(state0["v"], v_new,
                                              (0, pos, 0, 0))
        # grouped contraction against the COMPACT cache: never materialize
        # the H-expanded K/V copies GQA exists to avoid
        o = self._grouped_attention(q, kc, vc, causal=True, qpos0=pos)
        if mask is not None:
            o = o * mask[:, :, None, None].astype(o.dtype)
        y = self._out(params, o, B, T)
        ovf = overflow[:, None, None] if per_slot else overflow
        y = jnp.where(ovf, jnp.asarray(jnp.nan, y.dtype), y)
        # freeze the state on overflow (ADVICE r3): pos sticks at the
        # L_cap+1 sentinel so every LATER step also sees overflow and keeps
        # poisoning its output — the clamp-corrupted cache can never be
        # silently extended or wrapped back into a valid-looking range.
        # Recovery is rnn_clear_previous_state(), as documented above.
        next_pos = jnp.where(overflow, jnp.asarray(L_cap + 1, jnp.int32),
                             pos + T)
        return y, {"k": kc, "v": vc, "pos": next_pos}

    def _paged_step(self, params, x, state0, *, mask=None):
        """Paged-KV inference step (inference/kvpool.py, the ISSUE 6
        layout): K/V rows live in pool-wide page arrays
        (``k_pages``/``v_pages``: [pages, block, Hkv, Dh], page 0 the
        scratch row) instead of a per-slot contiguous stripe, and each
        batch row reaches its rows through an int32 block ``table``
        ([B, nb]: logical block index -> page). The write at absolute
        position p lands in ``pages[table[b, p//block], p % block]``;
        the read gathers the row's whole table back into logical order —
        positions [0, nb*block) — and runs the SAME grouped attention as
        the contiguous step (identical math, so paged decode is
        token-identical to contiguous decode).

        ``wmask`` ([B, T] bool, optional): rows whose write must NOT
        land (decode-masked idle/mid-prefill slots, padded prefill-chunk
        lanes) are redirected to the scratch page — without this, a
        frozen slot's garbage write would corrupt a possibly SHARED
        block at its own frontier. The scheduler guarantees every block
        a *real* write touches is allocated and exclusively owned
        (copy-on-write happens host-side, before dispatch).

        ``table``/``wmask`` are injected per call by the engine and not
        returned (the table is host-authoritative; device state carries
        only pages + pos).

        T=1 decode dispatches the attention READ through the
        ``paged_decode_attention`` helper seam (ops/helpers.py): a
        registered Pallas kernel (ops/pallas_kernels.py, ISSUE 15)
        walks the block table page by page with an online softmax
        instead of materializing the gathered cache, per-shape
        autotuned with silent XLA fallback. The engine threads its
        ``paged_kernel`` mode ("auto"/"on"/"off") and tp ``mesh`` in as
        injected trace-time constants next to the table. The gather/
        einsum body below STAYS the token-identity reference and the
        fallback — prefill chunks (T > 1), unsupported shapes, and
        autotune-picks-XLA all run it; K/V WRITES (wmask scratch
        redirect, int8 quantize) always run here in XLA, the kernel
        fuses only the read."""
        B, T, _ = x.shape
        pos = state0["pos"]          # [B] int32 (per-slot decode depths)
        table = state0["table"]      # [B, nb] int32, padded with page 0
        kp, vp = state0["k_pages"], state0["v_pages"]
        Bk = kp.shape[1]
        nb = table.shape[1]
        L = nb * Bk
        wmask = state0.get("wmask")
        # int8 KV pages (engine kv_dtype="int8"): values quantize on
        # write against a per-(position, head) max-abs scale stored in
        # parallel scale pages, and dequantize on the table gather —
        # under half the pool bytes per block, same step contract
        ks, vs = state0.get("k_scales"), state0.get("v_scales")
        quantized = ks is not None
        overflow = (pos + T) > L
        q, k_new, v_new = self._qkv(params, x, pos0=pos)
        p = pos[:, None] + jnp.arange(T, dtype=pos.dtype)[None, :]  # [B, T]
        blk = jnp.take_along_axis(table, jnp.minimum(p // Bk, nb - 1),
                                  axis=1)
        if wmask is not None:
            blk = jnp.where(wmask, blk, 0)  # masked lanes -> scratch page
            # ...and ZERO their values: a masked row deeper than this
            # step's table bucket is output-poisoned (overflow NaN), and
            # the next layer's K/V projection of that NaN would land in
            # the scratch page — where `softmax_prob(0) * NaN = NaN`
            # leaks through every later reader's attention einsum even
            # on causally-masked lanes. Pages must only ever hold
            # finite rows.
            keep = wmask[..., None, None]
            k_new = jnp.where(keep, k_new, 0)
            v_new = jnp.where(keep, v_new, 0)
        blk = jnp.where(p // Bk < nb, blk, 0)  # beyond-table -> scratch
        off = p % Bk
        ks2 = vs2 = None
        if quantized:
            kq, ksc = quantize_kv_rows(k_new)   # ops/kvquant.py — the
            vq, vsc = quantize_kv_rows(v_new)   # shared int8 contract
            kp2 = kp.at[blk, off].set(kq)
            vp2 = vp.at[blk, off].set(vq)
            ks2 = ks.at[blk, off].set(ksc)
            vs2 = vs.at[blk, off].set(vsc)
        else:
            kp2 = kp.at[blk, off].set(k_new)
            vp2 = vp.at[blk, off].set(v_new)
        o = None
        if T == 1:
            # fused page-walk decode kernel, or None = run the XLA
            # reference below (trace-time decision — see class docstring)
            o = ophelpers.paged_decode_attention(
                q, kp2, vp2, table, pos, k_scales=ks2, v_scales=vs2,
                mode=state0.get("paged_kernel", "auto"),
                mesh=state0.get("mesh"))
        if o is None:
            dt = q.dtype
            if quantized:
                kc = dequantize_kv_rows(kp2[table], ks2[table],
                                        dt).reshape(
                    B, L, kp.shape[2], kp.shape[3])
                vc = dequantize_kv_rows(vp2[table], vs2[table],
                                        dt).reshape(
                    B, L, vp.shape[2], vp.shape[3])
            else:
                kc = kp2[table].reshape(B, L, kp.shape[2], kp.shape[3])
                vc = vp2[table].reshape(B, L, vp.shape[2], vp.shape[3])
            o = self._grouped_attention(q, kc, vc, causal=True, qpos0=pos)
        if mask is not None:
            o = o * mask[:, :, None, None].astype(o.dtype)
        y = self._out(params, o, B, T)
        y = jnp.where(overflow[:, None, None],
                      jnp.asarray(jnp.nan, y.dtype), y)
        # the overflow sentinel must out-range EVERY table bucket the
        # scheduler may present later (bucket widths vary per step), so
        # it is an absolute huge position, not this bucket's cap+1
        next_pos = jnp.where(overflow, jnp.asarray(1 << 30, jnp.int32),
                             pos + T)
        out_state = {"k_pages": kp2, "v_pages": vp2, "pos": next_pos}
        if quantized:
            out_state["k_scales"] = ks2
            out_state["v_scales"] = vs2
        return y, out_state
