"""Weight initialization schemes.

Capability parity with the reference's `nn/weights/WeightInit` enum +
`WeightInitUtil` (deeplearning4j-core/.../nn/weights/WeightInitUtil.java), which
draws from ND4J RNG distributions. Here every draw takes an explicit threefry
key (TPU-first: deterministic, reproducible across device meshes — unlike
ND4J's global RNG, see SURVEY.md §7 'RNG parity').

Schemes: ZERO, SIZE, UNIFORM, NORMALIZED, VI, XAVIER, RELU, DISTRIBUTION.
fan_in/fan_out follow the reference convention: for a [n_in, n_out] weight
matrix fan_in = n_in, fan_out = n_out; for conv kernels [kh, kw, c_in, c_out]
fan_in = kh*kw*c_in, fan_out = kh*kw*c_out.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

ZERO = "zero"
ONES = "ones"
SIZE = "size"
UNIFORM = "uniform"
NORMALIZED = "normalized"
VI = "vi"
XAVIER = "xavier"
XAVIER_UNIFORM = "xavier_uniform"
RELU = "relu"
RELU_UNIFORM = "relu_uniform"
LECUN = "lecun"
DISTRIBUTION = "distribution"

ALL = (ZERO, ONES, SIZE, UNIFORM, NORMALIZED, VI, XAVIER, XAVIER_UNIFORM, RELU,
       RELU_UNIFORM, LECUN, DISTRIBUTION)


def _fans(shape: Sequence[int]) -> Tuple[float, float]:
    if len(shape) == 1:
        return float(shape[0]), float(shape[0])
    if len(shape) == 2:
        return float(shape[0]), float(shape[1])
    receptive = 1.0
    for d in shape[:-2]:
        receptive *= d
    return receptive * shape[-2], receptive * shape[-1]


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    scheme: str = XAVIER,
    distribution: Optional[dict] = None,
    dtype: jnp.dtype = jnp.float32,
) -> Array:
    """Draw a weight tensor. `distribution` is a serialized Distribution config
    (see nn/conf/distributions.py) used when scheme == DISTRIBUTION."""
    scheme = scheme.lower()
    shape = tuple(int(s) for s in shape)
    fan_in, fan_out = _fans(shape)

    if scheme == ZERO:
        return jnp.zeros(shape, dtype)
    if scheme == ONES:
        return jnp.ones(shape, dtype)
    if scheme == SIZE:
        # uniform in [-1/sqrt(fan_in+fan_out), 1/sqrt(fan_in+fan_out)]
        b = 1.0 / jnp.sqrt(fan_in + fan_out)
        return jax.random.uniform(key, shape, dtype, -b, b)
    if scheme == UNIFORM:
        a = 1.0 / jnp.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == NORMALIZED:
        # reference: uniform shifted by -0.5, scaled by 1/fan_in region
        u = jax.random.uniform(key, shape, dtype)
        return (u - 0.5) / fan_in
    if scheme == VI:
        # variance-init: uniform scaled by sqrt(6/(fan_in+fan_out)) region
        r = jnp.sqrt(6.0 / (fan_in + fan_out))
        u = jax.random.uniform(key, shape, dtype)
        return u * 2.0 * r - r
    if scheme == XAVIER:
        std = jnp.sqrt(2.0 / (fan_in + fan_out))
        return jax.random.normal(key, shape, dtype) * std
    if scheme == XAVIER_UNIFORM:
        r = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == RELU:
        std = jnp.sqrt(2.0 / fan_in)
        return jax.random.normal(key, shape, dtype) * std
    if scheme == RELU_UNIFORM:
        r = jnp.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -r, r)
    if scheme == LECUN:
        std = jnp.sqrt(1.0 / fan_in)
        return jax.random.normal(key, shape, dtype) * std
    if scheme == DISTRIBUTION:
        return _sample_distribution(key, shape, distribution or {}, dtype)
    raise ValueError(f"Unknown weight init scheme '{scheme}'. Available: {ALL}")


def _sample_distribution(key: jax.Array, shape, dist: dict, dtype) -> Array:
    kind = dist.get("type", "normal").lower()
    if kind in ("normal", "gaussian"):
        mean = dist.get("mean", 0.0)
        std = dist.get("std", 1.0)
        return jax.random.normal(key, shape, dtype) * std + mean
    if kind == "uniform":
        lower = dist.get("lower", -1.0)
        upper = dist.get("upper", 1.0)
        return jax.random.uniform(key, shape, dtype, lower, upper)
    if kind == "binomial":
        n = dist.get("n", 1)
        p = dist.get("p", 0.5)
        draws = jax.random.bernoulli(key, p, (n,) + tuple(shape))
        return jnp.sum(draws, axis=0).astype(dtype)
    raise ValueError(f"Unknown distribution '{kind}'")
