"""ComputationGraph: DAG network runtime.

Parity with the reference deeplearning4j-core/.../nn/graph/ComputationGraph.java
(1,863 LoC): topologicalOrder:91, init/params-view :235-325,
fit(DataSetIterator):565, fit(MultiDataSetIterator):627, backprop:960,
rnnTimeStep:1460; vertex impls under nn/graph/vertex/impl/* (Input/Layer/
ElementWise/Merge/Subset/Preprocessor + rnn LastTimeStep/DuplicateToTimeSeries).

TPU-first: like MultiLayerNetwork, the whole fit step — topo-ordered forward
over the DAG, multi-output loss, jax.grad backward, updaters — is ONE
jit-compiled pure function; vertices are pure ops, the backward pass through
merge/elementwise/subset vertices is autodiff.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .conf.graph import (ComputationGraphConfiguration,
                         DuplicateToTimeSeriesVertex, ElementWiseVertex,
                         GraphVertex, LastTimeStepVertex, LayerVertex,
                         MergeVertex, PreprocessorVertex, ScaleVertex,
                         SubsetVertex)
from .conf.layers import OutputLayer, RnnOutputLayer, LossLayer
from .layers.base import LayerImpl, impl_for, remat_forward
from .layers.recurrent import (BaseRecurrentImpl,
                               _materialize_rnn_states)
from .conf.config import BACKPROP_TBPTT
from .multilayer import _cast_floats, _compute_dtype_of, _dtype_of
from .updater.gradnorm import apply_gradient_normalization
from .updater.schedules import effective_lr
from ..ops import losses as losses_mod

Array = jax.Array


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.topo = conf.topological_order()
        self._impls: Dict[str, LayerImpl] = {}
        for name, v in conf.vertices.items():
            if isinstance(v, LayerVertex):
                self._impls[name] = impl_for(v.layer)
        self.params: Dict[str, Dict[str, Array]] = {}
        self.variables: Dict[str, Dict[str, Array]] = {}
        self.updater_state: Dict[str, Dict[str, Dict[str, Array]]] = {}
        self.step = 0
        self._score_raw: Any = float("nan")
        # minibatches fused per device dispatch in fit(iterator) — one
        # jitted lax.scan over stacked batches (see fit_scan)
        self.scan_batches = 16
        self.listeners: List[Any] = []
        self._rnn_state: Dict[str, Any] = {}
        self._jit_cache: Dict[Any, Any] = {}
        self._key = jax.random.PRNGKey(conf.conf.seed)
        self._initialized = False

    # score_ materializes lazily so training never blocks on a device->host
    # loss fetch (same contract as MultiLayerNetwork.score_)
    @property
    def score_(self) -> float:
        v = self._score_raw
        if not isinstance(v, float):
            v = float(v)
            self._score_raw = v
        return v

    @score_.setter
    def score_(self, v):
        self._score_raw = v

    # -- init ------------------------------------------------------------------
    def init(self) -> "ComputationGraph":
        dtype = _dtype_of(self.conf.conf)
        key = jax.random.PRNGKey(self.conf.conf.seed)
        names = sorted(self._impls)
        keys = jax.random.split(key, max(len(names), 1))
        for i, name in enumerate(names):
            impl = self._impls[name]
            self.params[name] = impl.init_params(keys[i], dtype)
            self.variables[name] = impl.init_variables(dtype)
            layer_conf = self.conf.vertices[name].layer
            self.updater_state[name] = {
                pname: layer_conf.updater.init_state(p)
                for pname, p in self.params[name].items()}
        self._initialized = True
        return self

    def _check_init(self):
        if not self._initialized:
            self.init()

    # -- vertex forward --------------------------------------------------------
    def _vertex_forward(self, name: str, vertex: GraphVertex,
                        inputs: List[Array], params, variables, *,
                        train, rng, mask, vmasks, states, new_states,
                        in_scan: bool = False, preouts=None):
        if isinstance(vertex, LayerVertex):
            x = inputs[0]
            if vertex.preprocessor is not None:
                x = vertex.preprocessor.preprocess(x)
            impl = self._impls[name]
            ckpt = train and getattr(self.conf.conf, "remat", False)
            if isinstance(impl, BaseRecurrentImpl):
                state0 = (states or {}).get(name)
                y, st = remat_forward(impl, train=train, ckpt=ckpt,
                                      recurrent=True, in_scan=in_scan)(
                    params[name], x, state0, rng, mask)
                new_states[name] = st
                return y, variables.get(name, {})
            if preouts is not None and hasattr(impl, "forward_with_preout"):
                # output vertex on the loss path: surface the pre-activation
                # for the stable from-logits losses (no remat — the loss
                # consumes it immediately)
                y, z, nv = impl.forward_with_preout(
                    params[name], x, train=train, rng=rng,
                    variables=variables.get(name, {}), mask=mask)
                preouts[name] = z
                return y, nv
            y, nv = remat_forward(impl, train=train, ckpt=ckpt,
                                  recurrent=False, in_scan=in_scan)(
                params[name], x, variables.get(name, {}), rng, mask)
            return y, nv
        if isinstance(vertex, MergeVertex):
            return jnp.concatenate(inputs, axis=-1), None
        if isinstance(vertex, ElementWiseVertex):
            op = vertex.op.lower()
            out = inputs[0]
            if op == "add":
                for a in inputs[1:]:
                    out = out + a
            elif op == "subtract":
                for a in inputs[1:]:
                    out = out - a
            elif op in ("product", "multiply"):
                for a in inputs[1:]:
                    out = out * a
            elif op in ("average", "avg"):
                out = sum(inputs) / float(len(inputs))
            elif op == "max":
                for a in inputs[1:]:
                    out = jnp.maximum(out, a)
            else:
                raise ValueError(f"Unknown elementwise op '{vertex.op}'")
            return out, None
        if isinstance(vertex, SubsetVertex):
            return inputs[0][..., vertex.from_idx:vertex.to_idx + 1], None
        if isinstance(vertex, PreprocessorVertex):
            return vertex.preprocessor.preprocess(inputs[0]), None
        if isinstance(vertex, ScaleVertex):
            return inputs[0] * vertex.scale_factor, None
        if isinstance(vertex, LastTimeStepVertex):
            x = inputs[0]
            mask = vmasks.get(vertex.mask_input)
            if mask is None:
                return x[:, -1, :], None
            idx = jnp.maximum(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx, :], None
        if isinstance(vertex, DuplicateToTimeSeriesVertex):
            x = inputs[0]
            ref = vertex.reference_input
            t = self._current_timesteps.get(ref)
            if t is None:
                raise ValueError(f"DuplicateToTimeSeries: unknown reference input {ref}")
            return jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[-1])), None
        raise ValueError(f"Unknown vertex type {type(vertex).__name__}")

    def _forward_impl(self, params, variables, inputs: Sequence[Array], *,
                      train, rng, fmasks=None, states=None,
                      in_scan: bool = False, want_preout: bool = False):
        """Topo-ordered DAG forward. Returns (dict name->activation,
        new variables, new rnn states) — plus a dict of output-vertex
        pre-activations as a 4th element when ``want_preout`` (loss path)."""
        conf = self.conf
        dtype = _compute_dtype_of(conf.conf)
        if dtype != _dtype_of(conf.conf):
            # mixed precision: see multilayer._forward_impl
            params = _cast_floats(params, dtype)
        acts: Dict[str, Array] = {}
        # per-vertex feature-mask propagation (reference tracks masks through
        # vertices via setLayerMaskArrays/feedForward(...,fMask,...)); a vertex
        # inherits the first non-None mask of its inputs while the time axis
        # survives, and drops it once time is collapsed (pooling/last-step).
        vmasks: Dict[str, Optional[Array]] = {}
        self._current_timesteps = {}
        for i, iname in enumerate(conf.network_inputs):
            x = inputs[i]
            if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dtype:
                x = x.astype(dtype)
            acts[iname] = x
            vmasks[iname] = (fmasks or {}).get(iname)
            if x.ndim == 3:
                self._current_timesteps[iname] = x.shape[1]
        new_vars = dict(variables)
        new_states: Dict[str, Any] = {}
        n_layer = max(len(self._impls), 1)
        rngs = (list(jax.random.split(rng, n_layer)) if rng is not None
                else [None] * n_layer)
        layer_rng = {name: rngs[i] for i, name in enumerate(sorted(self._impls))}
        preouts: Dict[str, Array] = {}
        out_names = set(conf.network_outputs) if want_preout else set()
        for name in self.topo:
            vertex = conf.vertices[name]
            srcs = conf.vertex_inputs[name]
            vin = [acts[src] for src in srcs]
            src_masks = [m for m in (vmasks.get(s) for s in srcs)
                         if m is not None]
            in_mask = src_masks[0] if src_masks else None
            for m in src_masks[1:]:  # multi-input: AND the masks together
                in_mask = jnp.minimum(in_mask, m)
            y, nv = self._vertex_forward(
                name, vertex, vin, params, variables,
                train=train, rng=layer_rng.get(name), mask=in_mask,
                vmasks=vmasks, states=states, new_states=new_states,
                in_scan=in_scan,
                preouts=preouts if name in out_names else None)
            if nv is not None:
                new_vars[name] = nv
            if (getattr(y, "ndim", None) is not None
                    and jnp.issubdtype(y.dtype, jnp.floating)
                    and y.dtype != dtype):
                y = y.astype(dtype)  # stop f32 creep under mixed precision
            acts[name] = y
            if isinstance(vertex, DuplicateToTimeSeriesVertex):
                vmasks[name] = vmasks.get(vertex.reference_input)
            else:
                vmasks[name] = in_mask if getattr(y, "ndim", 0) == 3 else None
            if y.ndim == 3:
                self._current_timesteps[name] = y.shape[1]
        if want_preout:
            return acts, new_vars, new_states, preouts
        return acts, new_vars, new_states

    # -- loss ------------------------------------------------------------------
    def _loss(self, acts: Dict[str, Array], labels: Sequence[Array],
              lmasks: Optional[Sequence[Optional[Array]]] = None,
              preouts: Optional[Dict[str, Array]] = None):
        total = jnp.asarray(0.0, jnp.float32)
        for i, out_name in enumerate(self.conf.network_outputs):
            layer_conf = self.conf.vertices[out_name].layer \
                if isinstance(self.conf.vertices[out_name], LayerVertex) else None
            loss_name = getattr(layer_conf, "loss", None) or "mse"
            fused = losses_mod.fused_from_logits(
                getattr(layer_conf, "activation", None), loss_name)
            if fused is not None and preouts and out_name in preouts:
                loss_fn, out = fused, preouts[out_name]
            else:
                loss_fn = losses_mod.get(loss_name)
                out = acts[out_name]
            y = labels[i]
            m = lmasks[i] if lmasks else None
            if out.ndim == 3:
                o = out.reshape(-1, out.shape[-1])
                t = y.reshape(-1, y.shape[-1])
                mm = m.reshape(-1) if m is not None else None
                total = total + loss_fn(t, o, mm).astype(jnp.float32)
            else:
                total = total + loss_fn(y, out,
                                        m.reshape(-1) if m is not None else None
                                        ).astype(jnp.float32)
        return total

    def _reg_loss(self, params):
        total = jnp.asarray(0.0, jnp.float32)
        for name, impl in self._impls.items():
            total = total + impl.reg_loss(params[name]).astype(jnp.float32)
        return total

    # -- train step ------------------------------------------------------------
    def _apply_updaters(self, params, grads, ustates, step):
        gconf = self.conf.conf
        new_params, new_ustates = {}, {}
        for name in params:
            layer_conf = self.conf.vertices[name].layer
            lgrads = grads[name]
            if not lgrads:
                new_params[name] = params[name]
                new_ustates[name] = ustates[name]
                continue
            lgrads = apply_gradient_normalization(
                lgrads, layer_conf.gradient_normalization or "none",
                layer_conf.gradient_normalization_threshold or 1.0)
            updater = layer_conf.updater
            base_lr = getattr(updater, "learning_rate", -1.0)
            if base_lr is None or base_lr < 0:
                base_lr = layer_conf.learning_rate
            bias_lr = layer_conf.bias_learning_rate or base_lr
            wd = float(getattr(updater, "weight_decay", 0.0) or 0.0)
            wkeys = self._impls[name].WEIGHT_KEYS
            lp, lu = {}, {}
            for pname, g in lgrads.items():
                lr0 = bias_lr if pname in ("b", "vb", "beta") else base_lr
                lr = effective_lr(lr0, step, gconf.lr_policy,
                                  gconf.lr_policy_decay_rate, gconf.lr_policy_power,
                                  gconf.lr_policy_steps, gconf.max_num_iterations,
                                  gconf.lr_schedule).astype(g.dtype)
                delta, ns = updater.apply(ustates[name][pname], g, lr, step)
                p = params[name][pname]
                if wd and pname in wkeys:  # decoupled (AdamW-style) decay
                    delta = delta - lr * jnp.asarray(wd, p.dtype) * p
                lp[pname] = p + delta
                lu[pname] = ns
            new_params[name] = lp
            new_ustates[name] = lu
        return new_params, new_ustates

    def _build_loss_fn(self, in_scan: bool = False):
        """The pure training loss with aux (new variables) — shared by the
        train step and the gradient-accumulation step."""
        def loss_fn(params, variables, inputs, labels, fmasks, lmasks, rng):
            acts, new_vars, _, preouts = self._forward_impl(
                params, variables, inputs, train=True, rng=rng, fmasks=fmasks,
                in_scan=in_scan, want_preout=True)
            loss = (self._loss(acts, labels, lmasks, preouts=preouts)
                    + self._reg_loss(params))
            return loss, new_vars
        return loss_fn

    def _build_train_step(self, in_scan: bool = False):
        """Raw (unjitted) pure train step — reused by the distributed
        trainers (parallel/) inside shard_map, mirroring
        MultiLayerNetwork._build_train_step. (jit retraces per input pytree
        structure, so no shape key is needed here; _get_train_step's key is
        purely a cache discriminator.) ``in_scan`` marks steps traced inside
        a lax.scan body (remat drops its CSE barriers there)."""
        loss_fn = self._build_loss_fn(in_scan)

        def train_step(params, variables, ustates, step, rng, inputs, labels,
                       fmasks, lmasks):
            (loss, new_vars), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, variables, inputs, labels, fmasks, lmasks, rng)
            new_params, new_ustates = self._apply_updaters(params, grads, ustates, step)
            return new_params, new_vars, new_ustates, loss

        return train_step

    def _build_train_step_stateful(self):
        """Train step that carries RNN vertex states across calls — the
        TBPTT window step (reference ComputationGraph.backprop(tbptt=true)
        :960 + rnnUpdateStateWithTBPTTState)."""

        def loss_fn(params, variables, inputs, labels, fmasks, lmasks, rng,
                    states):
            acts, new_vars, new_states, preouts = self._forward_impl(
                params, variables, inputs, train=True, rng=rng,
                fmasks=fmasks, states=states, want_preout=True)
            loss = (self._loss(acts, labels, lmasks, preouts=preouts)
                    + self._reg_loss(params))
            return loss, (new_vars, new_states)

        def train_step(params, variables, ustates, step, rng, inputs, labels,
                       fmasks, lmasks, states):
            ((loss, (new_vars, new_states)), grads) = jax.value_and_grad(
                loss_fn, has_aux=True)(params, variables, inputs, labels,
                                       fmasks, lmasks, rng, states)
            new_params, new_ustates = self._apply_updaters(params, grads,
                                                           ustates, step)
            return new_params, new_vars, new_ustates, loss, new_states

        return train_step

    def _get_train_step(self, key):
        if key in self._jit_cache:
            return self._jit_cache[key]
        fn = jax.jit(self._build_train_step(), donate_argnums=(0, 2))
        self._jit_cache[key] = fn
        return fn

    # ------------------------------------------- gradient accumulation ------
    def _build_accum_step(self):
        """ONE optimizer update from K accumulated microbatch gradients
        (mirrors MultiLayerNetwork._build_accum_step; unmasked inputs)."""
        loss_fn = self._build_loss_fn(in_scan=True)

        def accum_step(params, variables, ustates, step, rng, xs_t, ys_t):
            k = xs_t[0].shape[0]
            gzero = jax.tree_util.tree_map(jnp.zeros_like, params)

            def body(carry, inp):
                gsum, variables = carry
                xs_i, ys_i, i = inp
                sub = jax.random.fold_in(rng, i)
                (loss, new_vars), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, variables, list(xs_i),
                                           list(ys_i), None, None, sub)
                gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
                return (gsum, new_vars), loss

            (gsum, new_vars), losses = jax.lax.scan(
                body, (gzero, variables), (xs_t, ys_t, jnp.arange(k)))
            grads = jax.tree_util.tree_map(lambda g: g / k, gsum)
            new_params, new_ustates = self._apply_updaters(
                params, grads, ustates, step)
            return new_params, new_vars, new_ustates, losses

        return accum_step

    def fit_batch_accumulated(self, inputs, labels, accumulation_steps: int):
        """One optimizer step from `accumulation_steps` accumulated
        microbatch gradients (see MultiLayerNetwork.fit_batch_accumulated;
        the batch axis of every input/label must divide evenly; unmasked).
        Returns the device-resident mean microbatch loss."""
        self._check_init()
        algo = (self.conf.conf.optimization_algo or
                "stochastic_gradient_descent").lower()
        if (algo not in ("stochastic_gradient_descent", "sgd")
                or self.conf.conf.iterations > 1):
            raise ValueError(
                "fit_batch_accumulated supports SGD-family training with "
                f"iterations=1 (got algo={algo!r}, "
                f"iterations={self.conf.conf.iterations})")
        k = int(accumulation_steps)
        if k <= 0:
            raise ValueError(f"accumulation_steps must be >= 1 (got {k})")
        ins = [jnp.asarray(a) for a in (inputs if isinstance(inputs, (list, tuple))
                                        else [inputs])]
        outs = [jnp.asarray(a) for a in (labels if isinstance(labels, (list, tuple))
                                         else [labels])]
        for a in ins + outs:
            if a.shape[0] % k:
                raise ValueError(f"batch {a.shape[0]} not divisible by "
                                 f"accumulation_steps {k}")

        def split(a):
            return a.reshape((k, a.shape[0] // k) + tuple(a.shape[1:]))

        ck = ("accum", len(ins), len(outs))
        if ck not in self._jit_cache:
            self._jit_cache[ck] = jax.jit(self._build_accum_step(),
                                          donate_argnums=(0, 2))
        self._key, sub = jax.random.split(self._key)
        (self.params, self.variables, self.updater_state,
         losses) = self._jit_cache[ck](
            self.params, self.variables, self.updater_state,
            jnp.asarray(self.step), sub,
            tuple(split(a) for a in ins), tuple(split(a) for a in outs))
        self.step += 1
        mean_loss = jnp.mean(losses)
        self.score_ = mean_loss
        for listener in self.listeners:
            listener.iteration_done(self, self.step)
        return mean_loss

    # -- fit -------------------------------------------------------------------
    def fit(self, data, labels=None):
        """fit(MultiDataSet | DataSet | iterator | (inputs, labels))."""
        self._check_init()
        if labels is not None:
            ins = data if isinstance(data, (list, tuple)) else [data]
            labs = labels if isinstance(labels, (list, tuple)) else [labels]
            self._fit_one(ins, labs, None, None)
            return self
        if hasattr(data, "features"):
            self._fit_single_ds(data)
            return self
        self._fit_iterator(data)
        return self

    def _can_scan(self) -> bool:
        algo = (self.conf.conf.optimization_algo or
                "stochastic_gradient_descent").lower()
        return (self.scan_batches > 1 and self.conf.conf.iterations <= 1
                and algo in ("stochastic_gradient_descent", "sgd"))

    def _fit_iterator(self, iterator):
        """Fuse runs of same-shape unmasked (Multi)DataSets into one
        device-resident lax.scan dispatch — the DAG analog of
        MultiLayerNetwork._fit_iterator."""
        if (not self._can_scan()
                or self.conf.backprop_type == BACKPROP_TBPTT):
            for ds in iterator:
                self._fit_single_ds(ds)
            return

        def norm(ds):
            if hasattr(ds, "features_masks"):
                return (list(ds.features), list(ds.labels),
                        ds.features_masks, ds.labels_masks)
            fm = getattr(ds, "features_mask", None)
            lm = getattr(ds, "labels_mask", None)
            return ([ds.features], [ds.labels],
                    [fm] if fm is not None else None,
                    [lm] if lm is not None else None)

        buf = []
        buf_shapes = None

        def flush():
            nonlocal buf
            if not buf:
                return
            if len(buf) < self.scan_batches:
                for ins, labs, _, _ in buf:
                    self._fit_one(ins, labs, None, None)
            else:
                xs = [np.stack([np.asarray(t[0][k]) for t in buf])
                      for k in range(len(buf[0][0]))]
                ys = [np.stack([np.asarray(t[1][k]) for t in buf])
                      for k in range(len(buf[0][1]))]
                self.fit_scan(xs, ys)
            buf = []

        for ds in iterator:
            ins, labs, fms, lms = norm(ds)
            if fms is not None or lms is not None:
                flush()
                self._fit_one(ins, labs, fms, lms)
                continue
            shapes = (tuple(np.asarray(a).shape for a in ins),
                      tuple(np.asarray(a).shape for a in labs))
            if buf and shapes != buf_shapes:
                flush()
            buf_shapes = shapes
            buf.append((ins, labs, fms, lms))
            if len(buf) >= self.scan_batches:
                flush()
        flush()

    def fit_scan(self, xs_list, ys_list):
        """Run K training steps device-resident: one jitted lax.scan over
        stacked minibatches. xs_list/ys_list: lists (per network input /
        output) of [K, B, ...] arrays. Masks are not supported on this path
        (fit(iterator) routes masked batches through the one-step path)."""
        self._check_init()
        if not self._can_scan():
            raise ValueError("fit_scan requires SGD-class training "
                             "(iterations=1, scan_batches>1)")
        if (self.conf.backprop_type == BACKPROP_TBPTT
                and any(getattr(a, "ndim", 0) == 4
                        and a.shape[2] > self.conf.tbptt_fwd_length
                        for a in xs_list)):
            raise ValueError(
                "fit_scan does not window TBPTT sequences longer than "
                f"tbptt_fwd_length={self.conf.tbptt_fwd_length}; "
                "pass single windows or use fit()")
        xs_list = [jnp.asarray(a) for a in xs_list]
        ys_list = [jnp.asarray(a) for a in ys_list]
        cache_key = ("multi", len(xs_list), len(ys_list))
        if cache_key not in self._jit_cache:
            base = self._build_train_step(in_scan=True)

            def multi(params, variables, ustates, step0, rng, xs, ys):
                def body(carry, inp):
                    params, variables, ustates, step = carry
                    bx, by = inp
                    sub = jax.random.fold_in(rng, step)
                    p, v, u, loss = base(params, variables, ustates, step,
                                         sub, list(bx), list(by), None, None)
                    return (p, v, u, step + 1), loss

                (params, variables, ustates, _), losses = jax.lax.scan(
                    body, (params, variables, ustates, step0),
                    (tuple(xs), tuple(ys)))
                return params, variables, ustates, losses

            self._jit_cache[cache_key] = jax.jit(multi,
                                                 donate_argnums=(0, 1, 2))
        fn = self._jit_cache[cache_key]
        self._key, sub = jax.random.split(self._key)
        k = int(xs_list[0].shape[0])
        (self.params, self.variables, self.updater_state, losses) = fn(
            self.params, self.variables, self.updater_state,
            jnp.asarray(self.step), sub, tuple(xs_list), tuple(ys_list))
        self.step += k
        self._score_raw = losses[-1]
        if self.listeners:
            host_losses = np.asarray(losses)
            for j in range(k):
                self._score_raw = float(host_losses[j])
                for listener in self.listeners:
                    listener.iteration_done(self, self.step - k + 1 + j)
        return losses

    def _fit_single_ds(self, ds):
        if hasattr(ds, "features_masks"):  # MultiDataSet
            self._fit_one(ds.features, ds.labels, ds.features_masks, ds.labels_masks)
        else:
            fm = getattr(ds, "features_mask", None)
            lm = getattr(ds, "labels_mask", None)
            self._fit_one([ds.features], [ds.labels],
                          [fm] if fm is not None else None,
                          [lm] if lm is not None else None)

    def _fit_one(self, inputs, labels, fmasks, lmasks):
        inputs = [jnp.asarray(a) for a in inputs]
        labels = [jnp.asarray(a) for a in labels]
        fmasks_d = (dict(zip(self.conf.network_inputs,
                             [jnp.asarray(m) if m is not None else None
                              for m in fmasks])) if fmasks else None)
        lmasks_l = ([jnp.asarray(m) if m is not None else None for m in lmasks]
                    if lmasks else None)
        algo = (self.conf.conf.optimization_algo or
                "stochastic_gradient_descent").lower()
        if (self.conf.backprop_type == BACKPROP_TBPTT
                and any(a.ndim == 3 for a in inputs)):
            if algo not in ("stochastic_gradient_descent", "sgd"):
                raise NotImplementedError(
                    f"optimization_algo={algo!r} is not supported with "
                    "truncated BPTT; use stochastic_gradient_descent")
            return self._do_truncated_bptt(inputs, labels, fmasks_d, lmasks_l)
        if algo not in ("stochastic_gradient_descent", "sgd"):
            return self._fit_one_solver(algo, inputs, labels, fmasks_d, lmasks_l)
        step_fn = self._get_train_step((len(inputs), len(labels),
                                        fmasks is not None, lmasks is not None))
        for _ in range(max(1, self.conf.conf.iterations)):
            self._key, sub = jax.random.split(self._key)
            (self.params, self.variables, self.updater_state,
             loss) = step_fn(self.params, self.variables, self.updater_state,
                             jnp.asarray(self.step), sub, inputs, labels,
                             fmasks_d, lmasks_l)
            self._score_raw = loss  # lazy: no blocking device->host fetch
            self.step += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.step)

    def _do_truncated_bptt(self, inputs, labels, fmasks_d, lmasks_l):
        """Sliding-window TBPTT over the DAG with carried RNN vertex state
        (reference ComputationGraph.doTruncatedBPTT + backprop(tbptt):960).
        2-D inputs/labels (static features / per-sequence targets) pass
        through unwindowed; 3-D arrays window along time."""
        T = max(a.shape[1] for a in inputs if a.ndim == 3)
        L = self.conf.tbptt_fwd_length
        batch = inputs[0].shape[0]
        # state dtype = the network compute dtype (NOT input[0].dtype:
        # the first input may be integer embedding indices)
        states = _materialize_rnn_states(
            self._impls.items(), {}, batch,
            _compute_dtype_of(self.conf.conf), tbptt=True)
        key = ("tbptt_step",)
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(self._build_train_step_stateful(),
                                           donate_argnums=(0, 2))
        step_fn = self._jit_cache[key]

        def win(a, start, end):
            return a[:, start:end] if getattr(a, "ndim", 0) == 3 else a

        def win_mask(m, start, end, is_sequence):
            """Window a mask ONLY when its corresponding array is a time
            series — a [B, 1] mask on a static input must pass through."""
            if m is None or not is_sequence:
                return m
            return m[:, start:end] if m.ndim >= 2 else m

        seq_input = {name: inputs[i].ndim == 3
                     for i, name in enumerate(self.conf.network_inputs)}
        seq_label = [y.ndim == 3 for y in labels]
        start = 0
        while start < T:
            end = min(start + L, T)
            ins = [win(a, start, end) for a in inputs]
            labs = [win(y, start, end) for y in labels]
            fms = ({k: win_mask(m, start, end, seq_input.get(k, False))
                    for k, m in fmasks_d.items()} if fmasks_d else None)
            lms = ([win_mask(m, start, end, seq_label[i])
                    for i, m in enumerate(lmasks_l)]
                   if lmasks_l else None)
            self._key, sub = jax.random.split(self._key)
            (self.params, self.variables, self.updater_state, loss,
             states) = step_fn(self.params, self.variables,
                               self.updater_state, jnp.asarray(self.step),
                               sub, ins, labs, fms, lms, states)
            states = jax.tree_util.tree_map(jax.lax.stop_gradient, states)
            self._score_raw = loss
            self.step += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.step)
            start = end

    def _fit_one_solver(self, algo, inputs, labels, fmasks_d, lmasks_l):
        """Whole-graph training under CG / LBFGS / line-search — reference
        BaseOptimizer.java:51 driving ComputationGraph.computeGradientAndScore."""
        from jax.flatten_util import ravel_pytree
        from ..optimize.solver import OPTIMIZERS
        cls = OPTIMIZERS.get(algo)
        if cls is None:
            raise ValueError(f"Unknown optimization_algo {algo!r}; "
                             f"available: {sorted(OPTIMIZERS)}")
        flat0, unravel = ravel_pytree(self.params)
        self._key, rng = jax.random.split(self._key)

        def objective(flat):
            params = unravel(flat)
            acts, _, _, preouts = self._forward_impl(
                params, self.variables, inputs, train=True, rng=rng,
                fmasks=fmasks_d, want_preout=True)
            loss = (self._loss(acts, labels, lmasks_l, preouts=preouts)
                    + self._reg_loss(params))
            return loss.astype(jnp.float32)

        lrs = [v.layer.learning_rate for v in self.conf.vertices.values()
               if getattr(v, "layer", None) is not None]
        lr = lrs[0] if lrs else 0.1
        opt = cls(objective, max_iterations=max(1, self.conf.conf.iterations),
                  learning_rate=lr)
        flat = opt.optimize(flat0)
        self.params = unravel(jnp.asarray(flat, flat0.dtype))
        self.score_ = opt.score_
        self.step += 1
        for listener in self.listeners:
            listener.iteration_done(self, self.step)

    # -- inference -------------------------------------------------------------
    def _get_forward(self, n_inputs: int):
        # jit re-traces per fmask-presence pytree structure automatically
        key = ("fwd", n_inputs)
        if key not in self._jit_cache:
            def fwd(params, variables, inputs, fmasks_list):
                fmask_dict = (dict(zip(self.conf.network_inputs, fmasks_list))
                              if fmasks_list is not None else None)
                acts, _, _ = self._forward_impl(params, variables, inputs,
                                                train=False, rng=None,
                                                fmasks=fmask_dict)
                return [acts[name] for name in self.conf.network_outputs]
            self._jit_cache[key] = jax.jit(fwd)
        return self._jit_cache[key]

    def output(self, *inputs, train: bool = False, fmasks=None) -> List[Array]:
        self._check_init()
        ins = [jnp.asarray(a) for a in inputs]
        fl = ([jnp.asarray(m) if m is not None else None for m in fmasks]
              if fmasks is not None else None)
        if not train:
            return self._get_forward(len(ins))(self.params, self.variables,
                                               ins, fl)
        self._key, rng = jax.random.split(self._key)  # train-mode stochastics
        fmask_dict = (dict(zip(self.conf.network_inputs, fl))
                      if fl is not None else None)
        acts, _, _ = self._forward_impl(self.params, self.variables, ins,
                                        train=True, rng=rng, fmasks=fmask_dict)
        return [acts[name] for name in self.conf.network_outputs]

    def output_single(self, *inputs) -> Array:
        return self.output(*inputs)[0]

    def feed_forward(self, *inputs, train: bool = False) -> Dict[str, Array]:
        self._check_init()
        ins = [jnp.asarray(a) for a in inputs]
        acts, _, _ = self._forward_impl(self.params, self.variables, ins,
                                        train=train, rng=None)
        return acts

    def score(self, ds=None, inputs=None, labels=None, lmasks=None,
              fmasks=None) -> float:
        self._check_init()
        if ds is not None:
            if hasattr(ds, "features_masks"):
                inputs, labels = ds.features, ds.labels
                lmasks = ds.labels_masks
                fmasks = ds.features_masks
            else:
                inputs, labels = [ds.features], [ds.labels]
                lm = getattr(ds, "labels_mask", None)
                lmasks = [lm] if lm is not None else None
                fm = getattr(ds, "features_mask", None)
                fmasks = [fm] if fm is not None else None
        inputs = [jnp.asarray(a) for a in inputs]
        labels = [jnp.asarray(a) for a in labels]
        if lmasks is not None:
            lmasks = [jnp.asarray(m) if m is not None else None for m in lmasks]
        fmask_dict = None
        if fmasks is not None:
            fmask_dict = {name: (jnp.asarray(m) if m is not None else None)
                          for name, m in zip(self.conf.network_inputs, fmasks)}
        acts, _, _, preouts = self._forward_impl(
            self.params, self.variables, inputs, train=False, rng=None,
            fmasks=fmask_dict, want_preout=True)
        return float(self._loss(acts, labels, lmasks, preouts=preouts)
                     + self._reg_loss(self.params))

    def rnn_time_step(self, *inputs) -> List[Array]:
        """Stateful streaming inference (reference rnnTimeStep:1460)."""
        self._check_init()
        ins = []
        for a in inputs:
            a = jnp.asarray(a)
            if a.ndim == 2:
                a = a[:, None, :]
            ins.append(a)
        # materialize initial states so stateful-only machinery (e.g. the
        # attention KV cache) engages from the first call (see
        # MultiLayerNetwork.rnn_time_step)
        states = _materialize_rnn_states(
            self._impls.items(), self._rnn_state, ins[0].shape[0],
            _compute_dtype_of(self.conf.conf))
        acts, _, new_states = self._forward_impl(
            self.params, self.variables, ins, train=False, rng=None,
            states=states)
        self._rnn_state = new_states
        return [acts[name] for name in self.conf.network_outputs]

    def rnn_clear_previous_state(self):
        self._rnn_state = {}

    # -- params ----------------------------------------------------------------
    def num_params(self) -> int:
        return int(sum(int(np.prod(p.shape))
                       for lp in self.params.values() for p in lp.values()))

    def params_flat(self) -> np.ndarray:
        chunks = []
        for name in sorted(self.params):
            for pname in sorted(self.params[name]):
                chunks.append(np.asarray(self.params[name][pname]).reshape(-1))
        return np.concatenate(chunks) if chunks else np.zeros(0, np.float32)

    def set_params_flat(self, flat: np.ndarray):
        flat = np.asarray(flat)
        off = 0
        for name in sorted(self.params):
            for pname in sorted(self.params[name]):
                arr = self.params[name][pname]
                n = int(np.prod(arr.shape))
                self.params[name][pname] = jnp.asarray(
                    flat[off:off + n].reshape(arr.shape), arr.dtype)
                off += n

    def updater_state_flat(self) -> np.ndarray:
        chunks = []
        for name in sorted(self.updater_state):
            for pname in sorted(self.updater_state[name]):
                for sname in sorted(self.updater_state[name][pname]):
                    chunks.append(np.asarray(
                        self.updater_state[name][pname][sname]).reshape(-1))
        return np.concatenate(chunks) if chunks else np.zeros(0, np.float32)

    def set_updater_state_flat(self, flat: np.ndarray):
        flat = np.asarray(flat)
        off = 0
        for name in sorted(self.updater_state):
            for pname in sorted(self.updater_state[name]):
                for sname in sorted(self.updater_state[name][pname]):
                    arr = self.updater_state[name][pname][sname]
                    n = int(np.prod(arr.shape))
                    self.updater_state[name][pname][sname] = jnp.asarray(
                        flat[off:off + n].reshape(arr.shape), arr.dtype)
                    off += n

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)

    def evaluate(self, iterator, top_n: int = 1):
        from ..evaluation.evaluation import Evaluation
        ev = Evaluation(top_n=top_n)
        for ds in iterator:
            fm = getattr(ds, "features_mask", None)
            out = self.output(ds.features,
                              fmasks=[fm] if fm is not None else None)[0]
            ev.eval(ds.labels, out, mask=getattr(ds, "labels_mask", None))
        return ev

    def evaluate_regression(self, iterator):
        """Per-column regression metrics (reference
        ComputationGraph.evaluateRegression; single-input/single-output)."""
        from ..evaluation.evaluation import RegressionEvaluation
        ev = RegressionEvaluation()
        for ds in iterator:
            fm = getattr(ds, "features_mask", None)
            out = np.asarray(self.output(
                ds.features, fmasks=[fm] if fm is not None else None)[0])
            ev.eval(ds.labels, out, mask=getattr(ds, "labels_mask", None))
        return ev

    def clone(self) -> "ComputationGraph":
        g = ComputationGraph(copy.deepcopy(self.conf))
        if self._initialized:
            g.init()
            # deep-copy buffers: the jitted train step donates params/updater
            # state, which would invalidate shared arrays on TPU
            g.params = jax.tree_util.tree_map(jnp.array, self.params)
            g.variables = jax.tree_util.tree_map(jnp.array, self.variables)
            g.updater_state = jax.tree_util.tree_map(jnp.array, self.updater_state)
            g.step = self.step
        return g
