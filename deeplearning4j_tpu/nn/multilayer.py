"""MultiLayerNetwork: the sequential-network facade.

Capability parity with the reference's
deeplearning4j-core/.../nn/multilayer/MultiLayerNetwork.java (2,369 LoC):
fit(:1013 — async wrap, pretrain branch, TBPTT branch), feedForward(:619),
backprop(:1067), doTruncatedBPTT(:1159), output(:1502), rnnTimeStep (stateful
inference), score, flat param views, layerwise pretrain(:165)/finetune(:1331).

TPU-first redesign (SURVEY.md §7): the Solver/Updater/StepFunction object
machinery collapses into ONE jit-compiled pure `train_step`:
    (params, variables, updater_state, step, rng, batch) -> (params', ...)
traced once per input shape and fused end-to-end by XLA — forward, backward
(jax.grad — no handwritten backpropGradient), gradient normalization, lr
schedule, updater kernel, and parameter update all in a single HBM-resident
program. Listeners observe from the host side between steps, like the
reference's IterationListener hook.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .conf.config import (BACKPROP_TBPTT, MultiLayerConfiguration,
                          NeuralNetConfiguration)
from .conf.preprocessors import (CnnToRnnPreProcessor,
                                 FeedForwardToRnnPreProcessor)
from .layers.base import LayerImpl, impl_for, remat_forward
from .layers.pretrain import AutoEncoderImpl, RBMImpl
from .layers.recurrent import (BaseRecurrentImpl,
                               _materialize_rnn_states)
from .updater.gradnorm import apply_gradient_normalization
from .updater.schedules import effective_lr
from ..ops import losses as losses_mod

Array = jax.Array


def _dtype_of(conf: NeuralNetConfiguration):
    return {"bfloat16": jnp.bfloat16, "float64": jnp.float64}.get(conf.dtype, jnp.float32)


_COMPUTE_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                   "float64": jnp.float64}


def _compute_dtype_of(conf: NeuralNetConfiguration):
    """Forward/backward compute dtype: `compute_dtype` when set (mixed
    precision with f32 master weights), else the parameter dtype."""
    cd = getattr(conf, "compute_dtype", None)
    if cd:
        if cd not in _COMPUTE_DTYPES:
            raise ValueError(
                f"Unsupported compute_dtype '{cd}' "
                f"(supported: {sorted(_COMPUTE_DTYPES)})")
        return _COMPUTE_DTYPES[cd]
    return _dtype_of(conf)


def _cast_floats(tree, dtype):
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a, tree)


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self._impls: List[LayerImpl] = [impl_for(l) for l in conf.layers]
        self.params: List[Dict[str, Array]] = []
        self.variables: List[Dict[str, Array]] = []
        self.updater_state: List[Dict[str, Dict[str, Array]]] = []
        self.step = 0
        self._score_raw: Any = float("nan")
        # minibatches fused per device dispatch in fit(iterator) — one jitted
        # lax.scan over a [K, B, ...] stack (kills the per-step host floor)
        self.scan_batches = 16
        self.listeners: List[Any] = []
        self._rnn_state: Dict[int, Dict[str, Array]] = {}
        self._jit_cache: Dict[Any, Any] = {}
        self._key = jax.random.PRNGKey(conf.conf.seed)
        self._initialized = False

    # ------------------------------------------------------------------ init --
    def init(self) -> "MultiLayerNetwork":
        dtype = _dtype_of(self.conf.conf)
        key = jax.random.PRNGKey(self.conf.conf.seed)
        keys = jax.random.split(key, max(len(self._impls), 1))
        self.params = [impl.init_params(keys[i], dtype)
                       for i, impl in enumerate(self._impls)]
        self.variables = [impl.init_variables(dtype) for impl in self._impls]
        self.updater_state = [
            {name: self.conf.layers[i].updater.init_state(p)
             for name, p in layer_params.items()}
            for i, layer_params in enumerate(self.params)
        ]
        self.step = 0
        self._initialized = True
        return self

    def _check_init(self):
        if not self._initialized:
            self.init()

    # score_ is lazily materialized: the training paths store the device
    # scalar and only block on device->host transfer when someone reads it
    # (listener/early-stopping), keeping the dispatch pipeline full.
    @property
    def score_(self) -> float:
        v = self._score_raw
        if not isinstance(v, float):
            v = float(v)
            self._score_raw = v
        return v

    @score_.setter
    def score_(self, v):
        self._score_raw = v

    def _adapt_input(self, x: Array) -> Array:
        """Adapt raw data to the declared input type — the reference inserts
        this automatically (nn/conf/layers/setup/ConvolutionLayerSetup.java:37):
        flat [B, h*w*c] rows fed to a net declared convolutional are reshaped
        to NHWC; [B,h,w] grayscale gets its channel axis."""
        it = self.conf.input_type
        if it is None or getattr(it, "kind", None) != "convolutional":
            return x
        h, w, c = it.hwc()
        if x.ndim == 2 and x.shape[1] == h * w * c:
            return x.reshape(x.shape[0], h, w, c)
        if x.ndim == 3 and c == 1 and x.shape[1:] == (h, w):
            return x[..., None]
        return x

    # ------------------------------------------------------------- forward ---
    def _forward_impl(self, params, variables, x, *, train, rng, fmask=None,
                      states=None, upto: Optional[int] = None,
                      in_scan: bool = False, fuse_pairs: bool = False,
                      want_preout: bool = False):
        """Pure forward through layers [0, upto). Returns
        (activations per layer, new variables, new rnn states) — plus the
        final layer's PRE-activation as a 4th element when ``want_preout``
        (the loss path feeds it to the stable from-logits losses).

        ``fuse_pairs`` (set ONLY by the train-step loss path, where acts
        feed nothing but the loss) enables the BN+pool composite; public
        per-layer activation consumers (feed_forward, gradient checks)
        keep the exact layerwise outputs."""
        conf = self.conf
        n = len(self._impls) if upto is None else upto
        x = self._adapt_input(x)
        timesteps = x.shape[1] if x.ndim == 3 else 1
        if rng is None:
            rngs = [None] * n
        else:
            rngs = list(jax.random.split(rng, max(n, 1)))
        acts = []
        new_vars = list(variables)
        new_states: Dict[int, Any] = {}
        preout = None
        cur = x
        dtype = _compute_dtype_of(conf.conf)
        if dtype != _dtype_of(conf.conf):
            # mixed precision: params cast to the compute dtype for the
            # traced math; autodiff casts grads back to the (f32) master
            # params, and the updater runs in master precision
            params = _cast_floats(params, dtype)
        if jnp.issubdtype(cur.dtype, jnp.floating) and cur.dtype != dtype:
            cur = cur.astype(dtype)  # cast input to the net's compute dtype
        i = 0
        while i < n:
            proc = conf.preprocessor(i)
            if proc is not None:
                if isinstance(proc, (FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor)):
                    cur = proc.preprocess_with_time(cur, timesteps)
                else:
                    cur = proc.preprocess(cur)
            if cur.ndim == 3:
                timesteps = cur.shape[1]
            impl = self._impls[i]
            lmask_arg = fmask if cur.ndim == 3 else None
            ckpt = train and getattr(conf.conf, "remat", False)
            # BN+act+pool pair fusion (ops/helpers.bn_act_pool): one
            # composite op for [BatchNormalization -> 2x2/s2 max pool] in
            # train mode — the Pallas plugin replaces its backward with a
            # 2-pass fused kernel (the XLA backward costs ~4 HBM passes:
            # select-and-scatter + act/BN-dx + two stat-grad reductions).
            if (train and fuse_pairs and not ckpt and i + 1 < n
                    and hasattr(impl, "forward_fused_pool")
                    and type(self._impls[i + 1]).__name__
                    == "SubsamplingLayerImpl"
                    and conf.preprocessor(i + 1) is None
                    and impl.can_fuse_pool(impl.conf,
                                           self._impls[i + 1].conf, cur)):
                y, nv = impl.forward_fused_pool(params[i], cur,
                                                variables=variables[i])
                new_vars[i] = nv
                if jnp.issubdtype(y.dtype, jnp.floating) and y.dtype != dtype:
                    y = y.astype(dtype)
                # both fused layers record the pooled output
                acts.append(y)
                acts.append(y)
                cur = y
                i += 2
                continue
            if isinstance(impl, BaseRecurrentImpl):
                state0 = (states or {}).get(i)
                y, st = remat_forward(impl, train=train, ckpt=ckpt,
                                      recurrent=True, in_scan=in_scan)(
                    params[i], cur, state0, rngs[i], lmask_arg)
                new_states[i] = st
            elif (want_preout and i == n - 1
                    and hasattr(impl, "forward_with_preout")):
                # final layer, loss path: also surface the pre-activation
                # (cheap — no remat needed, the loss consumes it immediately)
                y, preout, nv = impl.forward_with_preout(
                    params[i], cur, train=train, rng=rngs[i],
                    variables=variables[i], mask=lmask_arg)
                new_vars[i] = nv
            else:
                y, nv = remat_forward(impl, train=train, ckpt=ckpt,
                                      recurrent=False, in_scan=in_scan)(
                    params[i], cur, variables[i], rngs[i], lmask_arg)
                new_vars[i] = nv
            if jnp.issubdtype(y.dtype, jnp.floating) and y.dtype != dtype:
                y = y.astype(dtype)  # stop f32 creep (e.g. BN's f32 stats)
            acts.append(y)
            cur = y
            i += 1
        if want_preout:
            return acts, new_vars, new_states, preout
        return acts, new_vars, new_states

    def _loss_from_output(self, out: Array, y: Array, lmask: Optional[Array],
                          preout: Optional[Array] = None):
        out_layer_conf = self.conf.layers[-1]
        loss_name = getattr(out_layer_conf, "loss", None) or "mse"
        fused = losses_mod.fused_from_logits(
            getattr(out_layer_conf, "activation", None), loss_name)
        if preout is not None and fused is not None:
            out, loss_fn = preout, fused  # stable from-logits path
        else:
            loss_fn = losses_mod.get(loss_name)
        if out.ndim == 3:  # RNN output: flatten time
            o = out.reshape(-1, out.shape[-1])
            t = y.reshape(-1, y.shape[-1])
            m = lmask.reshape(-1) if lmask is not None else None
            return loss_fn(t, o, m)
        m = lmask.reshape(-1) if lmask is not None else None
        return loss_fn(y, out, m)

    def _reg_loss(self, params):
        total = jnp.asarray(0.0, jnp.float32)
        for impl, p in zip(self._impls, params):
            total = total + impl.reg_loss(p)
        return total

    # ---------------------------------------------------------- train step ---
    def _apply_updaters(self, params, grads, ustates, step):
        gconf = self.conf.conf
        new_params, new_ustates = [], []
        for i, layer_conf in enumerate(self.conf.layers):
            lgrads = grads[i]
            if not lgrads:
                new_params.append(params[i])
                new_ustates.append(ustates[i])
                continue
            lgrads = apply_gradient_normalization(
                lgrads, layer_conf.gradient_normalization or "none",
                layer_conf.gradient_normalization_threshold or 1.0)
            updater = layer_conf.updater
            base_lr = updater_lr = getattr(updater, "learning_rate", -1.0)
            if updater_lr is None or updater_lr < 0:
                base_lr = layer_conf.learning_rate
            bias_lr = layer_conf.bias_learning_rate or base_lr
            wd = float(getattr(updater, "weight_decay", 0.0) or 0.0)
            wkeys = self._impls[i].WEIGHT_KEYS
            lp, lu = {}, {}
            for name, g in lgrads.items():
                lr0 = bias_lr if name in ("b", "vb", "beta") else base_lr
                lr = effective_lr(lr0, step, gconf.lr_policy,
                                  gconf.lr_policy_decay_rate, gconf.lr_policy_power,
                                  gconf.lr_policy_steps, gconf.max_num_iterations,
                                  gconf.lr_schedule).astype(g.dtype)
                delta, new_state = updater.apply(ustates[i][name], g, lr, step)
                p = params[i][name]
                if wd and name in wkeys:  # decoupled (AdamW-style) decay
                    delta = delta - lr * jnp.asarray(wd, p.dtype) * p
                lp[name] = p + delta
                lu[name] = new_state
            new_params.append(lp)
            new_ustates.append(lu)
        return new_params, new_ustates

    def _build_loss_fn(self, carry_state: bool, in_scan: bool):
        """The pure training loss (batch mean + regularization) with aux
        (new variables, new rnn states) — shared by the train step and the
        gradient-accumulation step."""
        def loss_fn(params, variables, x, y, fmask, lmask, rng, states):
            acts, new_vars, new_states, preout = self._forward_impl(
                params, variables, x, train=True, rng=rng, fmask=fmask,
                states=states if carry_state else None, in_scan=in_scan,
                fuse_pairs=True, want_preout=True)
            out = acts[-1]
            loss = (self._loss_from_output(out, y, lmask, preout=preout)
                    + self._reg_loss(params))
            return loss.astype(jnp.float32), (new_vars, new_states)
        return loss_fn

    def _build_train_step(self, key, in_scan: bool = False):
        """Build the raw (unjitted) pure train step — reused by the
        distributed trainers (parallel/) inside shard_map. ``in_scan`` marks
        steps traced inside a lax.scan body (remat drops its CSE barriers
        there; see layers/base.remat_forward)."""
        has_fmask, has_lmask, carry_state = key
        loss_fn = self._build_loss_fn(carry_state, in_scan)

        def train_step(params, variables, ustates, step, rng, x, y, fmask, lmask, states):
            (loss, (new_vars, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, variables, x, y, fmask, lmask, rng, states)
            new_params, new_ustates = self._apply_updaters(params, grads, ustates, step)
            return new_params, new_vars, new_ustates, loss, new_states

        return train_step

    def _get_train_step(self, key):
        if key in self._jit_cache:
            return self._jit_cache[key]
        fn = jax.jit(self._build_train_step(key), donate_argnums=(0, 2))
        self._jit_cache[key] = fn
        return fn

    # ------------------------------------------- gradient accumulation ------
    def _build_accum_step(self, key):
        """ONE optimizer update from K accumulated microbatch gradients, as
        one device program (beyond the reference; the HBM lever for batches
        that don't fit — each microbatch's activations are freed before the
        next runs under lax.scan). Each microbatch loss is a batch MEAN, so
        sum/K is exactly the full-batch mean gradient for batch-independent
        layers; BatchNorm uses per-MICRObatch statistics (the standard
        large-model behavior — document, don't hide)."""
        has_fmask, has_lmask = key
        loss_fn = self._build_loss_fn(carry_state=False, in_scan=True)

        def accum_step(params, variables, ustates, step, rng, xs, ys, fms, lms):
            k = xs.shape[0]
            gzero = jax.tree_util.tree_map(jnp.zeros_like, params)

            def body(carry, inp):
                gsum, variables = carry
                x, y, fm, lm, i = inp
                sub = jax.random.fold_in(rng, i)
                (loss, (new_vars, _)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(
                        params, variables, x, y,
                        fm if has_fmask else None,
                        lm if has_lmask else None, sub, None)
                gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
                return (gsum, new_vars), loss

            dummy = jnp.zeros((k,), jnp.float32)
            (gsum, new_vars), losses = jax.lax.scan(
                body, (gzero, variables),
                (xs, ys, fms if has_fmask else dummy,
                 lms if has_lmask else dummy, jnp.arange(k)))
            grads = jax.tree_util.tree_map(lambda g: g / k, gsum)
            new_params, new_ustates = self._apply_updaters(
                params, grads, ustates, step)
            return new_params, new_vars, new_ustates, losses

        return accum_step

    def fit_batch_accumulated(self, x, y, accumulation_steps: int,
                              fmask=None, lmask=None):
        """Train ONE optimizer step on a batch too large for HBM by
        accumulating gradients over `accumulation_steps` microbatches
        (batch size must divide evenly). Equivalent to `fit_batch` on the
        full batch for BatchNorm-free, unmasked nets (golden-tested); with
        BatchNorm statistics are per-microbatch, and with label masks the
        per-microbatch weighted means make it an approximation unless mask
        weight is uniform across microbatches. Returns the mean microbatch
        loss."""
        self._check_init()
        algo = (self.conf.conf.optimization_algo or
                "stochastic_gradient_descent").lower()
        if (algo not in ("stochastic_gradient_descent", "sgd")
                or self.conf.conf.iterations > 1):
            raise ValueError(
                "fit_batch_accumulated supports SGD-family training with "
                f"iterations=1 (got algo={algo!r}, "
                f"iterations={self.conf.conf.iterations}); use fit_batch "
                "for solver-based optimization")
        k = int(accumulation_steps)
        if k <= 0:
            raise ValueError(f"accumulation_steps must be >= 1 (got {k})")
        x, y = jnp.asarray(x), jnp.asarray(y)
        if x.shape[0] % k:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by "
                f"accumulation_steps {k}")
        b = x.shape[0] // k

        def split(a):
            return (None if a is None else
                    jnp.asarray(a).reshape((k, b) + tuple(a.shape[1:])))

        key = (fmask is not None, lmask is not None)
        ck = ("accum",) + key
        if ck not in self._jit_cache:
            self._jit_cache[ck] = jax.jit(self._build_accum_step(key),
                                          donate_argnums=(0, 2))
        self._key, sub = jax.random.split(self._key)
        (self.params, self.variables, self.updater_state,
         losses) = self._jit_cache[ck](
            self.params, self.variables, self.updater_state,
            jnp.asarray(self.step), sub, split(x), split(y),
            split(fmask), split(lmask))
        self.step += 1
        mean_loss = jnp.mean(losses)
        self.score_ = mean_loss  # lazy: reading .score_ fetches it later
        for listener in self.listeners:
            listener.iteration_done(self, self.step)
        return mean_loss  # device scalar — no blocking host fetch here

    # ------------------------------------------------- multi-step (scan) -----
    def _build_multi_step(self, key):
        """K optimization steps as ONE device program: lax.scan over a
        [K, B, ...] stack of minibatches. Replaces K host dispatches (and K
        blocking loss fetches) with a single dispatch + one [K] loss fetch —
        the TPU answer to the reference's per-minibatch Solver.optimize()
        round trip (MultiLayerNetwork.java:1033-1062)."""
        has_fmask, has_lmask = key
        base = self._build_train_step((has_fmask, has_lmask, False),
                                      in_scan=True)

        def multi_step(params, variables, ustates, step0, rng, xs, ys, fms, lms):
            def body(carry, inp):
                params, variables, ustates, step = carry
                x, y, fm, lm = inp
                sub = jax.random.fold_in(rng, step)
                p, v, u, loss, _ = base(params, variables, ustates, step, sub,
                                        x, y, fm if has_fmask else None,
                                        lm if has_lmask else None, None)
                return (p, v, u, step + 1), loss

            k = xs.shape[0]
            dummy = jnp.zeros((k,), jnp.float32)  # keeps scan xs-tree static
            (params, variables, ustates, _), losses = jax.lax.scan(
                body, (params, variables, ustates, step0),
                (xs, ys, fms if has_fmask else dummy,
                 lms if has_lmask else dummy))
            return params, variables, ustates, losses

        return multi_step

    def fit_scan(self, xs, ys, fms=None, lms=None):
        """Run xs.shape[0] training steps fully device-resident.

        xs: [K, B, ...] stacked minibatches, ys: [K, B, ...] labels. Returns
        the [K] per-step losses (device array; not fetched unless listeners
        are attached).

        Each xs[k] is ONE optimization step (no TBPTT windowing or RNN state
        carry across slices — for TBPTT nets each slice must be a single
        window, which is enforced below). Listeners get the exact per-step
        score, but observe the model's end-of-chunk parameters: per-step
        parameter snapshots require the one-step-per-dispatch `fit_batch`."""
        self._check_init()
        if not self._can_scan():
            raise ValueError(
                "fit_scan requires SGD-class training (optimization_algo="
                "stochastic_gradient_descent, iterations=1, scan_batches>1); "
                "use fit()/fit_batch for solver-driven or multi-iteration "
                "configurations")
        xs = jnp.asarray(xs)
        ys = jnp.asarray(ys)
        if (self.conf.backprop_type == BACKPROP_TBPTT and xs.ndim == 4
                and xs.shape[2] > self.conf.tbptt_fwd_length):
            raise ValueError(
                f"fit_scan slices have T={xs.shape[2]} > tbptt_fwd_length="
                f"{self.conf.tbptt_fwd_length}; fit_scan does not window — "
                "pass single TBPTT windows or use fit()")
        key = (fms is not None, lms is not None)
        cache_key = ("multi", key)
        if cache_key not in self._jit_cache:
            self._jit_cache[cache_key] = jax.jit(
                self._build_multi_step(key), donate_argnums=(0, 1, 2))
        fn = self._jit_cache[cache_key]
        self._key, sub = jax.random.split(self._key)
        k = int(xs.shape[0])
        (self.params, self.variables, self.updater_state, losses) = fn(
            self.params, self.variables, self.updater_state,
            jnp.asarray(self.step), sub, xs, ys,
            jnp.asarray(fms) if fms is not None else None,
            jnp.asarray(lms) if lms is not None else None)
        self.step += k
        self._score_raw = losses[-1]
        if self.listeners:
            host_losses = np.asarray(losses)
            for j in range(k):
                self._score_raw = float(host_losses[j])
                for listener in self.listeners:
                    listener.iteration_done(self, self.step - k + 1 + j)
        return losses

    def _can_scan(self) -> bool:
        algo = (self.conf.conf.optimization_algo or
                "stochastic_gradient_descent").lower()
        return (self.scan_batches > 1
                and self.conf.conf.iterations <= 1
                and algo in ("stochastic_gradient_descent", "sgd"))

    def fit_batch(self, x, y, fmask=None, lmask=None, states=None,
                  carry_state=False):
        """One (or conf.iterations) optimization step(s) on a single minibatch."""
        self._check_init()
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        fmask = jnp.asarray(fmask) if fmask is not None else None
        lmask = jnp.asarray(lmask) if lmask is not None else None
        algo = (self.conf.conf.optimization_algo or
                "stochastic_gradient_descent").lower()
        if algo not in ("stochastic_gradient_descent", "sgd"):
            if carry_state:
                raise NotImplementedError(
                    f"optimization_algo={algo!r} is not supported with "
                    "truncated BPTT; use stochastic_gradient_descent")
            return self._fit_batch_solver(algo, x, y, fmask, lmask)
        step_fn = self._get_train_step((fmask is not None, lmask is not None, carry_state))
        out_states = states
        for _ in range(max(1, self.conf.conf.iterations)):
            self._key, sub = jax.random.split(self._key)
            (self.params, self.variables, self.updater_state, loss,
             out_states) = step_fn(self.params, self.variables, self.updater_state,
                                   jnp.asarray(self.step), sub, x, y, fmask, lmask,
                                   states if carry_state else None)
            self._score_raw = loss  # lazy: no blocking device->host fetch
            self.step += 1
            for listener in self.listeners:
                listener.iteration_done(self, self.step)
        return out_states

    def _fit_batch_solver(self, algo: str, x, y, fmask, lmask):
        """Whole-net training under a classic optimizer (CG / LBFGS /
        line-search gradient descent) — the reference drives
        computeGradientAndScore through these when conf.optimizationAlgo
        selects them (optimize/solvers/BaseOptimizer.java:51,
        ConjugateGradient.java, LBFGS.java). The objective is the minibatch
        loss (+ regularization) over the flat parameter vector; conf.iterations
        bounds the optimizer iterations per minibatch, matching the
        reference's `iterations` semantics."""
        from jax.flatten_util import ravel_pytree
        from ..optimize.solver import OPTIMIZERS
        cls = OPTIMIZERS.get(algo)
        if cls is None:
            raise ValueError(
                f"Unknown optimization_algo {algo!r}; available: "
                f"{sorted(OPTIMIZERS)}")
        flat0, unravel = ravel_pytree(self.params)
        self._key, rng = jax.random.split(self._key)

        def objective(flat):
            params = unravel(flat)
            acts, _, _, preout = self._forward_impl(
                params, self.variables, x, train=True, rng=rng, fmask=fmask,
                want_preout=True)
            loss = self._loss_from_output(acts[-1], y, lmask, preout=preout)
            return (loss + self._reg_loss(params)).astype(jnp.float32)

        lr = self.conf.layers[0].learning_rate if self.conf.layers else 0.1
        opt = cls(objective, max_iterations=max(1, self.conf.conf.iterations),
                  learning_rate=lr)
        flat = opt.optimize(flat0)
        self.params = unravel(jnp.asarray(flat, flat0.dtype))
        # refresh batch-dependent variables (e.g. BN running stats) once
        _, self.variables, _ = self._forward_impl(self.params, self.variables, x,
                                                  train=True, rng=rng, fmask=fmask)
        self.score_ = opt.score_
        self.step += 1
        for listener in self.listeners:
            listener.iteration_done(self, self.step)
        return None

    # ------------------------------------------------------------------ fit --
    def fit(self, data, labels=None):
        """fit(DataSetIterator) | fit(DataSet) | fit(x, y).
        Mirrors MultiLayerNetwork.fit(DataSetIterator):1013."""
        self._check_init()
        from ..util.heartbeat import report_event
        report_event("standalone_fit", self)  # MultiLayerNetwork.java:52-56
        if labels is not None:
            self._fit_one(jnp.asarray(data), jnp.asarray(labels), None, None)
            return self
        if hasattr(data, "features"):  # single DataSet
            self._fit_one(data.features, data.labels,
                          getattr(data, "features_mask", None),
                          getattr(data, "labels_mask", None))
            return self
        # iterator path
        if self.conf.pretrain:
            self.pretrain(data)
            if hasattr(data, "reset"):
                data.reset()
        if self.conf.backprop:
            self._fit_iterator(data)
        return self

    def _fit_iterator(self, iterator):
        """Drive fit over a DataSetIterator: background prefetch (reference
        wraps in AsyncDataSetIterator, MultiLayerNetwork.java:1016-1018) +
        fusing runs of same-shape unmasked minibatches into one device-resident
        lax.scan dispatch (`fit_scan`)."""
        from ..datasets.iterators import AsyncDataSetIterator, DataSetIterator
        wrapped = (isinstance(iterator, DataSetIterator)
                   and not isinstance(iterator, AsyncDataSetIterator))
        if wrapped:
            # reset the UNDERLYING iterator first (matching `for ds in it`
            # semantics), then consume the async wrapper without reset — an
            # AsyncDataSetIterator.reset right after construction would
            # discard the batches the worker already prefetched
            iterator.reset()
            it = AsyncDataSetIterator(iterator,
                                      queue_size=2 * self.scan_batches)

            def batches():
                while True:
                    ds = it.next_batch()
                    if ds is None:
                        return
                    yield ds

            source = batches()
        else:
            source = iter(iterator)
        use_scan = self._can_scan() and self.conf.backprop_type != BACKPROP_TBPTT
        if not use_scan:
            for ds in source:
                self._fit_one(ds.features, ds.labels,
                              getattr(ds, "features_mask", None),
                              getattr(ds, "labels_mask", None))
            return

        buf: List[Any] = []

        def flush():
            if not buf:
                return
            if len(buf) < self.scan_batches:
                # partial chunk: reuse the single-step program instead of
                # compiling a one-off scan for this K
                for d in buf:
                    self.fit_batch(d.features, d.labels)
            else:
                xs = np.stack([np.asarray(d.features) for d in buf])
                ys = np.stack([np.asarray(d.labels) for d in buf])
                self.fit_scan(xs, ys)
            buf.clear()

        buf_shapes = None
        for ds in source:
            fm = getattr(ds, "features_mask", None)
            lm = getattr(ds, "labels_mask", None)
            if fm is not None or lm is not None:
                flush()
                self._fit_one(ds.features, ds.labels, fm, lm)
                continue
            shapes = (ds.features.shape, ds.labels.shape)
            if buf and shapes != buf_shapes:
                flush()
            buf_shapes = shapes
            buf.append(ds)
            if len(buf) >= self.scan_batches:
                flush()
        flush()

    def _fit_one(self, x, y, fmask, lmask):
        if (self.conf.backprop_type == BACKPROP_TBPTT
                and jnp.asarray(x).ndim == 3):
            self._do_truncated_bptt(x, y, fmask, lmask)
        else:
            self.fit_batch(x, y, fmask, lmask)

    def _do_truncated_bptt(self, x, y, fmask, lmask):
        """Sliding-window TBPTT with carried RNN state
        (reference doTruncatedBPTT:1159 + updateRnnStateWithTBPTTState:1217)."""
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        T = x.shape[1]
        L = self.conf.tbptt_fwd_length
        states = _materialize_rnn_states(
            enumerate(self._impls), {}, x.shape[0],
            _compute_dtype_of(self.conf.conf), tbptt=True)
        start = 0
        while start < T:
            end = min(start + L, T)
            xs = x[:, start:end]
            ys = y[:, start:end] if y.ndim == 3 else y
            fs = fmask[:, start:end] if fmask is not None else None
            ls = lmask[:, start:end] if lmask is not None else None
            states = self.fit_batch(xs, ys, fs, ls, states=states, carry_state=True)
            states = jax.tree_util.tree_map(jax.lax.stop_gradient, states)
            start = end

    # ------------------------------------------------------------- pretrain --
    def pretrain(self, iterator):
        """Greedy layerwise pretraining (reference pretrain:165)."""
        self._check_init()
        for i, impl in enumerate(self._impls):
            if not self.conf.layers[i].is_pretrain_layer():
                continue
            step_fn = self._make_pretrain_step(i)
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                x = jnp.asarray(ds.features)
                self._key, k1, k2 = jax.random.split(self._key, 3)
                # forward input through earlier layers (train-mode activations)
                if i > 0:
                    acts, _, _ = self._forward_impl(self.params, self.variables, x,
                                                    train=False, rng=None, upto=i)
                    x = acts[-1]
                self.params[i], self.updater_state[i], loss = step_fn(
                    self.params[i], self.updater_state[i], jnp.asarray(self.step),
                    k2, x)
                self.score_ = float(loss)

    def _make_pretrain_step(self, i: int):
        impl = self._impls[i]
        layer_conf = self.conf.layers[i]
        gconf = self.conf.conf

        def apply_update(params_i, ustate_i, grads, step):
            grads = apply_gradient_normalization(
                grads, layer_conf.gradient_normalization or "none",
                layer_conf.gradient_normalization_threshold or 1.0)
            updater = layer_conf.updater
            base_lr = getattr(updater, "learning_rate", -1.0)
            if base_lr is None or base_lr < 0:
                base_lr = layer_conf.learning_rate
            wd = float(getattr(updater, "weight_decay", 0.0) or 0.0)
            wkeys = impl.WEIGHT_KEYS
            new_p, new_u = {}, {}
            for name, g in grads.items():
                lr = effective_lr(base_lr, step, gconf.lr_policy,
                                  gconf.lr_policy_decay_rate, gconf.lr_policy_power,
                                  gconf.lr_policy_steps, gconf.max_num_iterations,
                                  gconf.lr_schedule).astype(g.dtype)
                delta, ns = updater.apply(ustate_i[name], g, lr, step)
                p = params_i[name]
                if wd and name in wkeys:
                    # same decoupled (AdamW-style) decay _apply_updaters
                    # uses — pretraining must not silently drop the decay
                    # that fine-tuning will apply (ADVICE r5 #4)
                    delta = delta - lr * jnp.asarray(wd, p.dtype) * p
                new_p[name] = p + delta
                new_u[name] = ns
            return new_p, new_u

        if isinstance(impl, RBMImpl):
            def rbm_step(params_i, ustate_i, step, rng, x):
                grads, recon = impl.cd_gradient(params_i, x, rng)
                new_p, new_u = apply_update(params_i, ustate_i, grads, step)
                return new_p, new_u, recon
            return jax.jit(rbm_step)

        if isinstance(impl, AutoEncoderImpl):
            def ae_step(params_i, ustate_i, step, rng, x):
                loss, grads = jax.value_and_grad(impl.pretrain_loss)(params_i, x, rng)
                new_p, new_u = apply_update(params_i, ustate_i, grads, step)
                return new_p, new_u, loss
            return jax.jit(ae_step)

        raise ValueError(f"Layer {i} is not a pretrainable layer")

    def finetune(self, iterator):
        """Supervised pass after pretraining (reference finetune:1331)."""
        for ds in iterator:
            self._fit_one(ds.features, ds.labels, None, None)

    # ---------------------------------------------------------- inference ----
    def _get_forward(self, train: bool):
        key = ("fwd", train)
        if key not in self._jit_cache:
            def fwd(params, variables, x, fmask, rng):
                acts, _, _ = self._forward_impl(params, variables, x, train=train,
                                                rng=rng, fmask=fmask)
                return acts[-1]
            self._jit_cache[key] = jax.jit(fwd)
        return self._jit_cache[key]

    def output(self, x, train: bool = False, fmask=None) -> Array:
        """Network output (reference output:1502). train=True applies
        train-mode stochastics (dropout) with a fresh rng, matching the
        reference's output(train) semantics."""
        self._check_init()
        rng = None
        if train:
            self._key, rng = jax.random.split(self._key)
        return self._get_forward(train)(self.params, self.variables, jnp.asarray(x),
                                        jnp.asarray(fmask) if fmask is not None else None,
                                        rng)

    def predict(self, x) -> np.ndarray:
        out = self.output(x)
        return np.asarray(jnp.argmax(out, axis=-1))

    def feed_forward(self, x, train: bool = False) -> List[Array]:
        """All layer activations, input first (reference feedForward:619)."""
        self._check_init()
        acts, _, _ = self._forward_impl(self.params, self.variables, jnp.asarray(x),
                                        train=train, rng=None)
        return [jnp.asarray(x)] + list(acts)

    def score(self, dataset=None, x=None, y=None) -> float:
        """Loss (incl. regularization) on a dataset, or last-minibatch score."""
        if dataset is None and x is None:
            return self.score_
        if dataset is not None:
            x, y = dataset.features, dataset.labels
            lmask = getattr(dataset, "labels_mask", None)
            fmask = getattr(dataset, "features_mask", None)
        else:
            lmask = fmask = None
        acts, _, _, preout = self._forward_impl(
            self.params, self.variables, jnp.asarray(x), train=False, rng=None,
            fmask=jnp.asarray(fmask) if fmask is not None else None,
            want_preout=True)
        loss = self._loss_from_output(acts[-1], jnp.asarray(y),
                                      jnp.asarray(lmask) if lmask is not None else None,
                                      preout=preout)
        return float(loss + self._reg_loss(self.params))

    # -------------------------------------------------------- rnn stepping ---
    def rnn_time_step(self, x) -> Array:
        """Stateful streaming inference (reference rnnTimeStep:1460).
        x: [B, T, F]; carries hidden state across calls."""
        self._check_init()
        x = jnp.asarray(x)
        if x.ndim == 2:
            x = x[:, None, :]
        # materialize initial states so stateful-only machinery (e.g. the
        # attention KV cache) engages from the first call; plain output()
        # (states=None) keeps the stateless full path
        states = _materialize_rnn_states(
            enumerate(self._impls), self._rnn_state, x.shape[0],
            _compute_dtype_of(self.conf.conf))
        acts, _, new_states = self._forward_impl(
            self.params, self.variables, x, train=False, rng=None,
            states=states)
        self._rnn_state = new_states
        return acts[-1]

    def rnn_clear_previous_state(self):
        self._rnn_state = {}

    def rnn_get_previous_state(self, layer_idx: int):
        return self._rnn_state.get(layer_idx)

    def rnn_set_previous_state(self, layer_idx: int, state):
        self._rnn_state[layer_idx] = state

    # ------------------------------------------------------------ params -----
    def num_params(self) -> int:
        return int(sum(int(np.prod(p.shape)) for lp in self.params for p in lp.values()))

    def params_flat(self) -> np.ndarray:
        """Flat parameter view in deterministic (layer, name) order —
        parity with the reference's params-as-flat-view contract
        (nn/api/Model.java:95-108)."""
        chunks = []
        for lp in self.params:
            for name in sorted(lp):
                chunks.append(np.asarray(lp[name]).reshape(-1))
        return np.concatenate(chunks) if chunks else np.zeros(0, np.float32)

    def set_params_flat(self, flat: np.ndarray):
        flat = np.asarray(flat)
        off = 0
        new_params = []
        for lp in self.params:
            nlp = {}
            for name in sorted(lp):
                n = int(np.prod(lp[name].shape))
                nlp[name] = jnp.asarray(flat[off:off + n].reshape(lp[name].shape),
                                        lp[name].dtype)
                off += n
            new_params.append(nlp)
        if off != flat.size:
            raise ValueError(f"Expected {off} params, got {flat.size}")
        self.params = new_params

    def updater_state_flat(self) -> np.ndarray:
        chunks = []
        for lu in self.updater_state:
            for name in sorted(lu):
                for sname in sorted(lu[name]):
                    chunks.append(np.asarray(lu[name][sname]).reshape(-1))
        return np.concatenate(chunks) if chunks else np.zeros(0, np.float32)

    def set_updater_state_flat(self, flat: np.ndarray):
        flat = np.asarray(flat)
        off = 0
        new_states = []
        for lu in self.updater_state:
            nlu = {}
            for name in sorted(lu):
                nlu[name] = {}
                for sname in sorted(lu[name]):
                    arr = lu[name][sname]
                    n = int(np.prod(arr.shape))
                    nlu[name][sname] = jnp.asarray(flat[off:off + n].reshape(arr.shape),
                                                   arr.dtype)
                    off += n
            new_states.append(nlu)
        self.updater_state = new_states

    # ------------------------------------------------------------- misc ------
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)

    def add_listener(self, listener):
        self.listeners.append(listener)

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        if self._initialized:
            net.init()
            # deep-copy buffers: the jitted train step donates params/updater
            # state, which would invalidate shared arrays on TPU
            net.params = jax.tree_util.tree_map(jnp.array, self.params)
            net.variables = jax.tree_util.tree_map(jnp.array, self.variables)
            net.updater_state = jax.tree_util.tree_map(jnp.array, self.updater_state)
            net.step = self.step
        return net

    def evaluate(self, iterator, top_n: int = 1):
        from ..evaluation.evaluation import Evaluation
        ev = Evaluation(top_n=top_n)
        for ds in iterator:
            out = self.output(ds.features,
                              fmask=getattr(ds, "features_mask", None))
            ev.eval(ds.labels, out, mask=getattr(ds, "labels_mask", None))
        return ev

    def evaluate_regression(self, iterator):
        """Per-column regression metrics over a dataset (reference
        MultiLayerNetwork.evaluateRegression)."""
        from ..evaluation.evaluation import RegressionEvaluation
        ev = RegressionEvaluation()
        for ds in iterator:
            out = np.asarray(self.output(
                ds.features, fmask=getattr(ds, "features_mask", None)))
            ev.eval(ds.labels, out, mask=getattr(ds, "labels_mask", None))
        return ev

    def summary(self) -> str:
        lines = ["=" * 70]
        for i, lc in enumerate(self.conf.layers):
            nparams = sum(int(np.prod(p.shape)) for p in self.params[i].values()) \
                if self._initialized else 0
            lines.append(f"{i:3d}  {type(lc).__name__:30s} params={nparams}")
        lines.append(f"Total params: {self.num_params() if self._initialized else '?'}")
        lines.append("=" * 70)
        return "\n".join(lines)
