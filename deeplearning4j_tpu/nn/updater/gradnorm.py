"""Gradient normalization strategies.

Parity with the reference `GradientNormalization` enum applied in
BaseUpdater.preApply (tested by nn/updater/TestGradientNormalization in the
reference). Operates on a per-layer dict of param-name -> gradient.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

Array = jax.Array

NONE = "none"
RENORMALIZE_L2_PER_LAYER = "renormalizel2perlayer"
RENORMALIZE_L2_PER_PARAM_TYPE = "renormalizel2perparamtype"
CLIP_ELEMENT_WISE_ABSOLUTE_VALUE = "clipelementwiseabsolutevalue"
CLIP_L2_PER_LAYER = "clipl2perlayer"
CLIP_L2_PER_PARAM_TYPE = "clipl2perparamtype"

ALL = (NONE, RENORMALIZE_L2_PER_LAYER, RENORMALIZE_L2_PER_PARAM_TYPE,
       CLIP_ELEMENT_WISE_ABSOLUTE_VALUE, CLIP_L2_PER_LAYER, CLIP_L2_PER_PARAM_TYPE)

_EPS = 1e-8


def _l2(x: Array) -> Array:
    return jnp.sqrt(jnp.sum(x * x))


def apply_gradient_normalization(
    grads: Dict[str, Array], strategy: str, threshold: float = 1.0
) -> Dict[str, Array]:
    s = (strategy or NONE).lower()
    if s == NONE:
        return grads
    if s == RENORMALIZE_L2_PER_LAYER:
        total = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + _EPS)
        return {k: g / total for k, g in grads.items()}
    if s == RENORMALIZE_L2_PER_PARAM_TYPE:
        return {k: g / (_l2(g) + _EPS) for k, g in grads.items()}
    if s == CLIP_ELEMENT_WISE_ABSOLUTE_VALUE:
        return {k: jnp.clip(g, -threshold, threshold) for k, g in grads.items()}
    if s == CLIP_L2_PER_LAYER:
        total = jnp.sqrt(sum(jnp.sum(g * g) for g in grads.values()) + _EPS)
        scale = jnp.where(total > threshold, threshold / total, 1.0)
        return {k: g * scale for k, g in grads.items()}
    if s == CLIP_L2_PER_PARAM_TYPE:
        out = {}
        for k, g in grads.items():
            n = _l2(g) + _EPS
            out[k] = g * jnp.where(n > threshold, threshold / n, 1.0)
        return out
    raise ValueError(f"Unknown gradient normalization '{strategy}'. Available: {ALL}")
