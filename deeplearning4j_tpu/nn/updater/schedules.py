"""Learning-rate decay policies.

Parity with the reference `LearningRatePolicy` enum + the schedule application
in BaseUpdater (`applyLrDecayPolicy`, deeplearning4j-core/.../nn/updater/
BaseUpdater.java:88-120 region). jit-safe: `iteration` may be a traced scalar.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

POLICIES = ("none", "exponential", "inverse", "poly", "sigmoid", "step",
            "schedule", "warmup_cosine")


def effective_lr(
    base_lr: float,
    iteration,
    policy: str = "none",
    decay_rate: float = 0.0,
    power: float = 1.0,
    steps: float = 1.0,
    max_iterations: int = 1,
    schedule: Optional[Dict[str, float]] = None,
):
    """Compute the scheduled learning rate for `iteration` (0-based)."""
    it = jnp.asarray(iteration, jnp.float32)
    lr = jnp.asarray(base_lr, jnp.float32)
    policy = (policy or "none").lower()
    if policy == "none":
        return lr
    if policy == "exponential":
        return lr * jnp.power(decay_rate, it)
    if policy == "inverse":
        return lr / jnp.power(1.0 + decay_rate * it, power)
    if policy == "poly":
        frac = jnp.clip(it / jnp.maximum(float(max_iterations), 1.0), 0.0, 1.0)
        return lr * jnp.power(1.0 - frac, power)
    if policy == "sigmoid":
        return lr / (1.0 + jnp.exp(decay_rate * (it - steps)))
    if policy == "step":
        return lr * jnp.power(decay_rate, jnp.floor(it / steps))
    if policy == "schedule":
        # piecewise-constant: lr takes the value of the largest key <= iteration
        out = lr
        for k, v in sorted((int(k), float(v)) for k, v in (schedule or {}).items()):
            out = jnp.where(it >= k, v, out)
        return out
    if policy == "warmup_cosine":
        # beyond reference (transformer-era default): linear warmup over
        # `steps` iterations from 0 to base_lr, then cosine decay to
        # base_lr*decay_rate by max_iterations
        warm = jnp.maximum(float(steps), 1.0)
        floor_frac = jnp.asarray(decay_rate, jnp.float32)
        warm_lr = lr * it / warm
        span = jnp.maximum(float(max_iterations) - warm, 1.0)
        prog = jnp.clip((it - warm) / span, 0.0, 1.0)
        cos_lr = lr * (floor_frac + (1.0 - floor_frac)
                       * 0.5 * (1.0 + jnp.cos(jnp.pi * prog)))
        return jnp.where(it < warm, warm_lr, cos_lr)
    raise ValueError(f"Unknown lr policy '{policy}'. Available: {POLICIES}")
