"""Updater (learning-rule) kernels + their serializable configs.

Capability parity with the reference updater zoo: `nn/updater/*`
(Sgd/Adam/AdaGrad/AdaDelta/RmsProp/Nesterovs/NoOp wrappers in
deeplearning4j-core/.../nn/updater/, kernels in ND4J
`org.nd4j.linalg.learning.GradientUpdater` — SURVEY.md §2.1). TPU-first
redesign: each updater is a pure (state, grad, lr, step) -> (delta, state)
function applied over the whole param pytree inside the single jit-compiled
train step, instead of the per-param-name Java object loop
(BaseUpdater.java:35). `delta` is ADDED to params.

State shapes mirror param shapes, so updater state averages across
data-parallel replicas exactly like the reference's UpdaterAggregator
(nn/updater/aggregate/UpdaterAggregator.java) averages Spark worker state.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..conf.serde import register

Array = jax.Array
State = Dict[str, Array]

_EPS_DEFAULT = 1e-8


def _f32_state(param, names):
    """Optimizer state is kept in float32 regardless of the param dtype
    (mixed-precision master state). In bf16, decay constants like 0.999
    round to 1.0 — Adam's bias correction would divide by zero — so all
    updater math below runs in f32 and only the delta is cast back."""
    return {n: jnp.zeros(param.shape, jnp.float32) for n in names}


@dataclass
class UpdaterConfig:
    """Base updater config. learning_rate < 0 means inherit the net-level lr."""

    def init_state(self, param: Array) -> State:
        return {}

    def apply(self, state: State, grad: Array, lr: Array, step: Array) -> Tuple[Array, State]:
        raise NotImplementedError


@register
@dataclass
class Sgd(UpdaterConfig):
    learning_rate: float = -1.0

    def apply(self, state, grad, lr, step):
        return -lr * grad, state


@register
@dataclass
class NoOp(UpdaterConfig):
    """Gradient applied raw (reference NoOpUpdater)."""

    def apply(self, state, grad, lr, step):
        return -grad, state


@register
@dataclass
class Nesterovs(UpdaterConfig):
    learning_rate: float = -1.0
    momentum: float = 0.9
    # iteration -> momentum overrides (reference momentumAfter schedule)
    momentum_schedule: Dict[str, float] = field(default_factory=dict)

    def init_state(self, param):
        return _f32_state(param, ("v",))

    def _momentum(self, step):
        mu = jnp.asarray(self.momentum, jnp.float32)
        for it, m in sorted((int(k), v) for k, v in self.momentum_schedule.items()):
            mu = jnp.where(step >= it, m, mu)
        return mu

    def apply(self, state, grad, lr, step):
        g = grad.astype(jnp.float32)
        mu = self._momentum(step)
        v = state["v"]
        v_new = mu * v - lr.astype(jnp.float32) * g
        # Nesterov look-ahead: params += -mu*v + (1+mu)*v_new
        delta = (1.0 + mu) * v_new - mu * v
        return delta.astype(grad.dtype), {"v": v_new}


@register
@dataclass
class Adam(UpdaterConfig):
    """Adam; with ``weight_decay > 0`` this is AdamW (decoupled decay,
    Loshchilov & Hutter): the decay is applied to the PARAMETER at the
    update site (nn/multilayer._apply_updaters), scaled by the effective
    lr and restricted to weight tensors — unlike `.l2(...)`, it never
    enters the adaptive moments. No reference counterpart (0.4-era)."""

    learning_rate: float = -1.0
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = _EPS_DEFAULT
    weight_decay: float = 0.0

    def init_state(self, param):
        return _f32_state(param, ("m", "u"))

    def apply(self, state, grad, lr, step):
        g = grad.astype(jnp.float32)
        t = jnp.asarray(step + 1, jnp.float32)
        b1 = jnp.float32(self.beta1)
        b2 = jnp.float32(self.beta2)
        m = b1 * state["m"] + (1.0 - b1) * g
        u = b2 * state["u"] + (1.0 - b2) * g * g
        mhat = m / (1.0 - jnp.power(b1, t))
        uhat = u / (1.0 - jnp.power(b2, t))
        delta = -lr.astype(jnp.float32) * mhat / (jnp.sqrt(uhat) + self.epsilon)
        return delta.astype(grad.dtype), {"m": m, "u": u}


@register
@dataclass
class AdaGrad(UpdaterConfig):
    learning_rate: float = -1.0
    epsilon: float = _EPS_DEFAULT

    def init_state(self, param):
        return _f32_state(param, ("h",))

    def apply(self, state, grad, lr, step):
        g = grad.astype(jnp.float32)
        h = state["h"] + g * g
        delta = -lr.astype(jnp.float32) * g / (jnp.sqrt(h) + self.epsilon)
        return delta.astype(grad.dtype), {"h": h}


@register
@dataclass
class AdaDelta(UpdaterConfig):
    rho: float = 0.95
    epsilon: float = 1e-6

    def init_state(self, param):
        return _f32_state(param, ("eg", "edx"))

    def apply(self, state, grad, lr, step):
        g = grad.astype(jnp.float32)
        rho = jnp.float32(self.rho)
        eg = rho * state["eg"] + (1.0 - rho) * g * g
        dx = -jnp.sqrt(state["edx"] + self.epsilon) / jnp.sqrt(eg + self.epsilon) * g
        edx = rho * state["edx"] + (1.0 - rho) * dx * dx
        return dx.astype(grad.dtype), {"eg": eg, "edx": edx}


@register
@dataclass
class RmsProp(UpdaterConfig):
    learning_rate: float = -1.0
    rms_decay: float = 0.95
    epsilon: float = _EPS_DEFAULT

    def init_state(self, param):
        return _f32_state(param, ("eg",))

    def apply(self, state, grad, lr, step):
        g = grad.astype(jnp.float32)
        d = jnp.float32(self.rms_decay)
        eg = d * state["eg"] + (1.0 - d) * g * g
        delta = -lr.astype(jnp.float32) * g / jnp.sqrt(eg + self.epsilon)
        return delta.astype(grad.dtype), {"eg": eg}


@register
@dataclass
class AdaMax(UpdaterConfig):
    learning_rate: float = -1.0
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = _EPS_DEFAULT

    def init_state(self, param):
        return _f32_state(param, ("m", "u"))

    def apply(self, state, grad, lr, step):
        g = grad.astype(jnp.float32)
        t = jnp.asarray(step + 1, jnp.float32)
        b1 = jnp.float32(self.beta1)
        m = b1 * state["m"] + (1.0 - b1) * g
        u = jnp.maximum(self.beta2 * state["u"], jnp.abs(g))
        delta = -lr.astype(jnp.float32) / (1.0 - jnp.power(b1, t)) * m / (u + self.epsilon)
        return delta.astype(grad.dtype), {"m": m, "u": u}


UPDATERS = {
    "sgd": Sgd,
    "noop": NoOp,
    "nesterovs": Nesterovs,
    "adam": Adam,
    "adagrad": AdaGrad,
    "adadelta": AdaDelta,
    "rmsprop": RmsProp,
    "adamax": AdaMax,
}


def resolve_updater(u) -> UpdaterConfig:
    """Accept an UpdaterConfig instance or a string name."""
    if isinstance(u, UpdaterConfig):
        return u
    if isinstance(u, str):
        try:
            return UPDATERS[u.lower()]()
        except KeyError:
            raise ValueError(f"Unknown updater '{u}'. Available: {sorted(UPDATERS)}") from None
    raise TypeError(f"Cannot resolve updater from {type(u)}")
