"""Updater (learning-rule) kernels + their serializable configs.

Capability parity with the reference updater zoo: `nn/updater/*`
(Sgd/Adam/AdaGrad/AdaDelta/RmsProp/Nesterovs/NoOp wrappers in
deeplearning4j-core/.../nn/updater/, kernels in ND4J
`org.nd4j.linalg.learning.GradientUpdater` — SURVEY.md §2.1). TPU-first
redesign: each updater is a pure (state, grad, lr, step) -> (delta, state)
function applied over the whole param pytree inside the single jit-compiled
train step, instead of the per-param-name Java object loop
(BaseUpdater.java:35). `delta` is ADDED to params.

State shapes mirror param shapes, so updater state averages across
data-parallel replicas exactly like the reference's UpdaterAggregator
(nn/updater/aggregate/UpdaterAggregator.java) averages Spark worker state.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..conf.serde import register

Array = jax.Array
State = Dict[str, Array]

_EPS_DEFAULT = 1e-8


@dataclass
class UpdaterConfig:
    """Base updater config. learning_rate < 0 means inherit the net-level lr."""

    def init_state(self, param: Array) -> State:
        return {}

    def apply(self, state: State, grad: Array, lr: Array, step: Array) -> Tuple[Array, State]:
        raise NotImplementedError


@register
@dataclass
class Sgd(UpdaterConfig):
    learning_rate: float = -1.0

    def apply(self, state, grad, lr, step):
        return -lr * grad, state


@register
@dataclass
class NoOp(UpdaterConfig):
    """Gradient applied raw (reference NoOpUpdater)."""

    def apply(self, state, grad, lr, step):
        return -grad, state


@register
@dataclass
class Nesterovs(UpdaterConfig):
    learning_rate: float = -1.0
    momentum: float = 0.9
    # iteration -> momentum overrides (reference momentumAfter schedule)
    momentum_schedule: Dict[str, float] = field(default_factory=dict)

    def init_state(self, param):
        return {"v": jnp.zeros_like(param)}

    def _momentum(self, step):
        mu = jnp.asarray(self.momentum, jnp.float32)
        for it, m in sorted((int(k), v) for k, v in self.momentum_schedule.items()):
            mu = jnp.where(step >= it, m, mu)
        return mu

    def apply(self, state, grad, lr, step):
        mu = self._momentum(step).astype(grad.dtype)
        v = state["v"]
        v_new = mu * v - lr * grad
        # Nesterov look-ahead: params += -mu*v + (1+mu)*v_new
        delta = (1.0 + mu) * v_new - mu * v
        return delta, {"v": v_new}


@register
@dataclass
class Adam(UpdaterConfig):
    learning_rate: float = -1.0
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = _EPS_DEFAULT

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "u": jnp.zeros_like(param)}

    def apply(self, state, grad, lr, step):
        t = jnp.asarray(step + 1, grad.dtype)
        b1 = jnp.asarray(self.beta1, grad.dtype)
        b2 = jnp.asarray(self.beta2, grad.dtype)
        m = b1 * state["m"] + (1.0 - b1) * grad
        u = b2 * state["u"] + (1.0 - b2) * grad * grad
        mhat = m / (1.0 - jnp.power(b1, t))
        uhat = u / (1.0 - jnp.power(b2, t))
        delta = -lr * mhat / (jnp.sqrt(uhat) + self.epsilon)
        return delta, {"m": m, "u": u}


@register
@dataclass
class AdaGrad(UpdaterConfig):
    learning_rate: float = -1.0
    epsilon: float = _EPS_DEFAULT

    def init_state(self, param):
        return {"h": jnp.zeros_like(param)}

    def apply(self, state, grad, lr, step):
        h = state["h"] + grad * grad
        delta = -lr * grad / (jnp.sqrt(h) + self.epsilon)
        return delta, {"h": h}


@register
@dataclass
class AdaDelta(UpdaterConfig):
    rho: float = 0.95
    epsilon: float = 1e-6

    def init_state(self, param):
        return {"eg": jnp.zeros_like(param), "edx": jnp.zeros_like(param)}

    def apply(self, state, grad, lr, step):
        rho = jnp.asarray(self.rho, grad.dtype)
        eg = rho * state["eg"] + (1.0 - rho) * grad * grad
        dx = -jnp.sqrt(state["edx"] + self.epsilon) / jnp.sqrt(eg + self.epsilon) * grad
        edx = rho * state["edx"] + (1.0 - rho) * dx * dx
        return dx, {"eg": eg, "edx": edx}


@register
@dataclass
class RmsProp(UpdaterConfig):
    learning_rate: float = -1.0
    rms_decay: float = 0.95
    epsilon: float = _EPS_DEFAULT

    def init_state(self, param):
        return {"eg": jnp.zeros_like(param)}

    def apply(self, state, grad, lr, step):
        d = jnp.asarray(self.rms_decay, grad.dtype)
        eg = d * state["eg"] + (1.0 - d) * grad * grad
        delta = -lr * grad / jnp.sqrt(eg + self.epsilon)
        return delta, {"eg": eg}


@register
@dataclass
class AdaMax(UpdaterConfig):
    learning_rate: float = -1.0
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = _EPS_DEFAULT

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "u": jnp.zeros_like(param)}

    def apply(self, state, grad, lr, step):
        t = jnp.asarray(step + 1, grad.dtype)
        b1 = jnp.asarray(self.beta1, grad.dtype)
        m = b1 * state["m"] + (1.0 - b1) * grad
        u = jnp.maximum(self.beta2 * state["u"], jnp.abs(grad))
        delta = -lr / (1.0 - jnp.power(b1, t)) * m / (u + self.epsilon)
        return delta, {"m": m, "u": u}


UPDATERS = {
    "sgd": Sgd,
    "noop": NoOp,
    "nesterovs": Nesterovs,
    "adam": Adam,
    "adagrad": AdaGrad,
    "adadelta": AdaDelta,
    "rmsprop": RmsProp,
    "adamax": AdaMax,
}


def resolve_updater(u) -> UpdaterConfig:
    """Accept an UpdaterConfig instance or a string name."""
    if isinstance(u, UpdaterConfig):
        return u
    if isinstance(u, str):
        try:
            return UPDATERS[u.lower()]()
        except KeyError:
            raise ValueError(f"Unknown updater '{u}'. Available: {sorted(UPDATERS)}") from None
    raise TypeError(f"Cannot resolve updater from {type(u)}")
