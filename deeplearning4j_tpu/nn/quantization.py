"""Post-training int8 quantization for inference (beyond reference).

The reference (0.4-era DL4J) has no quantization support anywhere; this
module is a beyond-reference capability shaped by the TPU hardware: the
v5e MXU executes s8xs8->s32 matmuls/convolutions at twice the bf16 peak
(394 TOPS vs 197 TFLOPS) and int8 weights halve HBM traffic. Measured
honestly on the zoo CNN, the wins that MATERIALIZE are ~4x weight bytes
(vs f32) and exactly-preserved accuracy; throughput sits at parity with
bf16 (interleaved A/B 0.74-1.04x — XLA's s8 conv lowering does not reach
its 2x peak there; bench row `alexnet_cifar10_int8` keeps the standing
A/B, win or lose).

Design (functional, jit-compiled once):

- ``fold_batchnorm``: inference-mode BatchNorm (global running stats) folded
  into the preceding identity-activation Convolution/Dense weights — exact
  in float arithmetic. The conv(identity)->BN(act) pattern is how every BN
  net in the zoo is built (models/zoo.py alexnet_cifar10).
- ``quantize(net, calib_batches)``: per-output-channel symmetric int8
  weights, per-tensor activation scales calibrated from data (max-abs over
  the calibration set), biases kept in f32. Each quantized layer runs
      x_q = clip(round(x / s_x))            (int8)
      acc = dot/conv(x_q, W_q) -> int32     (MXU s8 path)
      y   = acc * (s_x * s_w[out]) + b      (f32 epilogue)
  and the surrounding non-matmul layers (pool/LRN/activation/reshape
  preprocessors) run in float exactly as the source network defines them,
  via the same LayerImpl.forward SPI.

Layers with no quantized path (recurrent, attention, embedding, ...) fall
back to their float forward inside the same jitted program, so ``quantize``
accepts ANY MultiLayerNetwork and degrades gracefully to "fold + float".
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .layers.convolution import ConvolutionLayerImpl, _padding_config
from .layers.feedforward import DenseLayerImpl, OutputLayerImpl
from .layers.normalization import BatchNormalizationImpl
from .conf.preprocessors import (CnnToRnnPreProcessor,
                                 FeedForwardToRnnPreProcessor)
from .multilayer import _cast_floats, _compute_dtype_of

Array = jax.Array

_EPS = 1e-12


def _bn_scale_shift(bn_impl: BatchNormalizationImpl, params: Dict[str, Array],
                    variables: Dict[str, Array]) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel (scale, shift) of inference-mode BN:
    y = scale * x + shift with scale = gamma/sqrt(var+eps),
    shift = beta - mean*scale (nn/layers/normalization.py forward, global
    stats branch)."""
    conf = bn_impl.conf
    mean = np.asarray(variables["mean"], np.float64)
    var = np.asarray(variables["var"], np.float64)
    if conf.lock_gamma_beta:
        gamma = np.full_like(mean, float(conf.gamma))
        beta = np.full_like(mean, float(conf.beta))
    else:
        gamma = np.asarray(params["gamma"], np.float64)
        beta = np.asarray(params["beta"], np.float64)
    scale = gamma / np.sqrt(var + float(conf.eps))
    shift = beta - mean * scale
    return scale, shift


def fold_batchnorm(W: Array, b: Array, scale: np.ndarray,
                   shift: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Fold BN(conv(x)) = conv'(x): W' = W * scale[out], b' = b*scale + shift.
    Exact for identity-activation convs/denses (float associativity only)."""
    W = np.asarray(W, np.float64)
    b = np.asarray(b, np.float64)
    Wf = W * scale.reshape((1,) * (W.ndim - 1) + (-1,))
    bf = b * scale + shift
    return Wf, bf


def _weight_qparams(W: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-output-channel symmetric int8 quantization of W [..., out]."""
    maxabs = np.max(np.abs(W), axis=tuple(range(W.ndim - 1)))
    s = np.maximum(maxabs, _EPS) / 127.0
    Wq = np.clip(np.round(W / s), -127, 127).astype(np.int8)
    return Wq, s.astype(np.float32)


def _int8_forward(kind, Wq, w_scale, bias, x_scale, conv_args, activation,
                  act_dtype, x):
    """THE int8 inference kernel, shared by both facades: per-tensor input
    quantization, s8xs8->s32 dot/conv, f32 dequant epilogue, activation,
    cast to the net's activation dtype."""
    xq = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
    if kind == "dense":
        acc = lax.dot_general(xq, Wq, (((x.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    else:
        acc = lax.conv_general_dilated(
            xq, Wq,
            window_strides=conv_args["stride"],
            padding=conv_args["padding"],
            rhs_dilation=conv_args["dilation"],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (x_scale * w_scale) + bias
    return activation(y).astype(act_dtype)


class _QStep:
    """One plan step. kind: 'dense' | 'conv' | 'float'."""

    def __init__(self, kind: str, index: int, impl=None, consumed: int = 1,
                 activation=None, conv_args: Optional[dict] = None):
        self.kind = kind
        self.index = index          # first source-layer index this step covers
        self.impl = impl            # float-fallback impl (kind == 'float')
        self.consumed = consumed    # source layers consumed (2 when BN folded)
        self.activation = activation
        self.conv_args = conv_args or {}
        # filled by calibration/quantization:
        self.Wf: Optional[np.ndarray] = None   # folded float weights
        self.bf: Optional[np.ndarray] = None
        self.Wq: Optional[np.ndarray] = None
        self.w_scale: Optional[np.ndarray] = None
        self.x_scale: float = 0.0
        self.x_maxabs: float = 0.0


class QuantizedNetwork:
    """Inference-only int8 view of a trained MultiLayerNetwork.

    Build with :func:`quantize`. ``output``/``predict``/``evaluate`` mirror
    the source network's inference API.
    """

    def __init__(self, net, steps: List[_QStep], act_dtype=jnp.float32):
        self._net = net
        self._steps = steps
        self._act_dtype = act_dtype
        self._jitted = None
        self.conf = net.conf  # serving surface (/info) reads the config
        # device-resident consts: [(Wq, w_scale, bias, x_scale) per q-step]
        self._consts: Dict[int, Tuple[Array, Array, Array, Array]] = {}
        for si, st in enumerate(steps):
            if st.kind in ("dense", "conv"):
                self._consts[si] = (
                    jnp.asarray(st.Wq),
                    jnp.asarray(st.w_scale, jnp.float32),
                    jnp.asarray(st.bf, jnp.float32),
                    jnp.asarray(st.x_scale, jnp.float32),
                )

    def num_params(self) -> int:
        """Serving surface (/health, /info): logical parameter count of the
        underlying model — quantization changes bytes, not structure."""
        return self._net.num_params()

    # -- size accounting ---------------------------------------------------
    def param_bytes(self) -> int:
        total = 0
        for si, st in enumerate(self._steps):
            if si in self._consts:
                Wq, sw, b, _ = self._consts[si]
                total += Wq.size + sw.size * 4 + b.size * 4
            elif st.impl is not None:
                for p in jax.tree_util.tree_leaves(self._net.params[st.index]):
                    total += p.size * p.dtype.itemsize
        return total

    def float_param_bytes(self) -> int:
        return sum(p.size * p.dtype.itemsize
                   for p in jax.tree_util.tree_leaves(self._net.params))

    # -- forward -----------------------------------------------------------
    def _run(self, params, variables, x, fmask=None):
        def qstep(si, st, cur):
            Wq, sw, b, sx = self._consts[si]
            return _int8_forward(st.kind, Wq, sw, b, sx, st.conv_args,
                                 st.activation, self._act_dtype, cur)

        return _walk_plan(self._net, self._steps, params, variables, x,
                          self._act_dtype, qstep, fmask=fmask)

    def output(self, x, fmask=None) -> Array:
        if self._jitted is None:
            self._jitted = jax.jit(self._run)
        return self._jitted(self._net.params, self._net.variables,
                            jnp.asarray(x),
                            jnp.asarray(fmask) if fmask is not None else None)

    def predict(self, x) -> np.ndarray:
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def evaluate(self, iterator, top_n: int = 1):
        """Mirrors MultiLayerNetwork.evaluate's mask contract (ADVICE r5
        #1): features_mask rides the plan walk, labels_mask weights the
        eval — masked time-series evals match the float facade."""
        from ..evaluation.evaluation import Evaluation
        ev = Evaluation(top_n=top_n)
        for ds in iterator:
            out = self.output(ds.features,
                              fmask=getattr(ds, "features_mask", None))
            ev.eval(np.asarray(ds.labels), np.asarray(out),
                    mask=getattr(ds, "labels_mask", None))
        return ev


def _build_steps(net, fold_bn: bool) -> List[_QStep]:
    impls = net._impls
    steps: List[_QStep] = []
    i = 0
    while i < len(impls):
        impl = impls[i]
        params_i = net.params[i]
        kind = ("conv" if isinstance(impl, ConvolutionLayerImpl)
                else "dense" if type(impl) in (DenseLayerImpl, OutputLayerImpl)
                else None)
        if kind is None:
            steps.append(_QStep("float", i, impl=impl))
            i += 1
            continue
        conf = impl.conf
        act_name = conf.activation or "identity"
        consumed = 1
        Wf = np.asarray(params_i["W"], np.float64)
        bf = np.asarray(params_i["b"], np.float64)
        act_impl = impl
        # fold a directly-following inference-mode BN (conv/dense alike);
        # a preprocessor registered AT the BN's index would run between the
        # two layers, so folding across one would skip it — don't fold then
        if (fold_bn and act_name in ("identity", "linear")
                and i + 1 < len(impls)
                and isinstance(impls[i + 1], BatchNormalizationImpl)
                and net.conf.preprocessor(i + 1) is None):
            scale, shift = _bn_scale_shift(
                impls[i + 1], net.params[i + 1], net.variables[i + 1])
            Wf, bf = fold_batchnorm(Wf, bf, scale, shift)
            act_impl = impls[i + 1]
            consumed = 2
        conv_args = (dict(stride=conf.stride, padding=_padding_config(conf),
                          dilation=conf.dilation) if kind == "conv" else None)
        st = _QStep(kind, i, consumed=consumed,
                    activation=act_impl.activation_fn(), conv_args=conv_args)
        st.Wf, st.bf = Wf, bf
        steps.append(st)
        i += consumed
    return steps


def _walk_plan(net, steps, params, variables, x, act_dtype, qstep_fn,
               fmask=None):
    """THE plan walk, shared by calibration and quantized inference so the
    two can't drift: input adaptation, per-step preprocessor dispatch,
    timestep tracking, float-fallback layers via the LayerImpl SPI — with
    ``qstep_fn(si, step, cur)`` supplying the body of each quantized step.
    ``fmask`` follows MultiLayerNetwork._forward_impl's discipline: handed
    to every step whose input is 3D (time axis alive), dropped otherwise."""
    conf = net.conf
    cur = net._adapt_input(jnp.asarray(x))
    if jnp.issubdtype(cur.dtype, jnp.floating):
        cur = cur.astype(act_dtype)
    timesteps = cur.shape[1] if cur.ndim == 3 else 1
    for si, st in enumerate(steps):
        proc = conf.preprocessor(st.index)
        if proc is not None:
            if isinstance(proc, (FeedForwardToRnnPreProcessor,
                                 CnnToRnnPreProcessor)):
                cur = proc.preprocess_with_time(cur, timesteps)
            else:
                cur = proc.preprocess(cur)
        if cur.ndim == 3:
            timesteps = cur.shape[1]
        lmask_arg = fmask if cur.ndim == 3 else None
        if st.kind == "float":
            # mirror MultiLayerNetwork._forward_impl's compute-dtype
            # discipline: params cast to the activation dtype for the math,
            # output cast back — f32 master params must not creep the
            # activations of a bf16 net to f32 mid-plan
            p = params[st.index]
            if any(jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != act_dtype
                   for a in jax.tree_util.tree_leaves(p)):
                p = _cast_floats(p, act_dtype)
            cur, _ = st.impl.forward(p, cur, train=False,
                                     variables=variables[st.index],
                                     mask=lmask_arg)
            if jnp.issubdtype(cur.dtype, jnp.floating) and cur.dtype != act_dtype:
                cur = cur.astype(act_dtype)
        else:
            cur = qstep_fn(si, st, cur)
            if lmask_arg is not None and cur.ndim == 3:
                # the int8 kernel bypasses the impl's own mask application
                # (DenseLayerImpl.forward_with_preout): re-apply it here
                cur = cur * lmask_arg[..., None].astype(cur.dtype)
    return cur


def _calibrate(net, steps: List[_QStep], calib_batches: Sequence[Any]) -> None:
    """Run the float plan over the calibration set, recording per-quantized-
    step input max-abs (the per-tensor symmetric activation scale).
    Calibration walks in f32 regardless of the net's compute dtype — scale
    estimates want the extra precision; the ranges bf16 inference sees are
    within rounding of these."""

    def qstep(si, st, cur):
        st.x_maxabs = max(st.x_maxabs, float(jnp.max(jnp.abs(cur))))
        W = jnp.asarray(st.Wf, jnp.float32)
        b = jnp.asarray(st.bf, jnp.float32)
        if st.kind == "dense":
            return st.activation(cur @ W + b)
        return st.activation(lax.conv_general_dilated(
            cur, W,
            window_strides=st.conv_args["stride"],
            padding=st.conv_args["padding"],
            rhs_dilation=st.conv_args["dilation"],
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + b)

    for batch in calib_batches:
        x = getattr(batch, "features", batch)
        _walk_plan(net, steps, net.params, net.variables,
                   jnp.asarray(x, jnp.float32), jnp.float32, qstep)


class _QuantizedVertexImpl:
    """LayerImpl-shaped int8 shim for one ComputationGraph vertex.

    Slots into `graph._impls[name]` so the graph's own topo-ordered forward
    (`nn/graph.py _vertex_forward`) runs it like any layer — masks, vertex
    types, preprocessors and mixed-precision casts all behave identically.
    The quantized consts are closed over (they become jit constants), and
    the incoming `params` are ignored by the int8 math. The rest of the
    LayerImpl surface (conf, reg_loss, ...) delegates to the wrapped float
    impl so graph methods that iterate _impls (score's _reg_loss, serde)
    keep working; train-mode forward refuses — the quantized clone is
    inference-only (round() has zero gradient, training would silently
    learn nothing).
    """

    def __init__(self, float_impl, kind, Wq, w_scale, bias, x_scale,
                 conv_args, act_dtype):
        self._float_impl = float_impl
        self.conf = float_impl.conf
        self.WEIGHT_KEYS = float_impl.WEIGHT_KEYS
        self.kind = kind
        self.Wq = jnp.asarray(Wq)
        self.w_scale = jnp.asarray(w_scale, jnp.float32)
        self.bias = jnp.asarray(bias, jnp.float32)
        self.x_scale = jnp.asarray(x_scale, jnp.float32)
        self.activation = float_impl.activation_fn()
        self.conv_args = conv_args or {}
        self.act_dtype = act_dtype

    def has_params(self):
        return self._float_impl.has_params()

    def reg_loss(self, params):
        return self._float_impl.reg_loss(params)

    def activation_fn(self):
        return self.activation

    def forward(self, params, x, *, train=False, rng=None, variables=None,
                mask=None):
        if train:
            raise RuntimeError(
                "quantize_graph() produces an inference-only network; "
                "train on the float ComputationGraph and re-quantize")
        y = _int8_forward(self.kind, self.Wq, self.w_scale, self.bias,
                          self.x_scale, self.conv_args, self.activation,
                          self.act_dtype, x)
        return y, variables or {}


def quantize_graph(net, calib_batches: Sequence[Any], *, act_dtype=None):
    """Post-training int8 quantization of a trained ComputationGraph.

    Dense and Convolution layer VERTICES are quantized (per-output-channel
    int8 weights, calibrated per-tensor activation scales) — including
    Dense-type output heads, whose matmul goes int8 while the softmax
    epilogue stays f32. Every other vertex — attention, LayerNorm,
    BatchNorm, elementwise/merge/subset, recurrent, RnnOutput heads — runs
    its float forward unchanged inside the same jitted program. On the zoo
    transformer that covers the embed and FFN projections, i.e. most
    non-attention parameters. No BN folding here (a graph BN is a
    free-standing vertex; folding would need single-producer/single-
    consumer edge analysis for little gain).

    Returns an inference-only ComputationGraph clone: output /
    output_single / feed_forward / evaluate / score run the quantized
    program; calling a training entry point raises. ``calib_batches``:
    iterable of (Multi)DataSets or raw input arrays (single-input graphs).
    """
    net._check_init()
    if act_dtype is None:
        act_dtype = _compute_dtype_of(net.conf.conf)
    conf = net.conf
    targets = _graph_quant_targets(net)
    calib = list(calib_batches)
    if not calib:
        raise ValueError("quantize_graph() needs at least one calibration batch")

    # calibrate: float forward per batch; a target vertex's input is its
    # (single) source's activation run through the vertex preprocessor —
    # exactly what _vertex_forward hands the impl
    maxabs = {name: 0.0 for name in targets}
    for batch in calib:
        if hasattr(batch, "features_list"):
            inputs = batch.features_list
        elif hasattr(batch, "features"):
            inputs = [batch.features]
        else:
            inputs = [batch]
        acts = net.feed_forward(*[jnp.asarray(a, jnp.float32) for a in inputs],
                                train=False)
        for name in targets:
            src = conf.vertex_inputs[name][0]
            x = acts[src]
            proc = getattr(conf.vertices[name], "preprocessor", None)
            if proc is not None:
                x = proc.preprocess(x)
            maxabs[name] = max(maxabs[name], float(jnp.max(jnp.abs(x))))

    x_scales = {name: max(maxabs[name], _EPS) / 127.0 for name in targets}
    return _build_graph_clone(net, x_scales, act_dtype)


def _graph_quant_targets(net) -> Dict[str, str]:
    """vertex name -> 'conv' | 'dense' for every quantizable vertex —
    the single target-selection rule shared by `quantize_graph` and the
    artifact loader (so a persisted scale set can be validated against
    exactly what a fresh quantization would cover)."""
    targets: Dict[str, str] = {}
    for name, impl in net._impls.items():
        if isinstance(impl, ConvolutionLayerImpl):
            targets[name] = "conv"
        elif type(impl) in (DenseLayerImpl, OutputLayerImpl):
            targets[name] = "dense"
    return targets


def _build_graph_clone(net, x_scales: Dict[str, float], act_dtype):
    """Assemble the inference-only quantized ComputationGraph clone from
    a float graph plus per-vertex activation scales (freshly calibrated
    or reloaded from a `save_quantized_graph` artifact — weight
    quantization is deterministic from the float params either way)."""
    targets = _graph_quant_targets(net)
    qimpls = {}
    for name, sx in x_scales.items():
        kind = targets[name]
        p = net.params[name]
        Wq, w_scale = _weight_qparams(np.asarray(p["W"], np.float64))
        lconf = net._impls[name].conf
        conv_args = (dict(stride=lconf.stride, padding=_padding_config(lconf),
                          dilation=lconf.dilation) if kind == "conv" else None)
        qimpls[name] = _QuantizedVertexImpl(
            net._impls[name], kind, Wq, w_scale,
            np.asarray(p["b"], np.float32), float(sx), conv_args,
            act_dtype)

    clone = object.__new__(type(net))
    clone.__dict__.update(net.__dict__)
    clone._impls = {**net._impls, **qimpls}
    clone._jit_cache = {}
    clone._rnn_state = {}  # own decode state — never share the source's
    clone._quantized_vertices = sorted(qimpls)
    clone._quant_act_dtype = act_dtype
    return clone


QUANT_JSON = "quantization.json"

# activation dtypes a persisted artifact can name (one source of truth for
# save validation and load resolution)
_ACT_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
               "float64": jnp.float64}


def _finalize_steps(steps: List[_QStep]) -> None:
    for st in steps:
        if st.kind in ("dense", "conv"):
            st.Wq, st.w_scale = _weight_qparams(st.Wf)
            st.x_scale = max(st.x_maxabs, _EPS) / 127.0


def save_quantized(qnet: QuantizedNetwork, path) -> None:
    """Persist a quantized net: the float model checkpoint (ModelSerializer
    zip — config + params + updater + variables) plus `quantization.json`
    holding the calibration products (per-step activation scales, fold
    flag, activation dtype). Weight quantization is deterministic from the
    float params, so the scales are the only extra state; the artifact
    stays a valid float checkpoint that `restore_multi_layer_network` can
    also open."""
    import zipfile
    from ..util.model_serializer import write_model
    dtype_name = np.dtype(qnet._act_dtype).name
    if dtype_name not in _ACT_DTYPES:
        raise ValueError(
            f"act_dtype '{dtype_name}' cannot be persisted (supported: "
            f"{sorted(_ACT_DTYPES)}) — refusing to write an unloadable "
            "artifact")
    write_model(qnet._net, path)
    meta = {
        "facade": "multilayer",
        "fold_bn": any(s.consumed == 2 for s in qnet._steps),
        "act_dtype": dtype_name,
        "x_scales": {str(si): float(st.x_scale)
                     for si, st in enumerate(qnet._steps)
                     if st.kind in ("dense", "conv")},
    }
    with zipfile.ZipFile(path, "a", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(QUANT_JSON, json.dumps(meta))


def save_quantized_graph(qgraph, path) -> None:
    """Persist a `quantize_graph` clone: the float graph checkpoint
    (the clone's conf/params/variables ARE the float ones) plus
    `quantization.json` with the per-vertex activation scales. Weight
    quantization rebuilds deterministically from the float params at
    load time, so the artifact doubles as a valid float checkpoint —
    `dl4j-tpu serve --int8 --generate` loads it through
    :func:`load_quantized` and hands the int8 clone straight to the
    decode scheduler (the attention KV path stays float; only the
    dense matmuls run s8xs8->s32)."""
    import zipfile
    from ..util.model_serializer import write_model
    names = getattr(qgraph, "_quantized_vertices", None)
    if not names:
        raise ValueError("save_quantized_graph() wants a quantize_graph() "
                         "clone (no quantized vertices found)")
    act_dtype = getattr(qgraph, "_quant_act_dtype", jnp.float32)
    dtype_name = np.dtype(act_dtype).name
    if dtype_name not in _ACT_DTYPES:
        raise ValueError(
            f"act_dtype '{dtype_name}' cannot be persisted (supported: "
            f"{sorted(_ACT_DTYPES)}) — refusing to write an unloadable "
            "artifact")
    write_model(qgraph, path)
    meta = {
        "facade": "graph",
        "act_dtype": dtype_name,
        "x_scales": {name: float(qgraph._impls[name].x_scale)
                     for name in names},
    }
    with zipfile.ZipFile(path, "a", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(QUANT_JSON, json.dumps(meta))


def _load_quantized_graph(path, meta):
    from ..util.model_serializer import restore_computation_graph
    net = restore_computation_graph(path)
    act_dtype = _ACT_DTYPES.get(meta["act_dtype"])
    if act_dtype is None:
        raise ValueError(f"unsupported act_dtype '{meta['act_dtype']}'")
    x_scales = {str(k): float(v) for k, v in meta["x_scales"].items()}
    want = set(_graph_quant_targets(net))
    if set(x_scales) != want:
        raise ValueError("quantization plan mismatch: saved scales cover "
                         f"vertices {sorted(x_scales)} but the restored "
                         f"graph quantizes {sorted(want)}")
    return _build_graph_clone(net, x_scales, act_dtype)


def load_quantized(path):
    """Reload a quantized artifact — `save_quantized` (MultiLayerNetwork
    facade, returns a :class:`QuantizedNetwork`) or
    `save_quantized_graph` (ComputationGraph facade, returns the
    inference-only int8 graph clone): restore the float net, rebuild
    the quantization plan deterministically, and install the persisted
    activation scales (no recalibration data needed at load time)."""
    import zipfile
    from ..util.model_serializer import restore_multi_layer_network
    with zipfile.ZipFile(path) as zf:
        meta = json.loads(zf.read(QUANT_JSON).decode())
    if meta.get("facade") == "graph":
        return _load_quantized_graph(path, meta)
    if meta.get("facade") != "multilayer":
        raise ValueError(f"not a multilayer quantized artifact: {meta}")
    net = restore_multi_layer_network(path)
    act_dtype = _ACT_DTYPES.get(meta["act_dtype"])
    if act_dtype is None:
        raise ValueError(f"unsupported act_dtype '{meta['act_dtype']}'")
    steps = _build_steps(net, bool(meta["fold_bn"]))
    scales = meta["x_scales"]
    want = {si for si, st in enumerate(steps) if st.kind in ("dense", "conv")}
    if set(map(int, scales)) != want:
        raise ValueError("quantization plan mismatch: saved scales cover "
                         f"steps {sorted(scales)} but the restored net "
                         f"quantizes steps {sorted(want)}")
    _finalize_steps(steps)
    for si, st in enumerate(steps):
        if st.kind in ("dense", "conv"):
            # install the saved scale VERBATIM (a *127/127 round trip is
            # not bitwise-exact in double)
            st.x_scale = float(scales[str(si)])
    return QuantizedNetwork(net, steps, act_dtype=act_dtype)


def quantize(net, calib_batches: Sequence[Any], *, fold_bn: bool = True,
             act_dtype=None) -> QuantizedNetwork:
    """Post-training int8 quantization of a trained MultiLayerNetwork.

    ``calib_batches``: an iterable of DataSets (or raw feature arrays) run
    once in float to calibrate per-tensor activation scales. A handful of
    representative batches suffices (scales are max-abs).

    ``act_dtype``: dtype activations travel in between quantized layers
    (default: the net's compute dtype — bf16 nets stay bf16).
    """
    net._check_init()
    if act_dtype is None:
        act_dtype = _compute_dtype_of(net.conf.conf)
    steps = _build_steps(net, fold_bn)
    calib = list(calib_batches)
    if not calib:
        raise ValueError("quantize() needs at least one calibration batch")
    _calibrate(net, steps, calib)
    _finalize_steps(steps)
    return QuantizedNetwork(net, steps, act_dtype=act_dtype)
