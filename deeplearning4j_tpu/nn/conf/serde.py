"""Polymorphic config serialization: configs are *data*.

Capability parity with the reference's Jackson JSON/YAML round-trip
(NeuralNetConfiguration.java:250-270 `toJson`/`fromJson`, `:219-237` YAML) —
the property that makes configs shippable to workers and storable in
checkpoints (SURVEY.md §5 'Config / flag system').

Any registered dataclass serializes to a dict with an ``@class`` discriminator,
recursively. JSON and YAML entry points provided.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Type

_REGISTRY: Dict[str, Type] = {}


def register(cls):
    """Class decorator: make a dataclass JSON/YAML round-trippable."""
    _REGISTRY[cls.__name__] = cls
    return cls


def registry() -> Dict[str, Type]:
    return dict(_REGISTRY)


def to_dict(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        d: Dict[str, Any] = {"@class": type(obj).__name__}
        for f in dataclasses.fields(obj):
            d[f.name] = to_dict(getattr(obj, f.name))
        return d
    if isinstance(obj, tuple):
        return [to_dict(o) for o in obj]
    if isinstance(obj, list):
        return [to_dict(o) for o in obj]
    if isinstance(obj, dict):
        return {str(k): to_dict(v) for k, v in obj.items()}
    return obj


def from_dict(d: Any) -> Any:
    if isinstance(d, dict) and "@class" in d:
        name = d["@class"]
        if name not in _REGISTRY:
            raise ValueError(f"Unknown config class '{name}' (not registered)")
        cls = _REGISTRY[name]
        field_names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: from_dict(v) for k, v in d.items() if k != "@class" and k in field_names}
        return cls(**kwargs)
    if isinstance(d, list):
        return [from_dict(x) for x in d]
    if isinstance(d, dict):
        return {k: from_dict(v) for k, v in d.items()}
    return d


def to_json(obj: Any, indent: int = 2) -> str:
    return json.dumps(to_dict(obj), indent=indent, sort_keys=True)


def from_json(s: str) -> Any:
    return from_dict(json.loads(s))


def to_yaml(obj: Any) -> str:
    import yaml

    return yaml.safe_dump(to_dict(obj), sort_keys=True)


def from_yaml(s: str) -> Any:
    import yaml

    return from_dict(yaml.safe_load(s))
