"""Input type descriptors for automatic shape inference.

Parity with the reference `nn/conf/inputs/InputType` (feedForward / recurrent /
convolutional / convolutionalFlat) consumed by the ConvolutionLayerSetup-style
auto-configuration (reference nn/conf/layers/setup/ConvolutionLayerSetup.java:37).

TPU-first layout conventions (differ deliberately from the reference):
  - feedforward:    [batch, size]
  - recurrent:      [batch, time, size]      (reference uses [batch, size, time])
  - convolutional:  [batch, height, width, channels]  NHWC (reference is NCHW)
NHWC + trailing feature dim keeps the innermost (lane) dimension a multiple of
the TPU's 128-wide vector lanes for typical channel counts and lets XLA tile
matmuls/convs onto the MXU without transposes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from .serde import register


@dataclass
class InputType:
    kind: str = "feedforward"

    @staticmethod
    def feed_forward(size: int) -> "FeedForwardInputType":
        return FeedForwardInputType(size=size)

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "RecurrentInputType":
        return RecurrentInputType(size=size, timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "ConvolutionalInputType":
        return ConvolutionalInputType(height=height, width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "ConvolutionalFlatInputType":
        return ConvolutionalFlatInputType(height=height, width=width, channels=channels)


@register
@dataclass
class FeedForwardInputType(InputType):
    kind: str = "feedforward"
    size: int = 0

    def flat_size(self) -> int:
        return self.size


@register
@dataclass
class RecurrentInputType(InputType):
    kind: str = "recurrent"
    size: int = 0
    timesteps: Optional[int] = None

    def flat_size(self) -> int:
        return self.size


@register
@dataclass
class ConvolutionalInputType(InputType):
    kind: str = "convolutional"
    height: int = 0
    width: int = 0
    channels: int = 0

    def flat_size(self) -> int:
        return self.height * self.width * self.channels

    def hwc(self) -> Tuple[int, int, int]:
        return (self.height, self.width, self.channels)


@register
@dataclass
class ConvolutionalFlatInputType(InputType):
    """Flattened image input (e.g. raw MNIST rows of 784)."""

    kind: str = "convolutional_flat"
    height: int = 0
    width: int = 0
    channels: int = 1

    def flat_size(self) -> int:
        return self.height * self.width * self.channels
