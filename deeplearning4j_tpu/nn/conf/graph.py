"""ComputationGraph configuration: DAG of layers + graph vertices.

Parity with the reference ComputationGraphConfiguration (:56) + GraphBuilder
(:446) (deeplearning4j-core/.../nn/conf/ComputationGraphConfiguration.java)
and the vertex taxonomy under nn/conf/graph/* : LayerVertex, MergeVertex,
ElementWiseVertex, SubsetVertex, PreprocessorVertex, rnn/LastTimeStepVertex,
rnn/DuplicateToTimeSeriesVertex.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import serde
from .config import (NeuralNetConfiguration, resolve_layer_defaults,
                     BACKPROP_STANDARD)
from .inputs import InputType
from .layers import Layer
from .preprocessors import InputPreProcessor


@dataclass
class GraphVertex:
    """Base vertex config."""


@serde.register
@dataclass
class LayerVertex(GraphVertex):
    layer: Optional[Layer] = None
    preprocessor: Optional[InputPreProcessor] = None


@serde.register
@dataclass
class MergeVertex(GraphVertex):
    """Concatenate inputs along the feature (last) axis (reference MergeVertex)."""


@serde.register
@dataclass
class ElementWiseVertex(GraphVertex):
    """add | subtract | product | average | max (reference ElementWiseVertex)."""

    op: str = "add"


@serde.register
@dataclass
class SubsetVertex(GraphVertex):
    """Feature range [from_idx, to_idx] inclusive (reference SubsetVertex)."""

    from_idx: int = 0
    to_idx: int = 0


@serde.register
@dataclass
class PreprocessorVertex(GraphVertex):
    preprocessor: Optional[InputPreProcessor] = None


@serde.register
@dataclass
class ScaleVertex(GraphVertex):
    scale_factor: float = 1.0


@serde.register
@dataclass
class LastTimeStepVertex(GraphVertex):
    """[B,T,F] -> [B,F] at the last (or last unmasked) step
    (reference rnn/LastTimeStepVertex); mask_input names the graph input
    whose feature mask locates the last valid step."""

    mask_input: Optional[str] = None


@serde.register
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B,F] -> [B,T,F], T taken from a reference graph input
    (reference rnn/DuplicateToTimeSeriesVertex)."""

    reference_input: Optional[str] = None


@serde.register
@dataclass
class ComputationGraphConfiguration:
    conf: NeuralNetConfiguration = field(default_factory=NeuralNetConfiguration)
    network_inputs: List[str] = field(default_factory=list)
    network_outputs: List[str] = field(default_factory=list)
    vertices: Dict[str, GraphVertex] = field(default_factory=dict)
    vertex_inputs: Dict[str, List[str]] = field(default_factory=dict)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = BACKPROP_STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    input_types: Dict[str, InputType] = field(default_factory=dict)

    def to_json(self) -> str:
        return serde.to_json(self)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return serde.from_json(s)

    def to_yaml(self) -> str:
        return serde.to_yaml(self)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        return serde.from_yaml(s)

    def topological_order(self) -> List[str]:
        """Kahn topological sort over vertices (reference
        ComputationGraph.topologicalSortOrder():716)."""
        indeg = {name: 0 for name in self.vertices}
        children: Dict[str, List[str]] = {name: [] for name in self.vertices}
        for name, inputs in self.vertex_inputs.items():
            for src in inputs:
                if src in self.vertices:
                    indeg[name] += 1
                    children[src].append(name)
        queue = sorted(n for n, d in indeg.items() if d == 0)
        order = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for c in children[n]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    queue.append(c)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"Graph has a cycle involving: {sorted(cyc)}")
        return order


class GraphBuilder:
    """Fluent builder (reference ComputationGraphConfiguration.GraphBuilder:446)."""

    def __init__(self, conf: NeuralNetConfiguration):
        self._conf = conf
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, GraphVertex] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._backprop = True
        self._pretrain = False
        self._backprop_type = BACKPROP_STANDARD
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._input_types: Dict[str, InputType] = {}

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_input_types(self, **types: InputType) -> "GraphBuilder":
        self._input_types.update(types)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str,
                  preprocessor: Optional[InputPreProcessor] = None) -> "GraphBuilder":
        layer = resolve_layer_defaults(layer, self._conf)
        return self.add_vertex(name, LayerVertex(layer=layer, preprocessor=preprocessor),
                               *inputs)

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"Duplicate vertex name '{name}'")
        if not inputs:
            raise ValueError(f"Vertex '{name}' needs at least one input")
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def backprop(self, flag: bool) -> "GraphBuilder":
        self._backprop = flag
        return self

    def pretrain(self, flag: bool) -> "GraphBuilder":
        self._pretrain = flag
        return self

    def backprop_type(self, t: str) -> "GraphBuilder":
        self._backprop_type = t
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_fwd = n
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._tbptt_back = n
        return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs:
            raise ValueError("Graph needs at least one input (add_inputs)")
        if not self._outputs:
            raise ValueError("Graph needs at least one output (set_outputs)")
        known = set(self._inputs) | set(self._vertices)
        for name, inputs in self._vertex_inputs.items():
            for src in inputs:
                if src not in known:
                    raise ValueError(f"Vertex '{name}' references unknown input '{src}'")
        for out in self._outputs:
            if out not in self._vertices:
                raise ValueError(f"Output '{out}' is not a vertex")
        cfg = ComputationGraphConfiguration(
            conf=self._conf,
            network_inputs=list(self._inputs),
            network_outputs=list(self._outputs),
            vertices=copy.deepcopy(self._vertices),
            vertex_inputs=copy.deepcopy(self._vertex_inputs),
            backprop=self._backprop,
            pretrain=self._pretrain,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            input_types=dict(self._input_types),
        )
        cfg.topological_order()  # validate acyclicity at build time
        return cfg
